// Native pose decoder: limb-connection scoring + greedy person assembly.
//
// C++ twin of improved_body_parts_tpu/infer/decode.py (find_connections +
// find_people), which itself re-implements the reference's pure-Python
// post-processing (reference: evaluate.py:206-498 — the 5.2 FPS bottleneck,
// README.md:68).  Semantics, including tie-breaking order, match the NumPy
// path bit-for-bit up to float summation order; a parity test pins the two
// paths against each other (tests/test_native_decoder.py).
//
// Exposed as a C ABI for ctypes (no pybind11 dependency):
//   int decode_people(...)    -> number of people written, or -1 on error.
//   int assemble_people(...)  -> assembly only, from pre-selected
//       connections — the host stage of the compact inference path, where
//       pair scoring already ran on the device (ops/peaks.py).
//
// Build: make -C native   (or python tools/build_native.py)

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

namespace {

// Round-half-to-even, matching np.round on the NumPy twin (decode.py:85-118).
// std::lrint honours the FP environment's rounding mode, which defaults to
// FE_TONEAREST (ties-to-even) — half-integer sample coords pick the same
// pixel as the Python path.
inline long round_even(double v) { return std::lrint(v); }

struct Connection {
  double id_a, id_b;   // global peak ids
  double score;        // distance-prior score
  int i, j;            // indices into candA / candB
  double length;       // euclidean limb length
};

struct Candidate {
  int i, j;
  double prior;
  double norm;
  double rank;
};

// Greedy per-limb connection selection (evaluate.py:206-276).
std::vector<Connection> find_connections_for_limb(
    const double* peaks, const int* part_offset, int part_a, int part_b,
    const float* paf, int H, int W, int C, int limb_channel, int image_size,
    double thre2, double connect_ration, int mid_num) {
  std::vector<Connection> out;
  const int na = part_offset[part_a + 1] - part_offset[part_a];
  const int nb = part_offset[part_b + 1] - part_offset[part_b];
  if (na == 0 || nb == 0) return out;
  const double* cand_a = peaks + 4 * part_offset[part_a];
  const double* cand_b = peaks + 4 * part_offset[part_b];

  std::vector<Candidate> cands;
  cands.reserve(static_cast<size_t>(na) * nb);
  for (int i = 0; i < na; ++i) {
    const double ax = cand_a[4 * i], ay = cand_a[4 * i + 1];
    for (int j = 0; j < nb; ++j) {
      const double bx = cand_b[4 * j], by = cand_b[4 * j + 1];
      const double dx = bx - ax, dy = by - ay;
      const double norm = std::sqrt(dx * dx + dy * dy);
      if (norm == 0.0) continue;  // overlapping parts (evaluate.py:228)
      int m = static_cast<int>(round_even(norm + 1.0));
      if (m > mid_num) m = mid_num;
      if (m < 1) m = 1;
      // sample linspace(A, B, m) inclusive on the limb channel
      double sum = 0.0;
      int above = 0;
      for (int s = 0; s < m; ++s) {
        const double t = (m == 1) ? 0.0 : static_cast<double>(s) / (m - 1);
        int x = static_cast<int>(round_even(ax + t * dx));
        int y = static_cast<int>(round_even(ay + t * dy));
        x = std::min(std::max(x, 0), W - 1);
        y = std::min(std::max(y, 0), H - 1);
        const double v = paf[(static_cast<size_t>(y) * W + x) * C + limb_channel];
        sum += v;
        if (v > thre2) ++above;
      }
      const double mean = sum / m;
      const double prior =
          mean + std::min(0.5 * image_size / norm - 1.0, 0.0);
      if (above >= connect_ration * m && prior > 0.0) {
        const double rank =
            0.5 * prior + 0.25 * cand_a[4 * i + 2] + 0.25 * cand_b[4 * j + 2];
        cands.push_back({i, j, prior, norm, rank});
      }
    }
  }
  std::stable_sort(cands.begin(), cands.end(),
                   [](const Candidate& a, const Candidate& b) {
                     return a.rank > b.rank;
                   });
  std::vector<char> used_a(na, 0), used_b(nb, 0);
  const size_t limit = static_cast<size_t>(std::min(na, nb));
  for (const auto& c : cands) {
    if (used_a[c.i] || used_b[c.j]) continue;
    used_a[c.i] = used_b[c.j] = 1;
    out.push_back({cand_a[4 * c.i + 3], cand_b[4 * c.j + 3], c.prior, c.i,
                   c.j, c.norm});
    if (out.size() >= limit) break;
  }
  return out;
}

// Greedy person assembly over per-limb connection lists
// (evaluate.py:279-498); `get_conns(k)` yields limb k's selected
// connections.  Shared by decode_people (host-scored connections) and
// assemble_people (device-scored connections, the compact path).
template <typename ConnsForLimb>
int assemble_subsets(const double* peaks, int num_parts, const int* limbs,
                     int n_limbs, double len_rate, double connection_tole,
                     bool remove_recon, double min_parts,
                     double min_mean_score, ConnsForLimb get_conns,
                     double* out_subsets, int max_people) {
  const int rows = num_parts + 2;
  // subset rows: [part 0..num_parts-1][0]=peak id, [1]=confidence;
  // row -2 = total score; row -1 = (count, longest limb)
  std::vector<std::vector<double>> subset;  // each row: 2*rows doubles

  auto new_row = [&]() {
    return std::vector<double>(2 * rows, -1.0);
  };

  for (int k = 0; k < n_limbs; ++k) {
    const int index_a = limbs[2 * k];
    const int index_b = limbs[2 * k + 1];
    const std::vector<Connection> conns = get_conns(k);

    for (const auto& conn : conns) {
      const double score = conn.score;
      const double limb_len = conn.length;
      int found_idx[2] = {-1, -1};
      int found = 0;
      for (size_t j = 0; j < subset.size(); ++j) {
        const bool hit =
            static_cast<long>(subset[j][2 * index_a]) ==
                static_cast<long>(conn.id_a) ||
            static_cast<long>(subset[j][2 * index_b]) ==
                static_cast<long>(conn.id_b);
        if (hit && found < 2) found_idx[found++] = static_cast<int>(j);
      }

      if (found == 1) {
        auto& s = subset[found_idx[0]];
        const long slot_b = static_cast<long>(s[2 * index_b]);
        if (slot_b == -1 && len_rate * s[2 * (rows - 1) + 1] > limb_len) {
          // empty slot: assign part B (evaluate.py:320-344)
          s[2 * index_b] = conn.id_b;
          s[2 * index_b + 1] = score;
          s[2 * (rows - 1)] += 1.0;
          s[2 * (rows - 2)] +=
              peaks[4 * static_cast<long>(conn.id_b) + 2] + score;
          s[2 * (rows - 1) + 1] = std::max(limb_len, s[2 * (rows - 1) + 1]);
        } else if (slot_b != static_cast<long>(conn.id_b)) {
          if (s[2 * index_b + 1] >= score) {
            // keep the more confident existing connection
          } else if (len_rate * s[2 * (rows - 1) + 1] <= limb_len) {
            // new limb absurdly long: skip
          } else {
            // replace the weaker part B (evaluate.py:346-363)
            s[2 * (rows - 2)] -=
                peaks[4 * slot_b + 2] + s[2 * index_b + 1];
            s[2 * index_b] = conn.id_b;
            s[2 * index_b + 1] = score;
            s[2 * (rows - 2)] +=
                peaks[4 * static_cast<long>(conn.id_b) + 2] + score;
            s[2 * (rows - 1) + 1] = std::max(limb_len, s[2 * (rows - 1) + 1]);
          }
        } else if (slot_b == static_cast<long>(conn.id_b) &&
                   s[2 * index_b + 1] <= score) {
          // same part, higher confidence: rescore (evaluate.py:368-380)
          s[2 * (rows - 2)] -= peaks[4 * slot_b + 2] + s[2 * index_b + 1];
          s[2 * index_b] = conn.id_b;
          s[2 * index_b + 1] = score;
          s[2 * (rows - 2)] +=
              peaks[4 * static_cast<long>(conn.id_b) + 2] + score;
          s[2 * (rows - 1) + 1] = std::max(limb_len, s[2 * (rows - 1) + 1]);
        }
      } else if (found == 2) {
        const int j1 = found_idx[0], j2 = found_idx[1];
        auto& s1 = subset[j1];
        auto& s2 = subset[j2];
        bool overlap = false;
        for (int p = 0; p < num_parts; ++p)
          if (s1[2 * p] >= 0 && s2[2 * p] >= 0) overlap = true;
        if (!overlap) {
          // disjoint people sharing the limb: merge (evaluate.py:403-424)
          double min1 = 1e30, min2 = 1e30;
          for (int p = 0; p < num_parts; ++p) {
            if (s1[2 * p] >= 0) min1 = std::min(min1, s1[2 * p + 1]);
            if (s2[2 * p] >= 0) min2 = std::min(min2, s2[2 * p + 1]);
          }
          const double min_tol = std::min(min1, min2);
          if (score < connection_tole * min_tol ||
              len_rate * s1[2 * (rows - 1) + 1] <= limb_len)
            continue;
          for (int p = 0; p < num_parts; ++p) {
            s1[2 * p] += s2[2 * p] + 1.0;
            s1[2 * p + 1] += s2[2 * p + 1] + 1.0;
          }
          s1[2 * (rows - 2)] += s2[2 * (rows - 2)];
          s1[2 * (rows - 1)] += s2[2 * (rows - 1)];
          s1[2 * (rows - 2)] += score;
          s1[2 * (rows - 1) + 1] = std::max(limb_len, s1[2 * (rows - 1) + 1]);
          subset.erase(subset.begin() + j2);
        } else {
          // two people compete for this limb (evaluate.py:426-460)
          int c1 = -1, c2 = -1;
          bool a_in_j1 = false;
          for (int p = 0; p < num_parts; ++p)
            if (static_cast<long>(s1[2 * p]) == static_cast<long>(conn.id_a))
              a_in_j1 = true;
          const double want1 = a_in_j1 ? conn.id_a : conn.id_b;
          const double want2 = a_in_j1 ? conn.id_b : conn.id_a;
          for (int p = 0; p < num_parts; ++p) {
            if (c1 < 0 && static_cast<long>(s1[2 * p]) ==
                              static_cast<long>(want1))
              c1 = p;
            if (c2 < 0 && static_cast<long>(s2[2 * p]) ==
                              static_cast<long>(want2))
              c2 = p;
          }
          if (c1 < 0 || c2 < 0 || c1 == c2) return -2;
          if (score < s1[2 * c1 + 1] && score < s2[2 * c2 + 1]) continue;
          int small_j = j1, remove_c = c1;
          if (s1[2 * c1 + 1] > s2[2 * c2 + 1]) {
            small_j = j2;
            remove_c = c2;
          }
          if (remove_recon) {
            auto& sm = subset[small_j];
            sm[2 * (rows - 2)] -=
                peaks[4 * static_cast<long>(sm[2 * remove_c]) + 2] +
                sm[2 * remove_c + 1];
            sm[2 * remove_c] = -1.0;
            sm[2 * remove_c + 1] = -1.0;
            sm[2 * (rows - 1)] -= 1.0;
          }
        }
      } else {
        // no owner: create a new person (evaluate.py:473-488)
        auto row = new_row();
        row[2 * index_a] = conn.id_a;
        row[2 * index_a + 1] = score;
        row[2 * index_b] = conn.id_b;
        row[2 * index_b + 1] = score;
        row[2 * (rows - 1)] = 2.0;
        row[2 * (rows - 1) + 1] = limb_len;
        row[2 * (rows - 2)] = peaks[4 * static_cast<long>(conn.id_a) + 2] +
                              peaks[4 * static_cast<long>(conn.id_b) + 2] +
                              score;
        subset.push_back(std::move(row));
      }
    }
  }

  // prune sparse / low-confidence people (evaluate.py:491-496)
  int n_out = 0;
  for (const auto& s : subset) {
    const double count = s[2 * (rows - 1)];
    if (count < min_parts || s[2 * (rows - 2)] / count < min_mean_score)
      continue;
    if (n_out >= max_people) break;
    std::memcpy(out_subsets + static_cast<size_t>(n_out) * 2 * rows, s.data(),
                sizeof(double) * 2 * rows);
    ++n_out;
  }
  return n_out;
}

}  // namespace

extern "C" int decode_people(
    const double* peaks, int total_peaks, const int* peaks_per_part,
    int num_parts, const float* paf, int H, int W, int C, const int* limbs,
    int n_limbs, int image_size, const double* params, double* out_subsets,
    int max_people) {
  const double thre2 = params[0];
  const double connect_ration = params[1];
  const int mid_num = static_cast<int>(params[2]);

  std::vector<int> part_offset(num_parts + 1, 0);
  for (int p = 0; p < num_parts; ++p)
    part_offset[p + 1] = part_offset[p] + peaks_per_part[p];
  if (part_offset[num_parts] != total_peaks) return -1;

  return assemble_subsets(
      peaks, num_parts, limbs, n_limbs, params[3], params[4], params[5] > 0.0,
      params[6], params[7],
      [&](int k) {
        return find_connections_for_limb(
            peaks, part_offset.data(), limbs[2 * k], limbs[2 * k + 1], paf, H,
            W, C, k, image_size, thre2, connect_ration, mid_num);
      },
      out_subsets, max_people);
}

// Assembly from pre-selected connections (the compact path's host stage).
// `connections` is the per-limb concatenation of 6-double rows
// [peak_id_a, peak_id_b, score, i, j, length] — the layout of
// infer/decode.py's connection_all; `conns_per_limb[k]` rows belong to
// limb k.  Only params[3..7] (len_rate, connection_tole, remove_recon,
// min_parts, min_mean_score) are read.
extern "C" int assemble_people(
    const double* peaks, int total_peaks, const double* connections,
    const int* conns_per_limb, int num_parts, const int* limbs, int n_limbs,
    const double* params, double* out_subsets, int max_people) {
  (void)total_peaks;
  std::vector<int> conn_offset(n_limbs + 1, 0);
  for (int k = 0; k < n_limbs; ++k)
    conn_offset[k + 1] = conn_offset[k] + conns_per_limb[k];

  return assemble_subsets(
      peaks, num_parts, limbs, n_limbs, params[3], params[4], params[5] > 0.0,
      params[6], params[7],
      [&](int k) {
        std::vector<Connection> out;
        out.reserve(conns_per_limb[k]);
        for (int r = conn_offset[k]; r < conn_offset[k + 1]; ++r) {
          const double* row = connections + 6 * static_cast<size_t>(r);
          out.push_back({row[0], row[1], row[2], static_cast<int>(row[3]),
                         static_cast<int>(row[4]), row[5]});
        }
        return out;
      },
      out_subsets, max_people);
}
