"""Structured JSONL run-event sink.

One append-only file per run (re-running over the same path stacks
runs; ``tools/telemetry_report.py`` reports the last ``run_start``
onward); every line is one JSON record:

- the first record is a ``run_start`` header carrying the schema
  version, wall-clock anchor, pid and caller-supplied run metadata;
- every record carries ``t`` — seconds since the sink opened, from the
  MONOTONIC clock, so event spacing survives NTP step adjustments and
  the report tool can lay a recompile timeline over step records;
- records are schema-versioned (``SCHEMA_VERSION``): consumers
  (``tools/telemetry_report.py``) refuse streams from a future schema
  instead of silently misreading them.

Writes are line-buffered under a lock, so the stream is tail-able while
the run is live and safe to emit from the train loop, the prefetch
thread and the serving engine's threads concurrently.  A process-wide
default sink (:func:`set_sink` / :func:`get_sink`) lets library helpers
(``utils.profiling.timed``) report through the run's stream instead of
stdout whenever a run installed one.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Dict, List, Optional

SCHEMA_VERSION = 1


def _definan(o):
    """Map non-finite floats to their string names ('nan'/'inf'/'-inf'),
    recursively.  ``json.dumps`` would emit bare ``NaN``/``Infinity``
    tokens — not JSON — and the records most likely to carry them (a
    diverged loss) are exactly the ones a strict consumer (jq, Go, JS)
    must be able to parse."""
    if isinstance(o, float) and not math.isfinite(o):
        return repr(o)
    if isinstance(o, dict):
        return {k: _definan(v) for k, v in o.items()}
    if isinstance(o, (list, tuple)):
        return [_definan(v) for v in o]
    return o


def _jsonable(o):
    """numpy scalars / arrays and anything else json chokes on."""
    try:
        import numpy as np

        if isinstance(o, np.ndarray):
            return o.tolist()
        if isinstance(o, np.generic):
            return o.item()
    except Exception:  # noqa: BLE001
        pass
    try:
        return float(o)
    except Exception:  # noqa: BLE001
        return str(o)


def strict_dumps(obj, *, default=None, **kw) -> str:
    """``json.dumps`` that can never emit bare ``NaN``/``Infinity``
    tokens: first try strict (``allow_nan=False`` — the common all-finite
    record pays no scan), and on rejection re-serialize through
    :func:`_definan` so non-finite floats become their string names.

    This is the process-wide emission idiom (graftlint JGL004): the sink,
    the COMMIT markers and every tool/artifact writer route through it,
    because the records most likely to carry a NaN — a diverged loss, an
    empty histogram's quantiles — are exactly the ones strict consumers
    (jq, Go, JS, the report tools) must be able to parse.
    """
    d = default if default is not None else _jsonable
    try:
        return json.dumps(obj, default=d, allow_nan=False, **kw)
    except ValueError:  # non-finite float somewhere in the payload
        return json.dumps(_definan(obj), default=lambda o: _definan(d(o)),
                          allow_nan=True, **kw)


def strict_dump(obj, fp, *, default=None, **kw) -> None:
    """:func:`strict_dumps` for file targets (``json.dump`` call sites:
    the bench/report artifacts, COMMIT markers, run ledgers)."""
    fp.write(strict_dumps(obj, default=default, **kw))


class NullSink:
    """Telemetry disabled: every emit is a no-op (the default sink)."""

    enabled = False
    path: Optional[str] = None
    t0: Optional[float] = None

    def emit(self, event: str, **fields) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self) -> "NullSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class EventSink:
    """Append-only JSONL event stream for one run."""

    enabled = True

    def __init__(self, path: str, run_meta: Optional[Dict] = None):
        self.path = os.path.abspath(path)
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(self.path, "a", buffering=1)  # line-buffered text
        # public: the run's monotonic anchor — obs.trace.TraceRecorder
        # shares it so span ts and event t are the same axis
        self.t0 = time.monotonic()
        self._closed = False
        # public: what the header carried — the serve router reads
        # run_id off the live sink to stamp worker shards with the SAME
        # run identity (the report tools' shard-mismatch guard)
        self.run_meta = dict(run_meta or {})
        header = {"event": "run_start", "schema": SCHEMA_VERSION, "t": 0.0,
                  "time_unix": round(time.time(), 3), "pid": os.getpid()}
        header.update(self.run_meta)
        self._write(header)

    def _write(self, rec: dict) -> None:
        line = strict_dumps(rec, separators=(",", ":"))
        with self._lock:
            if not self._closed:
                self._f.write(line + "\n")

    def emit(self, event: str, **fields) -> None:
        rec = {"event": event,
               "t": round(time.monotonic() - self.t0, 6)}
        rec.update(fields)
        self._write(rec)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._f.close()

    def __enter__(self) -> "EventSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


_sink_lock = threading.Lock()
_sink = NullSink()


def get_sink():
    """The process's current default sink (``NullSink`` when no run
    installed one)."""
    return _sink


def set_sink(sink):
    """Install ``sink`` as the process default; returns the previous
    sink so callers can restore it (``RunTelemetry`` does)."""
    global _sink
    with _sink_lock:
        prev = _sink
        _sink = sink if sink is not None else NullSink()
        return prev


def read_events(path: str) -> List[dict]:
    """Parse a JSONL event stream back into a list of records (blank
    lines skipped; a torn final line — the writer died mid-record — is
    dropped rather than raised)."""
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out
