"""Run-health sentinel: catch numeric divergence while it is one window
old, not one checkpoint old.

The reference's only defense against a blown-up run is dropping batches
whose loss exceeds a threshold (train_distributed.py:259-261) — a NaN
loss sails straight through it (``NaN > thre`` is False) and every
dashboard keeps printing "training" while the parameters are garbage.
The sentinel closes that hole end to end:

- **On device** (``train.step.make_train_step(health=True)``): the step
  computes the global gradient norm — ONE extra scalar per step, read
  back only at the existing window readback, so the sentinel adds no
  syncs.  Loss finiteness needs no extra scalar (the loss itself is
  already read back).
- **On host** (this class): :meth:`check` runs at each window readback —
  non-finite loss, non-finite grad norm, or a grad norm past the
  configured limit marks the window divergent, updates the
  ``health_ok`` gauge + ``health_divergences_total`` counter, and emits
  a ``health`` event into the run's JSONL stream.
- **Policy** (``TrainConfig.on_divergence``):

  - ``warn`` — record and keep training (the reference's spirit);
  - ``halt`` — raise :class:`DivergenceError` out of the train loop: a
    multi-day run stops at the first poisoned window instead of
    checkpointing garbage for another epoch;
  - ``skip_step`` — enforced INSIDE the jitted step (the branchless
    select that already drops abnormal-loss batches additionally
    requires a finite, in-limit grad norm), so divergent updates never
    reach the parameters and there is still no host round-trip in the
    hot loop.  The sentinel's role under this policy is visibility:
    the skipped windows still show up as ``health`` events.

- **Exposure**: the overall state (:meth:`state`) backs the
  ``/healthz`` route on the live endpoint — 200 while the latest
  window was healthy, 503 once it diverged — the shape a stock
  load-balancer/watchdog probe expects.
"""
from __future__ import annotations

import math
import threading
from typing import Optional

from .events import NullSink
from .registry import Registry, get_registry

POLICIES = ("warn", "halt", "skip_step")


def _jsonsafe(v: Optional[float], digits: int = 6):
    """Strict-JSON scalar: non-finite floats become their string names
    ('nan'/'inf'/'-inf') — ``json.dumps`` would otherwise emit the bare
    ``NaN``/``Infinity`` tokens, which strict parsers (jq, Go, JS) reject
    in exactly the divergence records this module exists to produce."""
    if v is None:
        return None
    return round(v, digits) if math.isfinite(v) else repr(v)


class DivergenceError(RuntimeError):
    """Raised by the ``halt`` policy at the first divergent window."""


class HealthSentinel:
    def __init__(self, registry: Optional[Registry] = None, sink=None,
                 policy: str = "warn", grad_norm_limit: float = 0.0):
        if policy not in POLICIES:
            raise ValueError(
                f"on_divergence policy {policy!r} unknown; use one of "
                f"{POLICIES}")
        self.policy = policy
        self.grad_norm_limit = float(grad_norm_limit)
        registry = registry if registry is not None else get_registry()
        self._sink = sink if sink is not None else NullSink()
        self._ok_gauge = registry.gauge(
            "health_ok", "1 while the latest checked window was healthy")
        self._ok_gauge.set(1.0)
        self._gnorm_gauge = registry.gauge(
            "health_grad_norm", "latest global gradient norm read back")
        self._checks = registry.counter(
            "health_checks_total", "windows checked by the sentinel")
        self._divergences = registry.counter(
            "health_divergences_total",
            "windows with non-finite loss/grad-norm (or past the limit)")
        self._lock = threading.Lock()
        self._status = "ok"
        self._ever_diverged = False
        self._last: dict = {}
        # named extra state sources merged into the /healthz body (the
        # run supervisor reports running/draining/backing-off here)
        self._extra: dict = {}

    def set_extra(self, name: str, fn) -> None:
        """Merge ``{name: fn()}`` into every :meth:`state` — how other
        subsystems (``train.supervisor``) surface their state on the
        same ``/healthz`` body without a second endpoint."""
        self._extra[str(name)] = fn

    # ------------------------------------------------------------ checks
    def check(self, loss: float, grad_norm: Optional[float] = None,
              step: Optional[int] = None,
              epoch: Optional[int] = None) -> bool:
        """Judge one readback window; returns True when healthy.

        Emits a ``health`` event either way (the stream's heartbeat —
        a report can tell "healthy" from "sentinel never ran"), trips
        the policy on divergence.
        """
        loss = float(loss)
        reasons = []
        if not math.isfinite(loss):
            reasons.append("loss_not_finite")
        gn = None
        if grad_norm is not None:
            gn = float(grad_norm)
            if not math.isfinite(gn):
                reasons.append("grad_norm_not_finite")
            elif 0.0 < self.grad_norm_limit < gn:
                reasons.append("grad_norm_over_limit")
            if math.isfinite(gn):
                # a NaN gauge would render as a malformed exposition
                # line; the divergence itself is carried by health_ok
                self._gnorm_gauge.set(gn)
        healthy = not reasons
        self._checks.inc()
        if not healthy:
            self._divergences.inc()
        self._ok_gauge.set(1.0 if healthy else 0.0)
        with self._lock:
            # current-window state (a later healthy window recovers it —
            # the probe contract); ever_diverged stays up for forensics.
            # _jsonsafe here AND in the emit: the /healthz body serves
            # this dict verbatim and must stay strict JSON
            self._status = "ok" if healthy else "diverged"
            self._ever_diverged |= not healthy
            self._last = {"loss": _jsonsafe(loss),
                          "grad_norm": _jsonsafe(gn), "step": step,
                          "epoch": epoch, "reasons": reasons}
        self._sink.emit(
            "health", status=self._status, loss=_jsonsafe(loss),
            grad_norm=_jsonsafe(gn),
            step=step, epoch=epoch, policy=self.policy,
            **({"reasons": reasons} if reasons else {}))
        if not healthy and self.policy == "halt":
            raise DivergenceError(
                f"run diverged at epoch={epoch} step={step}: "
                f"{', '.join(reasons)} (loss={loss!r}, grad_norm={gn!r}); "
                "on_divergence=halt — restart from the last healthy "
                "checkpoint")
        return healthy

    # ------------------------------------------------------------- state
    def state(self) -> dict:
        """JSON-ready overall state — the ``/healthz`` body."""
        with self._lock:
            out = {
                "status": self._status,
                "policy": self.policy,
                "grad_norm_limit": self.grad_norm_limit or None,
                "checks": int(self._checks.value),
                "divergences": int(self._divergences.value),
                "ever_diverged": self._ever_diverged,
                "last": dict(self._last),
            }
        for name, fn in self._extra.items():
            try:
                v = fn()
            except Exception as e:  # noqa: BLE001 — a probe body must
                v = f"error: {type(e).__name__}"          # never 500
            out[name] = v
            # an extra source can escalate the probe: a dict carrying
            # its own non-ok "status" (the fleet block once a worker
            # exhausts its crash budget) flips the top-level status —
            # and with it /healthz to 503 — without owning the route
            if (isinstance(v, dict) and out["status"] == "ok"
                    and v.get("status", "ok") != "ok"):
                out["status"] = str(v["status"])
        return out
