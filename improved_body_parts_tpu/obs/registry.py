"""Process-wide telemetry registry: counters, gauges, percentile
histograms and low-overhead span timers with one exposition path.

The reference instruments training with ad-hoc ``AverageMeter`` prints
around ``cuda.synchronize`` (reference: train_distributed.py:285-298);
every signal dies in stdout.  Here every layer — the train loop, the
host→device prefetch thread, the shm-ring input pipeline, the serving
engine — registers into one :class:`Registry`, which renders the whole
process's state two ways:

- :meth:`Registry.prometheus` — Prometheus text exposition 0.0.4 (the
  ``/metrics`` endpoint, ``obs.http.MetricsServer``);
- :meth:`Registry.snapshot` — one JSON-ready dict (``/snapshot``).

Metric objects are cheap to mutate on hot paths: a counter ``inc`` is a
lock + float add (~1 µs), histograms reuse ``utils.meters.PercentileMeter``
(bounded-memory reservoir, exact mean/count).  Sources whose state
already lives behind their own lock (``serve.metrics.ServeMetrics``)
plug in as *collectors* — callables sampled at scrape time — instead of
mirroring every mutation into a second object.
"""
from __future__ import annotations

import functools
import re
import threading
import time
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Tuple

from ..utils.meters import PercentileMeter

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
# (name, labels, kind, value) — kind "counter"|"gauge"; collectors yield
# these and histogram quantiles are expanded into them at render time
Sample = Tuple[str, Dict[str, str], str, float]


@functools.lru_cache(maxsize=4096)
def _sanitize(name: str) -> str:
    """Prometheus metric-name charset; everything else becomes ``_``.
    Cached: metric names are a small fixed set, and scrape-time callers
    (``_flat``, the history sampler) hit this once per sample per tick."""
    name = _NAME_RE.sub("_", name)
    return name if not name[:1].isdigit() else "_" + name


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


def _render_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    body = ",".join(
        '%s="%s"' % (_sanitize(str(k)),
                     str(v).replace("\\", r"\\").replace('"', r'\"')
                     .replace("\n", r"\n"))
        for k, v in sorted(labels.items()))
    return "{" + body + "}"


class Counter:
    """Monotonically increasing float (events, seconds-of)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name, self.help, self.labels = name, help, dict(labels or {})
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Point-in-time value — settable, or computed at scrape time via
    ``fn`` (e.g. ring-slot occupancy read off the live free list)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None,
                 fn: Optional[Callable[[], float]] = None):
        self.name, self.help, self.labels = name, help, dict(labels or {})
        self._fn = fn
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001 — a dead source reads as 0
                return 0.0
        return self._value


class Histogram:
    """Distribution with exact mean/count and reservoir-estimated tails
    (``PercentileMeter``); exposed as a Prometheus *summary* (quantile
    samples + ``_sum``/``_count``), since reservoir sampling estimates
    quantiles directly rather than fixed buckets."""

    kind = "histogram"
    QUANTILES = ((0.5, "p50"), (0.95, "p95"), (0.99, "p99"))

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None,
                 capacity: int = 4096, seed: int = 0):
        self.name, self.help, self.labels = name, help, dict(labels or {})
        self._lock = threading.Lock()
        self._meter = PercentileMeter(capacity=capacity, seed=seed)

    def observe(self, v: float) -> None:
        with self._lock:
            self._meter.update(float(v))

    @property
    def count(self) -> int:
        return self._meter.count

    @property
    def sum(self) -> float:
        return self._meter.sum

    def summary(self, scale: float = 1.0) -> dict:
        with self._lock:
            return self._meter.summary(scale=scale)

    def percentile(self, q: float) -> float:
        with self._lock:
            return self._meter.percentile(q)


class _Span:
    """``with registry.span("shard_batch"): ...`` — one perf_counter pair
    per entry, observed into a ``*_seconds`` histogram on exit."""

    __slots__ = ("_hist", "_t0")

    def __init__(self, hist: Histogram):
        self._hist = hist

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._hist.observe(time.perf_counter() - self._t0)


class Registry:
    """Named get-or-create store for metrics + scrape-time collectors.

    Creation is idempotent: ``counter("x")`` twice returns the same
    object (so instrumentation sites don't coordinate), and a name/kind
    clash raises instead of silently shadowing.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple, object] = {}
        self._collectors: List[Callable[[], Iterable[Sample]]] = []

    # ------------------------------------------------------ construction
    def _get(self, cls, name: str, help: str,
             labels: Optional[Dict[str, str]], **kw):
        key = (cls.kind, _sanitize(name), _label_key(labels))
        with self._lock:
            m = self._metrics.get(key[1:])
            if m is None:
                m = cls(key[1], help, labels, **kw)
                self._metrics[key[1:]] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {key[1]!r}{key[2]} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None,
              fn: Optional[Callable[[], float]] = None) -> Gauge:
        g = self._get(Gauge, name, help, labels, fn=fn)
        if fn is not None:
            # rebind on every registration: a new source re-attaching
            # under the same name (a fresh ShmRingInput after the old
            # one closed) must supersede the dead closure, or the gauge
            # reads the dead source's 0 forever
            g._fn = fn
        return g

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  capacity: int = 4096, seed: int = 0) -> Histogram:
        return self._get(Histogram, name, help, labels,
                         capacity=capacity, seed=seed)

    def span(self, name: str,
             labels: Optional[Dict[str, str]] = None) -> _Span:
        """Span timer: times a ``with`` block into ``<name>_seconds``."""
        n = name if name.endswith("_seconds") else name + "_seconds"
        return _Span(self.histogram(n, labels=labels))

    def register_collector(self,
                           fn: Callable[[], Iterable[Sample]]) -> None:
        """Add a scrape-time sample source (a callable returning
        ``(name, labels, kind, value)`` tuples).  For subsystems whose
        counters already live behind their own lock (``ServeMetrics``)
        — sampled once per scrape, zero hot-path cost."""
        with self._lock:
            self._collectors.append(fn)

    # -------------------------------------------------------- exposition
    def _flat(self) -> Iterator[Tuple[str, Dict[str, str], str, float,
                                      str]]:
        """(name, labels, kind, value, help) for every sample, histograms
        expanded to quantile/sum/count samples."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        for m in metrics:
            if isinstance(m, Histogram):
                s = m.summary()
                for q, key in Histogram.QUANTILES:
                    yield (m.name, {**m.labels, "quantile": str(q)},
                           "summary", s[key], m.help)
                yield (m.name + "_sum", dict(m.labels), "counter",
                       m.sum, m.help)
                yield (m.name + "_count", dict(m.labels), "counter",
                       float(s["count"]), m.help)
            else:
                yield (m.name, dict(m.labels), m.kind, m.value, m.help)
        for fn in collectors:
            try:
                for tup in fn():
                    # collectors yield (name, labels, kind, value) or,
                    # with help text, (name, labels, kind, value, help)
                    # — the fleet merge uses the 5-tuple form so worker
                    # families render HELP like first-class metrics
                    name, labels, kind, value = tup[:4]
                    help = tup[4] if len(tup) > 4 else ""
                    yield (_sanitize(name), dict(labels or {}), kind,
                           float(value), help)
            except Exception:  # noqa: BLE001 — one dead collector must
                continue       # not take down the whole exposition

    def iter_samples(self) -> Iterator[Tuple[str, Dict[str, str], str,
                                             float, str]]:
        """Public sample walk: ``(name, labels, kind, value, help)`` for
        every signal the registry would expose — histograms expanded to
        quantile/_sum/_count samples, collectors folded in.  The shared
        ingestion surface for consumers that are neither Prometheus nor
        JSON (``obs.history.HistoryStore`` samples it on a cadence)."""
        return self._flat()

    def prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        typed: set = set()
        for name, labels, kind, value, help in self._flat():
            # a summary's _sum/_count samples ride under the base
            # metric's family without TYPE lines of their own
            family = name
            for suffix in ("_sum", "_count"):
                if name.endswith(suffix) and name[:-len(suffix)] in typed:
                    family = None
                    break
            if family is not None and family not in typed:
                typed.add(family)
                if help:
                    lines.append(f"# HELP {family} {help}")
                lines.append(f"# TYPE {family} "
                             f"{'summary' if kind == 'summary' else kind}")
            lines.append(f"{name}{_render_labels(labels)} {float(value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """One JSON-ready dict of every registered signal."""
        out: Dict[str, object] = {}
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        for m in metrics:
            key = m.name + _render_labels(m.labels)
            if isinstance(m, Histogram):
                out[key] = m.summary()
            else:
                out[key] = m.value
        for fn in collectors:
            try:
                for tup in fn():
                    name, labels, _kind, value = tup[:4]
                    out[_sanitize(name) + _render_labels(labels or {})] = \
                        float(value)
            except Exception:  # noqa: BLE001
                continue
        return out


# the fraction of consumer wall time spent waiting on data above which a
# run is INPUT-BOUND — the one verdict threshold shared by every
# consumer of the StepPhases split (tools/telemetry_report.py,
# tools/trace_report.py), so the two reports can never contradict each
# other about the same run
INPUT_BOUND_FRAC = 0.4


class StepPhases:
    """Data-wait vs device-compute attribution for a consumer loop.

    Wraps a batch iterator (:meth:`attribute`): time the consumer blocks
    in ``next()`` is **data wait** (the input pipeline failed to stay
    ahead), time between a yield and the consumer's re-entry is
    **compute** (the training step holds the thread — under throttled
    readback this is device compute plus dispatch overhead, since the
    per-window ``float(loss)`` sync parks the thread until the device
    drains).  The two sum to the loop's wall time, which is what lets
    ``tools/telemetry_report.py`` issue an input-bound vs compute-bound
    verdict instead of a bare step time.
    """

    def __init__(self, registry: Registry, prefix: str = "train"):
        self.wait = registry.counter(
            f"{prefix}_data_wait_seconds_total",
            "time the consumer blocked waiting for the next batch")
        self.hold = registry.counter(
            f"{prefix}_compute_seconds_total",
            "time the consumer held the thread between batches "
            "(device step + dispatch + readback)")
        self.batches = registry.counter(f"{prefix}_batches_total",
                                        "batches consumed")
        # start of the hold segment currently in progress (the consumer
        # is between batches); consumer-thread-only
        self._open_t: Optional[float] = None

    def attribute(self, iterable: Iterable) -> Iterator:
        def gen():
            from .trace import get_tracer

            it = iter(iterable)
            while True:
                # the process tracer can be (re)installed mid-run; one
                # global read per batch keeps the split and the timeline
                # in lockstep without plumbing
                trace = get_tracer()
                tr0 = trace.now() if trace.enabled else 0.0
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    self.wait.inc(time.perf_counter() - t0)
                    return
                t1 = time.perf_counter()
                self.wait.inc(t1 - t0)
                self.batches.inc()
                if trace.enabled:
                    tr1 = trace.now()
                    trace.add_span_rel("data_wait", tr0, tr1 - tr0)
                self._open_t = t1
                yield item
                self._open_t = None
                self.hold.inc(time.perf_counter() - t1)
                if trace.enabled:
                    trace.add_span_rel("compute", tr1, trace.now() - tr1)

        return gen()

    def totals(self) -> Tuple[float, float]:
        """(data_wait_seconds, compute_seconds) so far — callers diff
        consecutive readings for per-window splits.

        The in-progress hold segment is included: the train loop reads
        this right after a window's readback sync, i.e. from INSIDE the
        current batch's hold segment (the counter itself only advances
        when the consumer asks for the next batch).  Without the
        in-progress part, every window's sync — the bulk of realized
        device compute under async dispatch — would be attributed to
        the FOLLOWING window, and the epoch's last sync to none at all.
        """
        hold = self.hold.value
        open_t = self._open_t
        if open_t is not None:
            hold += time.perf_counter() - open_t
        return self.wait.value, hold


_DEFAULT = Registry()


def get_registry() -> Registry:
    """The process-wide registry (train, input pipeline and serving all
    default to it, so one ``/metrics`` endpoint exposes everything)."""
    return _DEFAULT
