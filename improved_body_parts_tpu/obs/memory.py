"""Per-device HBM accounting + OOM forensics.

Large-batch TPU training dies on exactly one resource before any other:
device memory — and XLA's OOM message names the allocation that tipped
the scale, not the buffers that filled it.  :class:`DeviceMemory` makes
the fill visible while the run is healthy and names the occupants when
it is not:

- **Live gauges** — ``device_bytes_in_use{device=N}`` /
  ``device_peak_bytes{device=N}`` straight off
  ``jax.Device.memory_stats()`` (allocator truth, scrape-time only), and
  ``device_watermark_bytes{device=N}``: the highest ``bytes_in_use``
  *sampled this run* — the number to compare against the device limit
  when sizing a batch, distinct from the allocator's process-lifetime
  peak.
- **Event-stream samples** — the train loop calls :meth:`sample` at
  step-window boundaries (the cadence every other window signal uses),
  so the JSONL stream shows memory growth against loss/step-time on the
  same ``t`` axis.
- **OOM forensics** — :meth:`forensics` walks ``jax.live_arrays()`` and
  groups live buffers by (shape, dtype): the train loop's exception path
  emits the top occupants as a ``memory_forensics`` event, so a
  RESOURCE_EXHAUSTED post-mortem starts from "what was resident", not
  from re-running with a profiler attached.

Backends without allocator stats (CPU: ``memory_stats()`` returns
``None``) degrade gracefully: :meth:`sample` reports nothing, registers
nothing, and costs one attribute call per device — the no-op contract
that lets every call site run unconditionally.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .events import NullSink
from .registry import Registry, get_registry


class DeviceMemory:
    """HBM accounting for every visible device through one registry."""

    def __init__(self, registry: Optional[Registry] = None, sink=None):
        self.registry = registry if registry is not None else get_registry()
        self._sink = sink if sink is not None else NullSink()
        self._watermark: Dict[str, int] = {}
        # None until the first sample proves stats present/absent
        self.supported: Optional[bool] = None

    # ---------------------------------------------------------- sampling
    def sample(self, emit: bool = False, **fields) -> Dict[str, dict]:
        """Read every device's allocator stats; update gauges and the
        per-run watermark; optionally emit a ``memory`` event carrying
        the per-device numbers plus ``fields`` (epoch/step).  Returns
        ``{device_id: {bytes_in_use, peak_bytes, watermark_bytes,
        bytes_limit?}}`` — empty on statless backends (the graceful
        no-op: nothing registered, nothing emitted)."""
        try:
            import jax

            devices = jax.devices()
        except Exception:  # noqa: BLE001 — no backend, no accounting
            return {}
        per_dev: Dict[str, dict] = {}
        for d in devices:
            try:
                stats = d.memory_stats()
            except Exception:  # noqa: BLE001 — backend without stats
                stats = None
            if not stats:
                continue
            dev = str(d.id)
            in_use = int(stats.get("bytes_in_use", 0))
            peak = int(stats.get("peak_bytes_in_use", 0))
            mark = max(self._watermark.get(dev, 0), in_use)
            self._watermark[dev] = mark
            labels = {"device": dev}
            self.registry.gauge(
                "device_bytes_in_use", "allocator bytes currently live",
                labels=labels).set(in_use)
            self.registry.gauge(
                "device_peak_bytes", "allocator lifetime peak bytes",
                labels=labels).set(peak)
            self.registry.gauge(
                "device_watermark_bytes",
                "highest bytes_in_use sampled this run",
                labels=labels).set(mark)
            rec = {"bytes_in_use": in_use, "peak_bytes": peak,
                   "watermark_bytes": mark}
            if "bytes_limit" in stats:
                rec["bytes_limit"] = int(stats["bytes_limit"])
            per_dev[dev] = rec
        self.supported = bool(per_dev)
        if emit and per_dev:
            self._sink.emit("memory", devices=per_dev, **fields)
        return per_dev

    # --------------------------------------------------------- forensics
    def forensics(self, top: int = 15) -> dict:
        """Largest live device buffers grouped by (shape, dtype).

        Works on every backend (``jax.live_arrays`` tracks the arrays
        themselves, not allocator internals), so the CPU tests exercise
        the exact code path an HBM OOM takes.
        """
        try:
            import jax

            arrays = jax.live_arrays()
        except Exception:  # noqa: BLE001 — old jax / no backend
            return {"live_arrays": 0, "live_bytes": 0, "largest": []}
        groups: Dict[tuple, List[int]] = {}
        total = 0
        for a in arrays:
            try:
                nbytes = int(a.size) * a.dtype.itemsize
                key = (tuple(a.shape), str(a.dtype))
            except Exception:  # noqa: BLE001 — deleted mid-walk
                continue
            g = groups.setdefault(key, [0, 0])
            g[0] += 1
            g[1] += nbytes
            total += nbytes
        largest = sorted(groups.items(), key=lambda kv: -kv[1][1])[:top]
        return {
            "live_arrays": len(arrays),
            "live_bytes": total,
            "largest": [
                {"shape": list(shape), "dtype": dtype, "count": count,
                 "bytes": nbytes}
                for (shape, dtype), (count, nbytes) in largest],
        }

    def emit_forensics(self, reason: str = "", **fields) -> dict:
        """Emit the forensics report (plus current device stats) into
        the event stream; the train loop's exception path calls this so
        an OOM'd run's last record names the resident buffers."""
        report = self.forensics()
        report["devices"] = self.sample()
        self._sink.emit("memory_forensics", reason=reason, **report,
                        **fields)
        return report
