"""Declarative SLOs with multi-window burn-rate and error budgets.

The serving stack emits per-hop attribution (``obs.reqtrace``,
``serve.metrics``) — raw material.  An autoscaler or deploy gate
(ROADMAP "fleet-scale serving control plane") needs a *decision* signal:
is the service keeping its latency/availability promise, and how fast
is it spending the budget it is allowed to miss by?  This module is
that layer:

- :class:`Objective` — one QoS class's promise, declared as data: a
  request is GOOD when it succeeded AND answered within
  ``latency_ms``; the class must keep ``target`` of its requests good.
- :class:`SLOTracker` — fed one ``record()`` per finished request,
  computes per class:

  - **availability** over each burn window (good / total);
  - **burn rate** per window — ``bad_frac / (1 - target)``: 1.0 means
    spending exactly the sustainable budget, N means the budget burns
    N× too fast (the Google SRE multi-window convention);
  - **error budget remaining** — cumulative over the tracker's life:
    1.0 untouched, 0.0 exhausted;
  - **alarm** — burning faster than ``burn_alarm`` on EVERY window
    simultaneously (the fast window catches the cliff, the slow window
    filters blips) with at least ``min_requests`` in the fast window.
    Alarm *transitions* emit ``slo_alarm`` sink events — the
    autoscaler/pager edge, not a level repeated every scrape.

- Exposition: ``register_into`` publishes gauges/counters on the shared
  registry (``slo_burn_rate{class=,window=}``,
  ``slo_error_budget_remaining{class=}``, …); ``obs.http.MetricsServer``
  serves :meth:`SLOTracker.state` at ``/slo`` (HEAD parity like every
  route) so a stock controller can poll one JSON document.

Wiring: ``DynamicBatcher`` / ``EnginePool`` / ``PolicyClient`` accept
``slo=tracker, qos_class="..."`` and record every finished request.
Attach the tracker at ONE layer per deployment — the outermost one the
caller's promise is made at (recording the same request at two layers
double-counts it).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from .events import get_sink


class Objective:
    """One QoS class's declarative promise.

    ``latency_ms``: a request slower than this is BAD even when it
    succeeded (the latency SLO and the availability SLO share one good
    count — a slow success spends the same budget as an error).
    ``target``: the good fraction promised (0 < target < 1).
    ``windows_s``: burn-rate windows, fastest first.
    ``burn_alarm``: the burn-rate multiple that fires the alarm when
    exceeded on every window at once.
    ``min_requests``: volume floor in the FAST window before the alarm
    may fire (ten bad requests out of ten is not a page).
    """

    __slots__ = ("name", "latency_ms", "target", "windows_s",
                 "burn_alarm", "min_requests")

    def __init__(self, name: str, latency_ms: float, target: float = 0.99,
                 windows_s: Sequence[float] = (60.0, 600.0),
                 burn_alarm: float = 2.0, min_requests: int = 10):
        if not 0.0 < target < 1.0:
            raise ValueError(f"target={target} must be in (0, 1) — an "
                             "SLO of 1.0 has no error budget to burn")
        if latency_ms <= 0:
            raise ValueError(f"latency_ms={latency_ms} must be > 0")
        if not windows_s or any(w <= 0 for w in windows_s):
            raise ValueError(f"windows_s={windows_s} must be positive")
        self.name = str(name)
        self.latency_ms = float(latency_ms)
        self.target = float(target)
        self.windows_s = tuple(sorted(float(w) for w in windows_s))
        self.burn_alarm = float(burn_alarm)
        self.min_requests = int(min_requests)

    @classmethod
    def from_dict(cls, name: str, spec: dict) -> "Objective":
        """Build from the declarative config shape::

            {"latency_ms": 250, "target": 0.99,
             "windows_s": [60, 600], "burn_alarm": 2.0,
             "min_requests": 10}
        """
        known = {"latency_ms", "target", "windows_s", "burn_alarm",
                 "min_requests"}
        unknown = set(spec) - known
        if unknown:
            raise ValueError(
                f"objective {name!r}: unknown keys {sorted(unknown)} "
                f"(known: {sorted(known)})")
        if "latency_ms" not in spec:
            raise ValueError(f"objective {name!r} needs latency_ms")
        return cls(name, **spec)

    def to_dict(self) -> dict:
        return {"latency_ms": self.latency_ms, "target": self.target,
                "windows_s": list(self.windows_s),
                "burn_alarm": self.burn_alarm,
                "min_requests": self.min_requests}


class _ClassState:
    __slots__ = ("obj", "events", "total", "good", "alarm",
                 "alarm_transitions")

    def __init__(self, obj: Objective):
        self.obj = obj
        # (t_mono, good) per request, trimmed past the slowest window
        self.events: deque = deque()
        self.total = 0
        self.good = 0
        self.alarm = False
        self.alarm_transitions = 0


class SLOTracker:
    """Per-class SLO state machine over request outcomes.

    ``objectives``: either :class:`Objective` instances or a declarative
    dict ``{class_name: {objective spec}}``.  ``clock`` is injectable
    (monotonic seconds) so burn windows are testable without sleeping.
    Requests recorded under an undeclared class fall into
    ``default_class`` when set, else they are counted in
    ``unclassified`` and otherwise ignored — a typo'd class must not
    silently vanish, and must not crash the serve thread either.
    """

    def __init__(self, objectives, *, default_class: Optional[str] = None,
                 clock=time.monotonic):
        if isinstance(objectives, dict):
            objectives = [Objective.from_dict(name, dict(spec))
                          for name, spec in objectives.items()]
        if not objectives:
            raise ValueError("SLOTracker needs at least one Objective")
        self._clock = clock
        self._lock = threading.Lock()
        self._classes: Dict[str, _ClassState] = {
            o.name: _ClassState(o) for o in objectives}
        if default_class is not None and default_class not in self._classes:
            raise ValueError(f"default_class={default_class!r} is not a "
                             f"declared objective "
                             f"({sorted(self._classes)})")
        self.default_class = default_class
        self.unclassified = 0

    # ------------------------------------------------------------- record
    def record(self, qos_class: str, latency_s: float,
               error: bool = False) -> None:
        """One finished request: latency in seconds, ``error`` True for
        a failure (which is bad at any latency).  Thread-safe and
        hot-path cheap: one lock, one append, one trim."""
        event = None
        with self._lock:
            cs = self._classes.get(qos_class)
            if cs is None:
                if self.default_class is None:
                    self.unclassified += 1
                    return
                cs = self._classes[self.default_class]
            now = self._clock()
            good = (not error) and latency_s * 1e3 <= cs.obj.latency_ms
            cs.events.append((now, good))
            cs.total += 1
            cs.good += good
            self._trim(cs, now)
            alarm = self._alarm_locked(cs, now)
            if alarm != cs.alarm:
                cs.alarm = alarm
                cs.alarm_transitions += alarm  # count firings only
                event = {"qos_class": cs.obj.name,
                         "state": "firing" if alarm else "resolved",
                         "burn_rates": self._burn_rates_locked(cs, now),
                         "target": cs.obj.target,
                         "burn_alarm": cs.obj.burn_alarm}
        # sink emission outside the lock (the sink has its own)
        if event is not None:
            get_sink().emit("slo_alarm", **event)

    def _trim(self, cs: _ClassState, now: float) -> None:
        horizon = now - cs.obj.windows_s[-1]
        ev = cs.events
        while ev and ev[0][0] < horizon:
            ev.popleft()

    # ------------------------------------------------------------ windows
    def _window_stats(self, cs: _ClassState,
                      now: float) -> List[Tuple[float, int, int]]:
        """[(window_s, total, good)] per configured window (events are
        time-ordered; one reverse scan covers all windows)."""
        stats = [[w, 0, 0] for w in cs.obj.windows_s]
        for t, good in reversed(cs.events):
            age = now - t
            live = False
            for s in stats:
                if age <= s[0]:
                    s[1] += 1
                    s[2] += good
                    live = True
            if not live:
                break       # older than every window
        return [tuple(s) for s in stats]

    def _burn_rates_locked(self, cs: _ClassState,
                           now: float) -> Dict[str, float]:
        budget = 1.0 - cs.obj.target
        out = {}
        for w, total, good in self._window_stats(cs, now):
            bad_frac = (total - good) / total if total else 0.0
            out[f"{w:g}s"] = round(bad_frac / budget, 4)
        return out

    def _alarm_locked(self, cs: _ClassState, now: float) -> bool:
        budget = 1.0 - cs.obj.target
        stats = self._window_stats(cs, now)
        if stats[0][1] < cs.obj.min_requests:
            return False
        for w, total, good in stats:
            bad_frac = (total - good) / total if total else 0.0
            if bad_frac / budget < cs.obj.burn_alarm:
                return False
        return True

    # ------------------------------------------------------------ readout
    def state(self) -> dict:
        """The ``/slo`` document: one JSON-ready dict an autoscaler or
        deploy gate polls.  ``status`` is "ok" unless any class's alarm
        is firing."""
        with self._lock:
            now = self._clock()
            classes = {}
            any_alarm = False
            for name, cs in self._classes.items():
                budget = 1.0 - cs.obj.target
                windows = {}
                for w, total, good in self._window_stats(cs, now):
                    bad_frac = ((total - good) / total) if total else 0.0
                    windows[f"{w:g}s"] = {
                        "requests": total,
                        "availability": (round(good / total, 6)
                                         if total else None),
                        "burn_rate": round(bad_frac / budget, 4),
                    }
                spent = (cs.total - cs.good) / max(cs.total * budget, 1e-12)
                any_alarm = any_alarm or cs.alarm
                classes[name] = {
                    "objective": cs.obj.to_dict(),
                    "requests_total": cs.total,
                    "good_total": cs.good,
                    "error_budget_remaining": round(
                        max(0.0, 1.0 - spent), 6) if cs.total else 1.0,
                    "windows": windows,
                    "alarm": cs.alarm,
                    "alarm_transitions": cs.alarm_transitions,
                }
            return {
                "status": "alarm" if any_alarm else "ok",
                "unclassified_requests": self.unclassified,
                "classes": classes,
            }

    # ---------------------------------------------------------- telemetry
    def register_into(self, registry) -> "SLOTracker":
        """Publish the consumable gauges on a shared ``obs.Registry``
        (weakref collector — the ServeMetrics discipline): burn rates
        per window, budget remaining, alarm level, good/total
        counters."""
        import weakref

        ref = weakref.ref(self)

        def _collect():
            t = ref()
            return t.collect() if t is not None else []

        registry.register_collector(_collect)
        return self

    def collect(self, prefix: str = "slo"):
        state = self.state()
        samples = [(f"{prefix}_unclassified_requests_total", {},
                    "counter", float(state["unclassified_requests"]))]
        for name, cls_state in state["classes"].items():
            labels = {"class": name}
            samples += [
                (f"{prefix}_requests_total", labels, "counter",
                 float(cls_state["requests_total"])),
                (f"{prefix}_good_total", labels, "counter",
                 float(cls_state["good_total"])),
                (f"{prefix}_error_budget_remaining", labels, "gauge",
                 float(cls_state["error_budget_remaining"])),
                (f"{prefix}_alarm", labels, "gauge",
                 1.0 if cls_state["alarm"] else 0.0),
                (f"{prefix}_alarm_transitions_total", labels, "counter",
                 float(cls_state["alarm_transitions"])),
            ]
            for w, win in cls_state["windows"].items():
                samples.append((f"{prefix}_burn_rate",
                                {**labels, "window": w}, "gauge",
                                float(win["burn_rate"])))
        return samples


def default_objectives() -> List[Objective]:
    """A reasonable starting declaration for the serve stack: an
    interactive class on a tight latency bound and a batch class on a
    loose one.  Deployments should declare their own numbers — these
    exist so ``SLOTracker(default_objectives())`` works out of the box
    in tools and tests."""
    return [
        Objective("interactive", latency_ms=250.0, target=0.99,
                  windows_s=(60.0, 600.0), burn_alarm=2.0),
        Objective("batch", latency_ms=2000.0, target=0.999,
                  windows_s=(300.0, 3600.0), burn_alarm=2.0),
    ]
