"""Recompile detection — the classic silent TPU perf killer.

An unexpected XLA compile after warmup (a shape bucket nobody
precompiled, a weak-type flip, a donated-buffer mismatch) stalls the
whole pipeline for seconds to minutes while every dashboard still shows
"training".  The reference has no defense; our serving warmup
(``serve.warmup.precompile``) only covers the buckets it was told about.

:class:`CompileWatch` hooks ``jax.monitoring``'s duration events —
``/jax/core/compile/backend_compile_duration`` fires once per actual
backend (XLA) compile and NOT on compilation-cache hits — counts every
compile into the registry, and once :meth:`mark_warm` is called (the
caller's "steady state starts now" signal: after serving warmup, after
the first train window's readback) every further compile:

- increments ``xla_recompiles_post_warmup_total``;
- appends to the in-process :attr:`timeline`;
- emits a visible ``recompile`` event into the run's JSONL sink, which
  ``tools/telemetry_report.py`` folds into a recompile timeline.

Fallback: on jax builds without ``jax.monitoring`` the hook degrades to
:meth:`wrap` — wrap a jitted callable and unseen (shape, dtype)
signatures are flagged as compiles from the call site.  ``wrap`` is a
no-op layer when the monitoring hook is live, so it is safe to apply
unconditionally.

jax exposes no per-listener deregistration, so :meth:`uninstall` flips
the instance inactive (the registered closure becomes a no-op) rather
than unhooking; idle inactive listeners are a few ns per compile event.
"""
from __future__ import annotations

import functools
import threading
from typing import List, Optional

from .events import NullSink
from .registry import Registry, get_registry

COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _abstract_signature(args, kwargs):
    """Hashable (shape, dtype) signature of every array-like leaf — the
    same thing jit's tracing cache keys on, minus static args."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype)))
        else:
            sig.append((type(leaf).__name__,))
    return treedef, tuple(sig)


class CompileWatch:
    def __init__(self, registry: Optional[Registry] = None, sink=None):
        registry = registry if registry is not None else get_registry()
        self.compiles = registry.counter(
            "xla_compiles_total", "backend (XLA) compiles this process")
        self.recompiles = registry.counter(
            "xla_recompiles_post_warmup_total",
            "unexpected XLA compiles after mark_warm — each one stalled "
            "the pipeline")
        self.compile_seconds = registry.counter(
            "xla_compile_seconds_total", "wall time spent compiling")
        self._sink = sink if sink is not None else NullSink()
        self._lock = threading.Lock()
        self._warm = False
        self._active = False
        self._hooked = False
        # post-warmup compiles in arrival order (the report's timeline)
        self.timeline: List[dict] = []

    # ---------------------------------------------------------- lifecycle
    def install(self) -> "CompileWatch":
        """Register the ``jax.monitoring`` listener (idempotent).

        The listener closes over a WEAKREF to this watch: jax offers no
        per-listener removal, so a strong reference would pin each run's
        watch — and through it the run's registry (reservoir histograms)
        and sink — for process lifetime in any process that constructs
        ``RunTelemetry`` repeatedly.  A dead watch's listener survives
        as an inert no-op closure instead.
        """
        import weakref

        with self._lock:
            if self._active:
                return self
            self._active = True
        try:
            from jax import monitoring

            ref = weakref.ref(self)

            def _listener(name, secs, **kw):
                watch = ref()
                if watch is not None:
                    watch._on_duration(name, secs, **kw)

            monitoring.register_event_duration_secs_listener(_listener)
            self._hooked = True
        except Exception:  # noqa: BLE001 — old jax: wrap() still works
            self._hooked = False
        return self

    def uninstall(self) -> None:
        """Deactivate (the jax-side listener stays registered but
        no-ops — jax has no per-listener removal)."""
        with self._lock:
            self._active = False

    @property
    def warm(self) -> bool:
        return self._warm

    # ------------------------------------------------------------ signals
    def _on_duration(self, name: str, secs: float, **kw) -> None:
        if self._active and name == COMPILE_EVENT:
            self._record(float(secs), source="jax.monitoring")

    def _record(self, secs: float, source: str) -> None:
        self.compiles.inc()
        self.compile_seconds.inc(secs)
        with self._lock:
            warm = self._warm
        if warm:
            self.recompiles.inc()
            ev = {"duration_s": round(secs, 6), "source": source}
            self.timeline.append(ev)
            self._sink.emit("recompile", **ev)

    def mark_warm(self, label: str = "") -> None:
        """Steady state starts now: every compile from here on is
        unexpected.  Idempotent — the first caller wins, so the train
        loop can call it every print window."""
        with self._lock:
            if self._warm:
                return
            self._warm = True
        self._sink.emit("warmup_complete", label=label,
                        compiles_during_warmup=self.compiles.value)

    # ------------------------------------------------------------ fallback
    def wrap(self, fn):
        """Jit-wrapper compile counter: flags calls whose arg
        (shape, dtype) signature was never seen — a fresh trace, hence a
        compile — for jax builds without ``jax.monitoring``.  When the
        monitoring hook is live this wrapper only tracks signatures (no
        double counting)."""
        seen = set()
        lock = threading.Lock()

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            try:
                sig = _abstract_signature(args, kwargs)
            except Exception:  # noqa: BLE001 — unhashable exotic args
                sig = None
            if sig is not None:
                with lock:
                    fresh = sig not in seen
                    seen.add(sig)
                if fresh and self._active and not self._hooked:
                    self._record(0.0, source="jit-wrapper")
            return fn(*args, **kwargs)

        return wrapper
