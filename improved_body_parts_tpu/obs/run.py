"""One run's telemetry bundle: registry + JSONL sink + compile watch +
optional live metrics endpoint, assembled from three config knobs
(``TrainConfig.telemetry_sink`` / ``telemetry_port`` /
``telemetry_sample``) or directly by tools.

::

    with RunTelemetry("events.jsonl", http_port=0,
                      run_meta={"tool": "train"}) as tele:
        fit(state, step, cfg, make_batches, epochs, telemetry=tele)

Installing the bundle also installs its sink as the process default
(``obs.events.set_sink``) so library helpers (``utils.profiling.timed``)
report through the run's stream instead of stdout; ``close()`` restores
the previous sink.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

from .events import EventSink, NullSink, set_sink
from .http import MetricsServer
from .recompile import CompileWatch
from .registry import Registry, StepPhases, get_registry


class RunTelemetry:
    def __init__(self, sink_path: Optional[str] = None,
                 http_port: Optional[int] = None,
                 registry: Optional[Registry] = None,
                 run_meta: Optional[Dict] = None,
                 step_sample: int = 1,
                 watch_compiles: bool = True,
                 install_default_sink: bool = True):
        self.registry = registry if registry is not None else get_registry()
        self.sink = (EventSink(sink_path, run_meta=run_meta)
                     if sink_path else NullSink())
        self._prev_sink = None
        self._installed_sink = False
        if install_default_sink and self.sink.enabled:
            self._prev_sink = set_sink(self.sink)
            self._installed_sink = True
        self.compile_watch = CompileWatch(self.registry, self.sink)
        if watch_compiles:
            self.compile_watch.install()
        # emit every Nth per-print_freq step record (cheap runs keep 1;
        # multi-week runs can thin the stream without losing the split,
        # which accumulates in counters regardless)
        self.step_sample = max(1, int(step_sample))
        self.server = (MetricsServer(self.registry, port=http_port,
                                     extra=lambda: {"events": self.sink.path})
                       if http_port is not None and http_port >= 0 else None)
        self._phases: Dict[str, StepPhases] = {}
        self._closed = False

    # ----------------------------------------------------------- accessors
    def phases(self, prefix: str = "train") -> StepPhases:
        """Get-or-create the data-wait/compute attribution counters for
        one consumer loop (train and eval keep separate prefixes)."""
        p = self._phases.get(prefix)
        if p is None:
            p = self._phases[prefix] = StepPhases(self.registry, prefix)
        return p

    def emit(self, event: str, **fields) -> None:
        self.sink.emit(event, **fields)

    def mark_warm(self, label: str = "") -> None:
        self.compile_watch.mark_warm(label)

    # ----------------------------------------------------------- lifecycle
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.server is not None:
            self.server.close()
        self.compile_watch.uninstall()
        if self._installed_sink:
            set_sink(self._prev_sink)
        self.sink.close()

    def __enter__(self) -> "RunTelemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def resolve_sink_path(configured: str, checkpoint_dir: str) -> Optional[str]:
    """Map a ``TrainConfig.telemetry_sink`` value to a concrete path:
    ``""`` → disabled (None), ``"auto"`` → ``<checkpoint_dir>/events.jsonl``,
    anything else is the path itself."""
    if not configured:
        return None
    if configured == "auto":
        return os.path.join(checkpoint_dir, "events.jsonl")
    return configured
