"""One run's telemetry bundle: registry + JSONL sink + compile watch +
span trace + device-memory accounting + run-health sentinel + optional
live metrics endpoint, assembled from the config knobs
(``TrainConfig.telemetry_sink`` / ``telemetry_port`` /
``telemetry_sample`` / ``telemetry_trace`` / ``on_divergence``) or
directly by tools.

::

    with RunTelemetry("events.jsonl", http_port=0,
                      trace_path="trace.json",
                      run_meta={"tool": "train"}) as tele:
        fit(state, step, cfg, make_batches, epochs, telemetry=tele)

Installing the bundle also installs its sink as the process default
(``obs.events.set_sink``) so library helpers (``utils.profiling.timed``)
report through the run's stream instead of stdout, and its span recorder
as the process default tracer (``obs.trace.set_tracer``) so
instrumentation sites the bundle is never plumbed to — the shm-ring
consumer, the prefetch producer thread, the serving engine — land on the
same timeline; ``close()`` restores both, saves the trace (when a path
was configured) and emits a ``trace_export`` event pointing at it.
"""
from __future__ import annotations

import os
from typing import Dict, Optional

from .events import EventSink, NullSink, set_sink
from .health import HealthSentinel
from .http import MetricsServer
from .memory import DeviceMemory
from .recompile import CompileWatch
from .registry import Registry, StepPhases, get_registry
from .reqtrace import NullReqTrace, ReqTrace, set_reqtrace
from .trace import NullTraceRecorder, TraceRecorder, set_tracer


class RunTelemetry:
    def __init__(self, sink_path: Optional[str] = None,
                 http_port: Optional[int] = None,
                 registry: Optional[Registry] = None,
                 run_meta: Optional[Dict] = None,
                 step_sample: int = 1,
                 watch_compiles: bool = True,
                 install_default_sink: bool = True,
                 trace_path: Optional[str] = None,
                 trace: Optional[bool] = None,
                 trace_capacity: int = 65536,
                 on_divergence: str = "warn",
                 grad_norm_limit: float = 0.0,
                 reqtrace_sample: Optional[int] = None,
                 slo=None,
                 history=None):
        self.registry = registry if registry is not None else get_registry()
        self.sink = (EventSink(sink_path, run_meta=run_meta)
                     if sink_path else NullSink())
        self._prev_sink = None
        self._installed_sink = False
        if install_default_sink and self.sink.enabled:
            self._prev_sink = set_sink(self.sink)
            self._installed_sink = True
        self.compile_watch = CompileWatch(self.registry, self.sink)
        if watch_compiles:
            self.compile_watch.install()
        # span recorder: on when a trace path was configured or (by
        # default) whenever the sink is — an in-memory ring is cheap and
        # keeps the overhead A/B honest about what a real run pays;
        # trace=False forces it off, trace=True forces it on
        trace_on = (trace if trace is not None
                    else bool(trace_path) or self.sink.enabled)
        self.trace = (TraceRecorder(capacity=trace_capacity,
                                    t0=self.sink.t0)
                      if trace_on else NullTraceRecorder())
        self.trace_path = trace_path
        self._prev_tracer = None
        self._installed_tracer = False
        if self.trace.enabled:
            self._prev_tracer = set_tracer(self.trace)
            self._installed_tracer = True
            # satellite: a lossy ring must be visible on /metrics, not
            # only as a stamp buried in the export
            self.trace.attach_registry(self.registry)
        # request-scoped causal tracing (obs.reqtrace): on whenever the
        # sink is (records emit through it), like the span trace; 0
        # forces it off, N samples every Nth request
        if reqtrace_sample is None:
            reqtrace_sample = 1 if self.sink.enabled else 0
        self.reqtrace = (ReqTrace(sample=reqtrace_sample,
                                  t0=self.sink.t0)
                         if reqtrace_sample >= 1 else NullReqTrace())
        self._prev_reqtrace = None
        self._installed_reqtrace = False
        if self.reqtrace.enabled:
            self._prev_reqtrace = set_reqtrace(self.reqtrace)
            self._installed_reqtrace = True
            self.reqtrace.attach_registry(self.registry)
        # optional SLO tracker (obs.slo): registered for exposition and
        # served at /slo when the endpoint is up
        self.slo = slo
        if slo is not None:
            slo.register_into(self.registry)
        # optional telemetry-history store (obs.history): its sampler
        # runs for the life of the bundle, its meta-signals join the
        # registry, and the endpoint serves it at /history + /query;
        # close() stops the sampler and flushes its shards
        self.history = history
        if history is not None:
            history.register_into(self.registry)
            history.start()
        # device-memory accounting (graceful no-op on statless backends)
        self.memory = DeviceMemory(self.registry, self.sink)
        # run-health sentinel; its state backs the endpoint's /healthz
        self.health = HealthSentinel(self.registry, self.sink,
                                     policy=on_divergence,
                                     grad_norm_limit=grad_norm_limit)
        # emit every Nth per-print_freq step record (cheap runs keep 1;
        # multi-week runs can thin the stream without losing the split,
        # which accumulates in counters regardless)
        self.step_sample = max(1, int(step_sample))
        self.server = (MetricsServer(self.registry, port=http_port,
                                     extra=self._server_extra,
                                     health=self.health.state,
                                     slo=(slo.state if slo is not None
                                          else None),
                                     history=history)
                       if http_port is not None and http_port >= 0 else None)
        self._phases: Dict[str, StepPhases] = {}
        self._closed = False

    def _server_extra(self) -> dict:
        return {"events": self.sink.path, "trace": self.trace_path,
                "health": self.health.state()}

    # ----------------------------------------------------------- accessors
    def phases(self, prefix: str = "train") -> StepPhases:
        """Get-or-create the data-wait/compute attribution counters for
        one consumer loop (train and eval keep separate prefixes)."""
        p = self._phases.get(prefix)
        if p is None:
            p = self._phases[prefix] = StepPhases(self.registry, prefix)
        return p

    def emit(self, event: str, **fields) -> None:
        self.sink.emit(event, **fields)

    def mark_warm(self, label: str = "") -> None:
        self.compile_watch.mark_warm(label)

    # ----------------------------------------------------------- lifecycle
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self.server is not None:
            self.server.close()
        if self.history is not None:
            self.history.close()
        self.compile_watch.uninstall()
        if self.trace.enabled and self.trace_path:
            # count via the ring's length — events() would serialize the
            # whole ring a second time just to be len()'d
            n = self.trace.recorded
            path = self.trace.save(self.trace_path)
            self.sink.emit("trace_export", path=path, events=n,
                           dropped=self.trace.dropped)
        if self._installed_reqtrace:
            set_reqtrace(self._prev_reqtrace)
        if self._installed_tracer:
            set_tracer(self._prev_tracer)
        if self._installed_sink:
            set_sink(self._prev_sink)
        self.sink.close()

    def __enter__(self) -> "RunTelemetry":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def resolve_sink_path(configured: str, checkpoint_dir: str,
                      default_name: str = "events.jsonl"
                      ) -> Optional[str]:
    """Map a ``TrainConfig.telemetry_sink`` / ``telemetry_trace`` value
    to a concrete path: ``""`` → disabled (None), ``"auto"`` →
    ``<checkpoint_dir>/<default_name>``, anything else is the path
    itself."""
    if not configured:
        return None
    if configured == "auto":
        return os.path.join(checkpoint_dir, default_name)
    return configured
