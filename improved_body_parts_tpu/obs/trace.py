"""Lock-cheap span recorder with Chrome/Perfetto ``trace_event`` export.

The event sink (``obs/events.py``) answers *what happened*; this module
answers *where the time went*: every layer records spans — worker
renders in the shm ring, ``shard_batch`` placements on the prefetch
thread, step windows with their data-wait/compute children, the serving
engine's request lifecycle — into one process-wide ring buffer, and the
whole timeline exports as Chrome ``trace_event`` JSON that loads
directly in Perfetto (ui.perfetto.dev) or ``chrome://tracing``.

Design constraints, in order:

- **Hot-path cheap.** A span record is two ``time.monotonic()`` calls
  and one ``deque.append`` (atomic under the GIL — no lock on the record
  path; the only lock guards first-use track registration).  With
  tracing off every site hits :class:`NullTraceRecorder`, whose methods
  are empty — one attribute check.
- **Bounded memory.** The ring holds ``capacity`` events and evicts the
  oldest; the export stamps how many were dropped so a truncated
  timeline can never read as a complete one.
- **One clock.** Timestamps are seconds on the MONOTONIC clock relative
  to a shared ``t0`` — ``RunTelemetry`` anchors the recorder to its
  event sink's ``t0``, so a span's ``ts`` and an event's ``t`` are the
  same axis and the JSONL stream can be laid over the timeline.
  ``CLOCK_MONOTONIC`` is system-wide on Linux, which is what lets the
  shm-ring *workers* (separate processes) ship a raw monotonic start
  stamp on the existing done-queue token and have
  :meth:`TraceRecorder.add_span_abs` place the render correctly among
  consumer-side spans.
- **Tracks, not threads.** Every span lands on a named track (default:
  the recording thread's name); tracks map to stable ``tid``s with
  ``thread_name`` metadata so Perfetto labels them.  Cross-thread
  request lifecycles (the dynamic batcher) use async begin/end pairs
  keyed by request id, plus flow arrows from each submit to the batch
  that executed it — batching fan-in is visible as N arrows converging
  on one ``execute`` slice.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional


class _NullSpan:
    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NULL_SPAN = _NullSpan()


class NullTraceRecorder:
    """Tracing disabled: every record is a no-op (the default)."""

    enabled = False
    recorded = 0
    dropped = 0

    def now(self) -> float:
        return 0.0

    def span(self, name: str, track: Optional[str] = None,
             args: Optional[dict] = None) -> _NullSpan:
        return _NULL_SPAN

    def add_span_rel(self, name, ts, dur, track=None, args=None) -> None:
        pass

    def add_span_abs(self, name, t_mono, dur, track=None, args=None) -> None:
        pass

    def instant(self, name, track=None, args=None) -> None:
        pass

    def async_begin(self, name, id, cat="async", args=None) -> None:
        pass

    def async_end(self, name, id, cat="async", args=None) -> None:
        pass

    def flow_start(self, name, id, track=None, ts=None, cat="flow") -> None:
        pass

    def flow_step(self, name, id, track=None, ts=None, cat="flow") -> None:
        pass

    def flow_finish(self, name, id, track=None, ts=None, cat="flow") -> None:
        pass

    def attach_registry(self, registry) -> None:
        pass

    def events(self) -> List[dict]:
        return []

    def export(self) -> dict:
        return {"traceEvents": []}

    def save(self, path: str) -> None:
        pass


class _Span:
    """``with recorder.span("render"): ...`` — records one X event."""

    __slots__ = ("_rec", "_name", "_track", "_args", "_t0")

    def __init__(self, rec: "TraceRecorder", name: str,
                 track: Optional[str], args: Optional[dict]):
        self._rec, self._name = rec, name
        self._track, self._args = track, args

    def __enter__(self) -> "_Span":
        self._t0 = self._rec.now()
        return self

    def __exit__(self, *exc) -> None:
        rec = self._rec
        rec.add_span_rel(self._name, self._t0, rec.now() - self._t0,
                         track=self._track, args=self._args)


class TraceRecorder:
    """Ring-buffered span recorder for one process.

    ``t0`` is an absolute ``time.monotonic()`` reading that anchors the
    timeline (pass the event sink's ``t0`` so spans and JSONL events
    share an axis); all recorded timestamps are seconds since it.
    """

    enabled = True

    # event tuple layout: (ph, cat, name, track, ts_s, dur_s, id, args)
    def __init__(self, capacity: int = 65536, t0: Optional[float] = None):
        self.capacity = int(capacity)
        self._t0 = float(t0) if t0 is not None else time.monotonic()
        self._events: deque = deque(maxlen=self.capacity)
        self._appended = 0      # monotonic count; appended - len = dropped
        self._track_lock = threading.Lock()
        self._tracks: Dict[str, int] = {}
        self._pid = os.getpid()

    # ------------------------------------------------------------ clock
    @property
    def t0(self) -> float:
        return self._t0

    def now(self) -> float:
        """Seconds since ``t0`` (monotonic)."""
        return time.monotonic() - self._t0

    # ----------------------------------------------------------- tracks
    def _tid(self, track: Optional[str]) -> int:
        name = track if track is not None else threading.current_thread().name
        tid = self._tracks.get(name)
        if tid is None:
            with self._track_lock:
                tid = self._tracks.setdefault(name, len(self._tracks) + 1)
        return tid

    # ---------------------------------------------------------- records
    def _put(self, ev) -> None:
        # deque.append is atomic under the GIL; the += is bookkeeping
        # only (approximate under a race, never load-bearing)
        self._events.append(ev)
        self._appended += 1

    def span(self, name: str, track: Optional[str] = None,
             args: Optional[dict] = None) -> _Span:
        return _Span(self, name, track, args)

    def add_span_rel(self, name: str, ts: float, dur: float,
                     track: Optional[str] = None,
                     args: Optional[dict] = None) -> None:
        """One complete span at ``ts`` seconds since ``t0`` (what
        :meth:`now` returns) lasting ``dur`` seconds."""
        self._put(("X", None, name, self._tid(track), ts, max(dur, 0.0),
                   None, args))

    def add_span_abs(self, name: str, t_mono: float, dur: float,
                     track: Optional[str] = None,
                     args: Optional[dict] = None) -> None:
        """One complete span whose start is an ABSOLUTE
        ``time.monotonic()`` reading — possibly taken in another process
        (the shm-ring workers' render stamps ride the done-queue token)."""
        self.add_span_rel(name, t_mono - self._t0, dur, track=track,
                          args=args)

    def instant(self, name: str, track: Optional[str] = None,
                args: Optional[dict] = None) -> None:
        self._put(("i", None, name, self._tid(track), self.now(), None,
                   None, args))

    def async_begin(self, name: str, id: int, cat: str = "async",
                    args: Optional[dict] = None) -> None:
        """Async span begin (Perfetto groups b/e pairs by cat+id onto
        their own track — overlapping lifetimes render side by side)."""
        self._put(("b", cat, name, self._tid(None), self.now(), None,
                   int(id), args))

    def async_end(self, name: str, id: int, cat: str = "async",
                  args: Optional[dict] = None) -> None:
        self._put(("e", cat, name, self._tid(None), self.now(), None,
                   int(id), args))

    def flow_start(self, name: str, id: int, track: Optional[str] = None,
                   ts: Optional[float] = None, cat: str = "flow") -> None:
        """Start a flow arrow (binds to the slice enclosing ``ts`` on the
        recording track).  ``cat`` namespaces the id: flows bind by
        (cat, id), so independent id counters (the batcher's rids, the
        reqtrace request ids) must not share one category."""
        self._put(("s", cat, name, self._tid(track),
                   self.now() if ts is None else ts, None, int(id), None))

    def flow_step(self, name: str, id: int, track: Optional[str] = None,
                  ts: Optional[float] = None, cat: str = "flow") -> None:
        """Intermediate flow point ("t" phase): the arrow threads
        through the slice enclosing ``ts`` — what makes a multi-hop
        request ONE followable arc across engine tracks."""
        self._put(("t", cat, name, self._tid(track),
                   self.now() if ts is None else ts, None, int(id), None))

    def flow_finish(self, name: str, id: int, track: Optional[str] = None,
                    ts: Optional[float] = None, cat: str = "flow") -> None:
        self._put(("f", cat, name, self._tid(track),
                   self.now() if ts is None else ts, None, int(id), None))

    def attach_registry(self, registry) -> None:
        """Expose the ring's drop accounting on a shared
        ``obs.Registry`` (weakref collector): a lossy trace previously
        only stamped its drops into the export's ``otherData`` — a
        consumer watching ``/metrics`` could mistake a truncated
        timeline for a complete one.  ``trace_spans_dropped_total``
        growing during a run is the live signal to raise ``capacity``
        (or accept the loss knowingly)."""
        import weakref

        ref = weakref.ref(self)

        def _collect():
            t = ref()
            if t is None:
                return []
            return [
                ("trace_spans_dropped_total", {}, "counter",
                 float(t.dropped)),
                ("trace_spans_recorded", {}, "gauge", float(t.recorded)),
            ]

        registry.register_collector(_collect)

    # ----------------------------------------------------------- export
    @property
    def recorded(self) -> int:
        """Events currently in the ring (cheap — no serialization)."""
        return len(self._events)

    @property
    def dropped(self) -> int:
        return max(0, self._appended - len(self._events))

    def events(self) -> List[dict]:
        """The ring's events in Chrome ``trace_event`` dict form,
        parent-before-child ordered (ts ascending, longer span first on
        ties so nesting resolves)."""
        out = []
        for ph, cat, name, tid, ts, dur, id_, args in list(self._events):
            ev = {"name": name, "ph": ph, "ts": round(ts * 1e6, 3),
                  "pid": self._pid, "tid": tid}
            ev["cat"] = cat if cat is not None else "span"
            if dur is not None:
                ev["dur"] = round(dur * 1e6, 3)
            if id_ is not None:
                ev["id"] = id_
            if ph == "f":
                ev["bp"] = "e"  # bind the arrowhead to the enclosing slice
            if ph == "i":
                ev["s"] = "t"   # thread-scoped instant
            if args:
                ev["args"] = dict(args)
            out.append(ev)
        out.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        return out

    def export(self) -> dict:
        """Chrome trace JSON object (loads in Perfetto / chrome://tracing)."""
        with self._track_lock:
            tracks = dict(self._tracks)
        meta = [{"name": "process_name", "ph": "M", "pid": self._pid,
                 "tid": 0, "args": {"name": "improved_body_parts_tpu"}}]
        for name, tid in sorted(tracks.items(), key=lambda kv: kv[1]):
            meta.append({"name": "thread_name", "ph": "M", "pid": self._pid,
                         "tid": tid, "args": {"name": name}})
        return {
            "traceEvents": meta + self.events(),
            "displayTimeUnit": "ms",
            # t0_mono: the absolute CLOCK_MONOTONIC anchor — what lets
            # tools/trace_report.py rebase a worker process's export
            # onto the parent's axis ((t0_shard - t0_parent) µs shift)
            # and stitch the fleet into ONE timeline
            "otherData": {"dropped_events": self.dropped,
                          "capacity": self.capacity,
                          "t0_mono": self._t0},
        }

    def save(self, path: str) -> str:
        """Write the export to ``path``; returns the absolute path."""
        path = os.path.abspath(path)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        from .events import strict_dump

        with open(path, "w") as f:
            # span args carry run floats (losses) — strict emission so a
            # diverged run's trace stays loadable (graftlint JGL004)
            strict_dump(self.export(), f)
        return path


_tracer_lock = threading.Lock()
_tracer = NullTraceRecorder()


def get_tracer():
    """The process's current recorder (``NullTraceRecorder`` when no run
    installed one) — instrumentation sites record through this
    unconditionally."""
    return _tracer


def set_tracer(tracer):
    """Install ``tracer`` as the process default; returns the previous
    one so callers can restore it (``RunTelemetry`` does)."""
    global _tracer
    with _tracer_lock:
        prev = _tracer
        _tracer = tracer if tracer is not None else NullTraceRecorder()
        return prev
