"""Fleet observability plane: worker-process telemetry over the shm
wire, merged into ONE parent-side exposition surface.

PR 16 promoted serving replicas to real worker processes, which made
the obs stack (registry / ``/metrics`` / ``/slo`` / Perfetto / reqtrace)
process-local: the parent saw only the router side, while each
``serve/worker.py`` child was a telemetry black hole — a bare event-sink
shard plus four heartbeat floats.  This module closes the boundary in
both directions without any new IPC channel:

- **Worker side** (:class:`WorkerTelemetry`): each worker process runs
  its own ``Registry`` + ``CompileWatch`` + bounded ``TraceRecorder``
  ring + ``DeviceMemory`` gauges and measures the device/decode hops,
  batch occupancy and served/failed counters *in the process that pays
  them*.  Snapshots are published through an **extended heartbeat
  region** at the tail of the existing shared-memory wire: a
  fixed-shape float64 block (:data:`TELEM_FLOATS` wide, versioned by
  :data:`TELEM_VERSION`) written under the same seqlock parity
  discipline the slot rows use — no pickling, no queues, readable at
  any moment by the parent.  The PR 16 4-float heartbeat survives
  unchanged as the degenerate case (telemetry off → only the heartbeat
  block moves).
- **Parent side** (:class:`FleetRegistry`): merges every worker's
  snapshot block into the router's registry **at scrape time** under
  ``worker=``/``pid=`` labels, so one ``MetricsServer`` serves
  fleet-wide ``/metrics``, ``/snapshot``, ``/slo`` and the new
  ``/fleet`` route (per-worker liveness, respawn/crash-budget counters,
  heartbeat staleness).  A cross-process conservation check compares
  router-view submitted against Σ worker-view served + in-flight.
- **Flight recorder**: the worker mirrors its last-N request milestones
  into a crash-persistent shm ring (:data:`REC_SLOTS` × fixed-width
  records).  When the supervisor detects a dead worker — including
  SIGKILL, where no user code gets to run — the router exhumes the
  ring (:func:`read_flight_records`) and emits a ``worker_postmortem``
  naming the in-flight slot/seq, the last completed hop and the last
  recorded milestones (:func:`build_postmortem`,
  :func:`verify_postmortem`).

Staleness discipline: a worker whose telemetry block was never
published (version word still 0 — spawn zeroes the region) exports
ONLY liveness/staleness families, never fresh zeros; a published block
older than the staleness threshold exports its last-known values plus
a ``fleet_worker_stale`` marker.  Timestamps are ``time.perf_counter``
(CLOCK_MONOTONIC — system-wide on Linux, the ``serve/worker.py``
wire-stamp precedent), so heartbeat age is directly comparable across
the process boundary.

Double-count hazard (the §7g contract): the worker-side hop reservoirs
exported here are a *second view* of the same requests the router's
``ServeMetrics.on_hops`` already feeds from wire stamps.  Exactly ONE
of the two may feed the SLO tracker — the router's, which sees the
full submit→deliver window; the fleet families exist for attribution
(is the device hop slow *inside* worker 1?), not for objectives.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ..utils.meters import PercentileMeter
from .events import NullSink
from .registry import Registry
from .trace import NullTraceRecorder, TraceRecorder

# --------------------------------------------------------------------- #
# telemetry block layout (float64 indices)                              #
# --------------------------------------------------------------------- #
#: layout version stamped into every published block; the parent
#: refuses to decode an unknown version (same build normally — spawn,
#: not network peers — so this is a debugging aid like WIRE_VERSION)
TELEM_VERSION = 1

T_PARITY = 0        # seqlock word: odd while the worker writes
T_VERSION = 1       # TELEM_VERSION; 0 = never published
T_STAMP = 2         # perf_counter at publish (CLOCK_MONOTONIC)
T_PID = 3
T_SERVED = 4        # requests served, any status (ok+error+expired)
T_OK = 5
T_ERRORS = 6
T_EXPIRED = 7
T_COMPILES = 8      # worker-process XLA compiles (CompileWatch)
T_RECOMPILES = 9    # post-warmup recompiles
T_COMPILE_S = 10    # wall seconds spent compiling
T_BURSTS = 11       # token bursts drained back-to-back (occupancy)
T_BURST_REQS = 12   # requests across those bursts
T_DEV_BYTES = 13    # device bytes_in_use (0 on statless backends)
T_DEV_PEAK = 14
T_SPANS_RECORDED = 15
T_SPANS_DROPPED = 16
T_HOP0 = 17         # per-hop summary block starts here

#: hops measured IN the worker process (the router's on_hops sees the
#: same requests from wire stamps; see the double-count hazard above)
WORKER_HOPS = ("device", "decode")
#: per-hop summary fields published in the block, seconds
HOP_FIELDS = ("count", "sum_s", "p50_s", "p95_s", "p99_s")

#: block width: 17 fixed + len(WORKER_HOPS)*len(HOP_FIELDS) = 27 used,
#: the rest headroom for the next layout version
TELEM_FLOATS = 32
assert T_HOP0 + len(WORKER_HOPS) * len(HOP_FIELDS) <= TELEM_FLOATS

# --------------------------------------------------------------------- #
# flight-recorder ring layout                                           #
# --------------------------------------------------------------------- #
#: ring header: [parity, total records written, ring slots, record width]
REC_HEADER = 4
REC_SLOTS = 32
REC_WIDTH = 6       # [code, t_mono, slot, seq, a, b]
REC_FLOATS = REC_HEADER + REC_SLOTS * REC_WIDTH

#: record codes — request milestones double as "last completed hop"
REC_PICKUP = 1.0    # token picked up (queue hop done); a=deadline_abs
REC_EXEC_DONE = 2.0  # predictor returned (device hop done)
REC_DONE = 3.0      # response written + token sent (decode done); a=status
REC_BEAT = 4.0      # idle heartbeat tick
REC_WARMUP = 5.0    # warmup handled; a=1 ok / 0 failed

REC_NAMES = {1: "pickup", 2: "exec_done", 3: "done", 4: "beat",
             5: "warmup"}
#: milestone → the last serve hop that COMPLETED before it was written
REC_LAST_HOP = {1: "queue", 2: "device", 3: "decode"}


def flow_id(worker: int, slot: int, seq: int) -> int:
    """Stable Perfetto flow-arc id for one request crossing the process
    boundary — router submit, worker serve and router deliver all stamp
    the same ``(cat="proc", id)`` so the three slices join as one arc."""
    return (((worker + 1) << 44) ^ ((slot & 0xFFF) << 32)
            ^ (seq & 0xFFFFFFFF))


# --------------------------------------------------------------------- #
# seqlock-consistent block reads                                        #
# --------------------------------------------------------------------- #
def read_block(view, retries: int = 64) -> Optional[np.ndarray]:
    """Consistent copy of a parity-worded float64 block (index 0 is the
    seqlock word: odd while the writer mutates).  Bounded retries; a
    persistently torn block — writer died mid-write, or rewriting
    faster than we can copy — returns ``None``."""
    for _ in range(retries):
        p0 = float(view[0])
        if p0 % 2.0 != 0.0:
            continue
        arr = np.array(view, dtype=np.float64)   # copy
        if float(view[0]) == p0 and float(arr[0]) == p0:
            return arr
    return None


def decode_telem(arr: Optional[np.ndarray],
                 staleness_s: float = 5.0,
                 now: Optional[float] = None) -> dict:
    """Decode one telemetry block copy into a JSON-ready dict.

    ``arr=None`` (torn read) and a never-published block (version word
    0) both come back ``{"published": False, ...}`` — the caller must
    not export their zeros as fresh samples."""
    if arr is None:
        return {"published": False, "torn": True}
    version = int(arr[T_VERSION])
    if version == 0:
        return {"published": False, "torn": False}
    if version != TELEM_VERSION:
        return {"published": False, "torn": False,
                "version_mismatch": version}
    now = time.perf_counter() if now is None else now
    age = max(0.0, now - float(arr[T_STAMP]))
    bursts = float(arr[T_BURSTS])
    hops = {}
    for i, hop in enumerate(WORKER_HOPS):
        off = T_HOP0 + i * len(HOP_FIELDS)
        hops[hop] = {f: float(arr[off + j])
                     for j, f in enumerate(HOP_FIELDS)}
    return {
        "published": True,
        "torn": False,
        "version": version,
        "stamp": float(arr[T_STAMP]),
        "age_s": round(age, 3),
        "stale": bool(age > staleness_s),
        "pid": int(arr[T_PID]),
        "served": int(arr[T_SERVED]),
        "ok": int(arr[T_OK]),
        "errors": int(arr[T_ERRORS]),
        "expired": int(arr[T_EXPIRED]),
        "compiles": int(arr[T_COMPILES]),
        "recompiles_post_warmup": int(arr[T_RECOMPILES]),
        "compile_seconds": float(arr[T_COMPILE_S]),
        "bursts": int(bursts),
        "burst_requests": int(arr[T_BURST_REQS]),
        "batch_occupancy_mean": (float(arr[T_BURST_REQS]) / bursts
                                 if bursts else 0.0),
        "device_bytes_in_use": int(arr[T_DEV_BYTES]),
        "device_peak_bytes": int(arr[T_DEV_PEAK]),
        "trace_spans_recorded": int(arr[T_SPANS_RECORDED]),
        "trace_spans_dropped": int(arr[T_SPANS_DROPPED]),
        "hops": hops,
    }


def read_flight_records(view) -> dict:
    """Exhume the flight-recorder ring — tolerant by design: a SIGKILL
    mid-write leaves the parity word odd forever, so after the bounded
    consistent-read attempts fail we take a best-effort copy and flag
    it ``torn`` instead of refusing (a postmortem with one possibly-
    garbled record beats no postmortem)."""
    arr = read_block(view, retries=8)
    torn = arr is None
    if torn:
        arr = np.array(view, dtype=np.float64)
    count = int(max(0.0, arr[1]))
    slots = int(arr[2]) or REC_SLOTS
    width = int(arr[3]) or REC_WIDTH
    records: List[dict] = []
    if 0 < slots <= REC_SLOTS and width == REC_WIDTH:
        for w in range(max(0, count - slots), count):
            base = REC_HEADER + (w % slots) * width
            code = int(arr[base])
            if code not in REC_NAMES:
                continue            # unwritten or garbled slot
            records.append({
                "code": code,
                "kind": REC_NAMES[code],
                "t_mono": float(arr[base + 1]),
                "slot": int(arr[base + 2]),
                "seq": int(arr[base + 3]),
                "a": float(arr[base + 4]),
                "b": float(arr[base + 5]),
            })
    return {"records": records, "count": count, "torn": torn}


# --------------------------------------------------------------------- #
# worker-side publisher                                                 #
# --------------------------------------------------------------------- #
class WorkerTelemetry:
    """The worker process's own obs stack + shm publisher.

    Owns a private :class:`Registry` (this process's families never
    collide with the parent's), a :class:`CompileWatch` armed in the
    process that actually compiles, a bounded :class:`TraceRecorder`
    ring and a :class:`DeviceMemory` sampler.  :meth:`publish` writes
    the whole snapshot into the telemetry block under seqlock parity;
    :meth:`record` appends one milestone to the flight-recorder ring.

    ``enabled=False`` is the explicit OFF arm of the overhead A/B
    (``tools/fleet_audit.py``): the trace recorder is the null one,
    hop meters / flight records / publishes are skipped, and only the
    PR 16 4-float heartbeat keeps moving — the degenerate case, chosen
    deliberately so the A/B never degenerates to A/A.
    """

    def __init__(self, worker_idx: int, telem=None, rec=None, *,
                 enabled: bool = True, sink=None,
                 trace_capacity: int = 8192,
                 trace_t0: Optional[float] = None,
                 publish_min_interval_s: float = 0.05):
        self.worker_idx = int(worker_idx)
        self.enabled = bool(enabled)
        self._telem = telem
        self._rec = rec
        self._sink = sink if sink is not None else NullSink()
        self.registry = Registry()
        from .recompile import CompileWatch

        self.watch = CompileWatch(registry=self.registry,
                                  sink=self._sink).install()
        if self.enabled:
            self.trace: object = TraceRecorder(capacity=trace_capacity,
                                               t0=trace_t0)
        else:
            self.trace = NullTraceRecorder()
        from .memory import DeviceMemory

        self.memory = DeviceMemory(registry=self.registry,
                                   sink=NullSink())
        self.hops: Dict[str, PercentileMeter] = {
            h: PercentileMeter(capacity=1024, seed=worker_idx)
            for h in WORKER_HOPS}
        self.served = 0
        self.ok = 0
        self.errors = 0
        self.expired = 0
        self.bursts = 0
        self.burst_reqs = 0
        self._dev_bytes = 0.0
        self._dev_peak = 0.0
        self._last_publish = 0.0
        self._publish_min = float(publish_min_interval_s)
        import os

        self._pid = os.getpid()
        self._rec_count = 0
        if self.enabled and rec is not None:
            # stamp the ring geometry so an exhumer never guesses it
            rec[0] += 1
            rec[2] = float(REC_SLOTS)
            rec[3] = float(REC_WIDTH)
            rec[0] += 1

    # ------------------------------------------------------------ inputs
    def count_status(self, status_ok: bool, expired: bool = False) -> None:
        self.served += 1
        if expired:
            self.expired += 1
        elif status_ok:
            self.ok += 1
        else:
            self.errors += 1

    def observe_hops(self, device_s: float, decode_s: float) -> None:
        if not self.enabled:
            return
        self.hops["device"].update(max(0.0, float(device_s)))
        self.hops["decode"].update(max(0.0, float(decode_s)))

    def on_burst(self, n: int) -> None:
        if n > 0:
            self.bursts += 1
            self.burst_reqs += int(n)

    def sample_memory(self) -> None:
        """Device allocator stats — idle-tick cadence only (walking
        ``jax.devices()`` per request would be real overhead; statless
        backends no-op)."""
        if not self.enabled:
            return
        per_dev = self.memory.sample()
        if per_dev:
            self._dev_bytes = float(sum(d["bytes_in_use"]
                                        for d in per_dev.values()))
            self._dev_peak = float(sum(d["peak_bytes"]
                                       for d in per_dev.values()))

    # --------------------------------------------------------- flight ring
    def record(self, code: float, slot: int = 0, seq: int = 0,
               a: float = 0.0, b: float = 0.0) -> None:
        """Append one milestone under the ring's seqlock parity.  Cheap
        enough for the hot path: seven float stores."""
        rec = self._rec
        if rec is None or not self.enabled:
            return
        base = REC_HEADER + (self._rec_count % REC_SLOTS) * REC_WIDTH
        rec[0] += 1                    # odd: writing
        rec[base] = float(code)
        rec[base + 1] = time.perf_counter()
        rec[base + 2] = float(slot)
        rec[base + 3] = float(seq)
        rec[base + 4] = float(a)
        rec[base + 5] = float(b)
        self._rec_count += 1
        rec[1] = float(self._rec_count)
        rec[0] += 1                    # even: consistent

    # ------------------------------------------------------------ publish
    def publish(self, force: bool = False) -> bool:
        """Write the snapshot block under seqlock parity.

        Split hot/cold: the counters are ~20 float stores and publish
        on EVERY call (so a quiescent parent always reads current
        served/ok/error counts — the conservation check's input); the
        per-hop quantile summaries sort the reservoirs, so they
        refresh at most once per ``publish_min_interval_s`` unless
        forced."""
        telem = self._telem
        if telem is None or not self.enabled:
            return False
        now = time.perf_counter()
        do_hops = force or now - self._last_publish >= self._publish_min
        telem[T_PARITY] += 1           # odd: writing
        telem[T_VERSION] = float(TELEM_VERSION)
        telem[T_STAMP] = now
        telem[T_PID] = float(self._pid)
        telem[T_SERVED] = float(self.served)
        telem[T_OK] = float(self.ok)
        telem[T_ERRORS] = float(self.errors)
        telem[T_EXPIRED] = float(self.expired)
        telem[T_COMPILES] = float(self.watch.compiles.value)
        telem[T_RECOMPILES] = float(self.watch.recompiles.value)
        telem[T_COMPILE_S] = float(self.watch.compile_seconds.value)
        telem[T_BURSTS] = float(self.bursts)
        telem[T_BURST_REQS] = float(self.burst_reqs)
        telem[T_DEV_BYTES] = self._dev_bytes
        telem[T_DEV_PEAK] = self._dev_peak
        telem[T_SPANS_RECORDED] = float(self.trace.recorded)
        telem[T_SPANS_DROPPED] = float(self.trace.dropped)
        if do_hops:
            self._last_publish = now
            for i, hop in enumerate(WORKER_HOPS):
                m = self.hops[hop]
                s = m.summary()
                off = T_HOP0 + i * len(HOP_FIELDS)
                telem[off] = float(s["count"])
                telem[off + 1] = float(m.sum)
                telem[off + 2] = float(s["p50"])
                telem[off + 3] = float(s["p95"])
                telem[off + 4] = float(s["p99"])
        telem[T_PARITY] += 1           # even: consistent
        return True

    def flush_trace(self, path: Optional[str]) -> Optional[str]:
        """Write the worker's trace ring to its per-worker span file —
        same-axis stitching happens in ``tools/trace_report.py``."""
        if path and self.enabled and getattr(self.trace, "enabled", False):
            try:
                return self.trace.save(path)
            except Exception:  # noqa: BLE001 — a full disk must not
                return None    # kill the serve loop
        return None


# --------------------------------------------------------------------- #
# postmortem                                                            #
# --------------------------------------------------------------------- #
def build_postmortem(worker_idx: int, pid: Optional[int],
                     exitcode: Optional[int],
                     flight: dict,
                     in_flight: Iterable[Tuple[int, int]]) -> dict:
    """Assemble the ``worker_postmortem`` record from an exhumed ring
    plus the router's in-flight ledger.  Each in-flight ``(slot, seq)``
    is matched against the ring newest-first: the newest milestone for
    that request names the last hop it completed before the process
    died (``None`` = the worker never picked it up)."""
    records = list(flight.get("records", []))
    inflight_out = []
    for slot, seq in in_flight:
        last_hop = None
        last_kind = None
        for r in reversed(records):
            if r["slot"] == int(slot) and r["seq"] == int(seq) \
                    and r["code"] in REC_LAST_HOP:
                last_hop = REC_LAST_HOP[r["code"]]
                last_kind = r["kind"]
                break
        inflight_out.append({"slot": int(slot), "seq": int(seq),
                             "last_completed_hop": last_hop,
                             "last_milestone": last_kind})
    overall = None
    for r in reversed(records):
        if r["code"] in REC_LAST_HOP:
            overall = REC_LAST_HOP[r["code"]]
            break
    return {
        "worker": int(worker_idx),
        "pid": pid,
        "exitcode": exitcode,
        "torn": bool(flight.get("torn", False)),
        "records_written": int(flight.get("count", 0)),
        "in_flight": inflight_out,
        "last_completed_hop": overall,
        "last_records": records[-10:],
    }


def verify_postmortem(pm: dict, require_in_flight: bool = True
                      ) -> Tuple[bool, List[str]]:
    """Structural verifier for a ``worker_postmortem`` record — the
    chaos harness's assertion that the exhumed ring actually identifies
    the killed batch, not merely that a dict exists."""
    problems: List[str] = []
    if not isinstance(pm, dict):
        return False, ["postmortem is not a dict"]
    if not isinstance(pm.get("worker"), int):
        problems.append("missing integer 'worker'")
    if "exitcode" not in pm:
        problems.append("missing 'exitcode'")
    recs = pm.get("last_records")
    if not isinstance(recs, list):
        problems.append("missing 'last_records' list")
        recs = []
    for r in recs:
        if not (isinstance(r, dict) and r.get("code") in REC_NAMES
                and isinstance(r.get("t_mono"), float)):
            problems.append(f"malformed record: {r!r}")
            break
    inflight = pm.get("in_flight")
    if not isinstance(inflight, list):
        problems.append("missing 'in_flight' list")
        inflight = []
    for e in inflight:
        if not (isinstance(e, dict) and isinstance(e.get("slot"), int)
                and e.get("slot") >= 0 and isinstance(e.get("seq"), int)
                and e.get("seq") > 0):
            problems.append(f"in-flight entry lacks slot/seq: {e!r}")
            break
    hops = set(REC_LAST_HOP.values()) | {None}
    if pm.get("last_completed_hop") not in hops:
        problems.append(
            f"last_completed_hop {pm.get('last_completed_hop')!r} is "
            f"not a known hop")
    if require_in_flight:
        if not inflight:
            problems.append("no in-flight slot/seq named (the killed "
                            "batch is unidentified)")
        elif not any(e.get("last_completed_hop") for e in inflight):
            problems.append("no in-flight request matched a recorded "
                            "milestone — the ring does not identify "
                            "the killed batch")
    return not problems, problems


# --------------------------------------------------------------------- #
# parent-side merge                                                     #
# --------------------------------------------------------------------- #
_Q = (("0.5", "p50_s"), ("0.95", "p95_s"), ("0.99", "p99_s"))


class FleetRegistry:
    """Scrape-time merge of worker snapshot blocks into one registry.

    Workers register as ``(idx, telem_fn, info_fn)``: ``telem_fn``
    returns a consistent block copy (or ``None``), ``info_fn`` the
    router-side view (liveness, crash budget, in-flight, submitted).
    Nothing is cached — every scrape reads the live shm blocks, so a
    merge-under-rewrite is torn-read-safe purely through the seqlock
    (hammered by the tier-1 suite).
    """

    def __init__(self, staleness_s: float = 5.0):
        self.staleness_s = float(staleness_s)
        self._lock = threading.Lock()
        self._workers: List[Tuple[int, Callable, Callable]] = []

    def add_worker(self, idx: int, telem_fn: Callable[[], object],
                   info_fn: Callable[[], dict]) -> None:
        with self._lock:
            self._workers = [w for w in self._workers if w[0] != idx]
            self._workers.append((int(idx), telem_fn, info_fn))
            self._workers.sort(key=lambda w: w[0])

    def add_engine(self, engine) -> None:
        """Convenience for ``ProcessWorkerEngine``-shaped sources."""
        self.add_worker(engine.worker_idx, engine.telem_read,
                        engine.worker_info)

    # ------------------------------------------------------------ readout
    def _rows(self) -> List[dict]:
        with self._lock:
            workers = list(self._workers)
        now = time.perf_counter()
        rows = []
        for idx, telem_fn, info_fn in workers:
            try:
                info = dict(info_fn() or {})
            except Exception:  # noqa: BLE001 — a dead engine reads as
                info = {}      # a down worker, not a scrape crash
            try:
                arr = telem_fn()
            except Exception:  # noqa: BLE001
                arr = None
            telem = decode_telem(arr, staleness_s=self.staleness_s,
                                 now=now)
            rows.append({"worker": idx, "info": info, "telemetry": telem})
        return rows

    def conservation(self, rows: Optional[List[dict]] = None) -> dict:
        """Router-view submitted vs Σ worker-view served + in-flight.

        At quiescence on a clean run the two sides are EQUAL (frac 1.0);
        worker crashes lose their in-flight served-side counts, so the
        audit gate is ≥ 0.95 over a run with chaos in it.  Falls back to
        the 4-float heartbeat's served counter for unpublished workers
        so the check stays meaningful with telemetry off."""
        rows = self._rows() if rows is None else rows
        submitted = 0
        served = 0
        in_flight = 0
        for r in rows:
            info, telem = r["info"], r["telemetry"]
            submitted += int(info.get("submitted", 0))
            in_flight += int(info.get("in_flight", 0))
            if telem.get("published"):
                served += int(telem["served"])
            else:
                served += int(info.get("hb_served", 0))
        frac = (served + in_flight) / submitted if submitted else None
        return {"router_submitted": submitted,
                "workers_served": served,
                "in_flight": in_flight,
                "frac": round(frac, 4) if frac is not None else None}

    def fleet_state(self) -> dict:
        """The ``/fleet`` route body."""
        rows = self._rows()
        out_workers = []
        for r in rows:
            info, telem = r["info"], r["telemetry"]
            out_workers.append({
                "worker": r["worker"],
                **info,
                "telemetry": telem,
            })
        return {"workers": out_workers,
                "staleness_threshold_s": self.staleness_s,
                "conservation": self.conservation(rows)}

    def health_extra(self) -> dict:
        """The ``/healthz`` fleet block (``HealthSentinel.set_extra``):
        per-worker alive/backing-off/gave-up + heartbeat staleness, and
        a non-ok status once any worker is past its crash budget — the
        sentinel escalates that to the probe's 503."""
        rows = self._rows()
        workers = []
        exhausted = []
        for r in rows:
            info, telem = r["info"], r["telemetry"]
            gave_up = bool(info.get("gave_up", False))
            if gave_up:
                exhausted.append(r["worker"])
            workers.append({
                "worker": r["worker"],
                "alive": bool(info.get("alive", False)),
                "backing_off": bool(info.get("backing_off", False)),
                "gave_up": gave_up,
                "consecutive_failures": int(
                    info.get("consecutive_failures", 0)),
                "crash_budget": int(info.get("crash_budget", 0)),
                "heartbeat_age_s": info.get("hb_age_s"),
                "stale": bool(telem.get("stale", False)),
            })
        status = ("worker_crash_budget_exhausted" if exhausted else "ok")
        return {"status": status, "workers": workers,
                "exhausted": exhausted}

    # --------------------------------------------------------- exposition
    def attach(self, registry) -> "FleetRegistry":
        """Register the scrape-time collector (weakref — a registry
        outliving its fleet scrapes no samples instead of pinning it)."""
        import weakref

        ref = weakref.ref(self)

        def _collect():
            fleet = ref()
            return fleet.samples() if fleet is not None else []

        registry.register_collector(_collect)
        return self

    def samples(self) -> List[tuple]:
        """``(name, labels, kind, value, help)`` samples for every
        worker — the registry accepts the 5-tuple collector form so
        fleet families carry HELP text like first-class metrics."""
        out: List[tuple] = []
        rows = self._rows()
        for r in rows:
            idx, info, telem = r["worker"], r["info"], r["telemetry"]
            pid = telem.get("pid") or info.get("pid")
            lab = {"worker": str(idx),
                   "pid": str(pid if pid is not None else "none")}
            up = bool(info.get("alive", False)
                      and info.get("running", False))
            out += [
                ("fleet_worker_up", lab, "gauge", float(up),
                 "1 while the worker process is alive and serving"),
                ("fleet_worker_stale", lab, "gauge",
                 float(bool(telem.get("stale", False))),
                 "1 while the worker's telemetry block is older than "
                 "the staleness threshold"),
                ("fleet_worker_heartbeat_age_seconds", lab, "gauge",
                 float(info.get("hb_age_s") or 0.0),
                 "seconds since the worker's last heartbeat stamp"),
                ("fleet_worker_restarts_total", lab, "counter",
                 float(info.get("restarts", 0)),
                 "times this worker slot was (re)spawned"),
                ("fleet_worker_gave_up", lab, "gauge",
                 float(bool(info.get("gave_up", False))),
                 "1 once the worker exhausted its crash budget"),
                ("fleet_worker_consecutive_failures", lab, "gauge",
                 float(info.get("consecutive_failures", 0)),
                 "consecutive no-progress spawns (crash-budget input)"),
                ("fleet_worker_crash_budget", lab, "gauge",
                 float(info.get("crash_budget", 0)),
                 "configured crash budget"),
                ("fleet_worker_in_flight", lab, "gauge",
                 float(info.get("in_flight", 0)),
                 "router-view requests currently pinned to this "
                 "worker's slots"),
            ]
            if not telem.get("published"):
                # never-published / torn block: liveness families only —
                # a worker that has not reported must not export fresh
                # zeros that read as 'served nothing, using no memory'
                continue
            out += [
                ("fleet_worker_served_total", lab, "counter",
                 float(telem["served"]),
                 "requests served by this worker (any status), counted "
                 "in the worker process"),
                ("fleet_worker_ok_total", lab, "counter",
                 float(telem["ok"]), "requests served OK"),
                ("fleet_worker_errors_total", lab, "counter",
                 float(telem["errors"]), "requests that errored in the "
                 "worker"),
                ("fleet_worker_expired_total", lab, "counter",
                 float(telem["expired"]),
                 "requests that expired before serving"),
                ("fleet_worker_xla_compiles_total", lab, "counter",
                 float(telem["compiles"]),
                 "XLA compiles in the worker process"),
                ("fleet_worker_xla_recompiles_post_warmup_total", lab,
                 "counter", float(telem["recompiles_post_warmup"]),
                 "post-warmup recompiles in the worker process"),
                ("fleet_worker_xla_compile_seconds_total", lab,
                 "counter", float(telem["compile_seconds"]),
                 "wall seconds the worker spent compiling"),
                ("fleet_worker_batch_bursts_total", lab, "counter",
                 float(telem["bursts"]),
                 "back-to-back token bursts drained"),
                ("fleet_worker_burst_requests_total", lab, "counter",
                 float(telem["burst_requests"]),
                 "requests across those bursts"),
                ("fleet_worker_batch_occupancy_mean", lab, "gauge",
                 float(telem["batch_occupancy_mean"]),
                 "mean requests per drained burst"),
                ("fleet_worker_device_bytes_in_use", lab, "gauge",
                 float(telem["device_bytes_in_use"]),
                 "worker-process device allocator bytes in use"),
                ("fleet_worker_device_peak_bytes", lab, "gauge",
                 float(telem["device_peak_bytes"]),
                 "worker-process device allocator peak bytes"),
                ("fleet_worker_trace_spans_recorded", lab, "gauge",
                 float(telem["trace_spans_recorded"]),
                 "spans currently in the worker's trace ring"),
                ("fleet_worker_trace_spans_dropped_total", lab,
                 "counter", float(telem["trace_spans_dropped"]),
                 "spans evicted from the worker's trace ring"),
            ]
            for hop, s in telem["hops"].items():
                hlab = {**lab, "hop": hop}
                for q, key in _Q:
                    out.append(("fleet_worker_hop_latency_seconds",
                                {**hlab, "quantile": q}, "gauge",
                                float(s[key]),
                                "per-hop latency measured in the worker "
                                "process"))
                out += [
                    ("fleet_worker_hop_latency_seconds_sum", hlab,
                     "counter", float(s["sum_s"]), ""),
                    ("fleet_worker_hop_latency_seconds_count", hlab,
                     "counter", float(s["count"]), ""),
                ]
        cons = self.conservation(rows)
        out += [
            ("fleet_router_submitted_total", {}, "counter",
             float(cons["router_submitted"]),
             "router-view requests submitted across the fleet"),
            ("fleet_workers_served_total", {}, "counter",
             float(cons["workers_served"]),
             "worker-view requests served across the fleet"),
            ("fleet_in_flight", {}, "gauge", float(cons["in_flight"]),
             "requests currently crossing the process boundary"),
        ]
        if cons["frac"] is not None:
            out.append(("fleet_conservation_frac", {}, "gauge",
                        float(cons["frac"]),
                        "(workers served + in-flight) / router "
                        "submitted — 1.0 at clean-run quiescence"))
        return out
