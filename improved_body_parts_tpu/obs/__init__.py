"""Unified telemetry subsystem.

The reference's observability is an ``AverageMeter`` and a print around
``cuda.synchronize`` (reference: train_distributed.py:285-298).  This
package replaces that with one process-wide pipeline every layer shares:

- :mod:`registry` — counters / gauges / percentile histograms / span
  timers with Prometheus + JSON exposition (``Registry``,
  ``get_registry``, ``StepPhases`` data-wait/compute attribution);
- :mod:`events`   — schema-versioned JSONL run-event sink
  (``EventSink``, ``read_events``, process-default ``set_sink``);
- :mod:`http`     — background ``/metrics`` + ``/snapshot`` endpoint
  (``MetricsServer``);
- :mod:`recompile` — post-warmup XLA recompile detection
  (``CompileWatch``);
- :mod:`run`      — the per-run bundle (``RunTelemetry``).

``tools/telemetry_report.py`` folds a run's JSONL stream into a
human-readable summary with an input-bound vs compute-bound verdict.
"""
from .events import (
    SCHEMA_VERSION,
    EventSink,
    NullSink,
    get_sink,
    read_events,
    set_sink,
)
from .http import MetricsServer
from .recompile import COMPILE_EVENT, CompileWatch
from .registry import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    StepPhases,
    get_registry,
)
from .run import RunTelemetry, resolve_sink_path

__all__ = [
    "SCHEMA_VERSION", "EventSink", "NullSink", "get_sink", "read_events",
    "set_sink", "MetricsServer", "COMPILE_EVENT", "CompileWatch",
    "Counter", "Gauge", "Histogram", "Registry", "StepPhases",
    "get_registry", "RunTelemetry", "resolve_sink_path",
]
