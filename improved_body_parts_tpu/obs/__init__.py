"""Unified telemetry subsystem.

The reference's observability is an ``AverageMeter`` and a print around
``cuda.synchronize`` (reference: train_distributed.py:285-298).  This
package replaces that with one process-wide pipeline every layer shares:

- :mod:`registry` — counters / gauges / percentile histograms / span
  timers with Prometheus + JSON exposition (``Registry``,
  ``get_registry``, ``StepPhases`` data-wait/compute attribution);
- :mod:`events`   — schema-versioned JSONL run-event sink
  (``EventSink``, ``read_events``, process-default ``set_sink``);
- :mod:`trace`    — lock-cheap ring-buffered span recorder with
  Chrome/Perfetto ``trace_event`` export (``TraceRecorder``,
  process-default ``set_tracer``);
- :mod:`http`     — background ``/metrics`` + ``/snapshot`` +
  ``/healthz`` endpoint (``MetricsServer``);
- :mod:`recompile` — post-warmup XLA recompile detection
  (``CompileWatch``);
- :mod:`memory`   — per-device HBM gauges, run watermark and OOM
  forensics (``DeviceMemory``);
- :mod:`health`   — loss/grad-norm divergence sentinel with a
  configurable ``warn|halt|skip_step`` policy (``HealthSentinel``,
  ``DivergenceError``);
- :mod:`reqtrace` — request-scoped causal tracing across the multi-hop
  serve stack: per-request trees with reason-annotated hop edges and
  per-hop waterfalls (``ReqTrace``, process-default ``set_reqtrace``;
  reporter: ``tools/request_report.py``);
- :mod:`slo`      — declarative per-QoS-class latency/availability
  objectives, multi-window burn rate, error budgets and alarm events
  (``SLOTracker``, ``Objective``; served at ``/slo``);
- :mod:`fleet`    — cross-process fleet observability over the shm
  wire: worker-side telemetry publisher (``WorkerTelemetry``), the
  parent-side merge registry (``FleetRegistry``; served at ``/fleet``)
  and the crash flight recorder (``build_postmortem``,
  ``verify_postmortem``);
- :mod:`history`  — bounded multi-resolution telemetry history with
  explicit gap accounting, strict-JSON shard persistence, offline
  replay and the derived control-plane signal feed (``HistoryStore``;
  served at ``/history`` + ``/query``; fitted into replica counts by
  ``serve.capacity.CapacityModel``);
- :mod:`run`      — the per-run bundle (``RunTelemetry``).

``tools/telemetry_report.py`` folds a run's JSONL stream into a
human-readable summary with an input-bound vs compute-bound verdict;
``tools/trace_report.py`` turns its span trace into a
``.perfetto.json`` plus a text critical-path summary.
"""
from .events import (
    SCHEMA_VERSION,
    EventSink,
    NullSink,
    get_sink,
    read_events,
    set_sink,
)
from .fleet import (
    TELEM_VERSION,
    FleetRegistry,
    WorkerTelemetry,
    build_postmortem,
    decode_telem,
    flow_id,
    read_block,
    read_flight_records,
    verify_postmortem,
)
from .health import POLICIES, DivergenceError, HealthSentinel
from .history import (
    HISTORY_SCHEMA,
    HistoryStore,
    discover_history_shards,
    history_path_for,
    series_key,
)
from .http import ROUTES, MetricsServer
from .memory import DeviceMemory
from .recompile import COMPILE_EVENT, CompileWatch
from .registry import (
    INPUT_BOUND_FRAC,
    Counter,
    Gauge,
    Histogram,
    Registry,
    StepPhases,
    get_registry,
)
from .reqtrace import (
    NullReqTrace,
    ReqTrace,
    get_reqtrace,
    set_reqtrace,
)
from .run import RunTelemetry, resolve_sink_path
from .slo import Objective, SLOTracker, default_objectives
from .trace import (
    NullTraceRecorder,
    TraceRecorder,
    get_tracer,
    set_tracer,
)

__all__ = [
    "SCHEMA_VERSION", "EventSink", "NullSink", "get_sink", "read_events",
    "set_sink", "MetricsServer", "COMPILE_EVENT", "CompileWatch",
    "Counter", "Gauge", "Histogram", "Registry", "StepPhases",
    "get_registry", "RunTelemetry", "resolve_sink_path",
    "POLICIES", "DivergenceError", "HealthSentinel", "DeviceMemory",
    "NullTraceRecorder", "TraceRecorder", "get_tracer", "set_tracer",
    "INPUT_BOUND_FRAC", "NullReqTrace", "ReqTrace", "get_reqtrace",
    "set_reqtrace", "Objective", "SLOTracker", "default_objectives",
    "TELEM_VERSION", "FleetRegistry", "WorkerTelemetry",
    "build_postmortem", "decode_telem", "flow_id", "read_block",
    "read_flight_records", "verify_postmortem",
    "HISTORY_SCHEMA", "HistoryStore", "ROUTES",
    "discover_history_shards", "history_path_for", "series_key",
]
