"""Background metrics endpoint: ``/metrics`` + ``/snapshot`` +
``/healthz`` + ``/slo``.

A daemon-threaded ``ThreadingHTTPServer`` over one :class:`Registry`:

- ``GET /metrics``  → Prometheus text exposition 0.0.4 (scrapeable by a
  stock Prometheus/victoria agent);
- ``GET /snapshot`` → the registry's JSON snapshot, plus any
  caller-supplied ``extra`` dict (e.g. the run's event-sink path);
- ``GET /healthz``  → the run-health state from the caller-supplied
  ``health`` callable (``obs.health.HealthSentinel.state``): HTTP 200
  with ``{"status": "ok", ...}`` while healthy, 503 once the latest
  window diverged — the contract a stock load-balancer / liveness probe
  expects.  Without a health source the route answers 200/"ok" (the
  endpoint being up is the only health there is);
- ``GET /slo``      → the SLO/error-budget document from the
  caller-supplied ``slo`` callable (``obs.slo.SLOTracker.state``):
  per-class burn rates, budget remaining and alarm level — what the
  autoscaler / deploy gate polls.  HTTP 200 while every class is
  within budget, 503 while any alarm fires (so a dumb threshold-less
  consumer can gate on status alone); 404 when no tracker was wired;
- ``GET /fleet``    → the per-worker fleet document from the
  caller-supplied ``fleet`` callable
  (``obs.fleet.FleetRegistry.fleet_state``): per-worker liveness,
  respawn/crash-budget counters, telemetry staleness age and the
  cross-process conservation block.  404 when no fleet was wired.

``HEAD`` is answered for every route with the same status and headers
and no body — LB probes default to HEAD, and an unanswered method must
not read as an unhealthy backend.

Port 0 binds an ephemeral port (read it back from ``.port`` / ``.url``);
the listener binds loopback by default — operators who want it exposed
front it with whatever ingress their deployment already has.  Serving is
scrape-time-only work: nothing is computed until a request arrives, so
an idle endpoint costs one parked thread.
"""
from __future__ import annotations

import json
import threading
from typing import Callable, Optional

from .events import _definan
from .registry import Registry

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    def __init__(self, registry: Registry, port: int = 0,
                 host: str = "127.0.0.1",
                 extra: Optional[Callable[[], dict]] = None,
                 health: Optional[Callable[[], dict]] = None,
                 slo: Optional[Callable[[], dict]] = None,
                 fleet: Optional[Callable[[], dict]] = None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        reg = registry
        extra_fn = extra
        health_fn = health
        slo_fn = slo
        fleet_fn = fleet

        class Handler(BaseHTTPRequestHandler):
            def _handle(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        code = 200
                        body = reg.prometheus().encode()
                        ctype = PROMETHEUS_CONTENT_TYPE
                    elif path == "/snapshot":
                        snap = {"metrics": reg.snapshot()}
                        if extra_fn is not None:
                            snap.update(extra_fn())
                        code = 200
                        # an empty histogram's quantiles are real NaNs;
                        # _definan keeps the body strict JSON (JGL004)
                        body = json.dumps(_definan(snap), indent=2,
                                          default=str).encode()
                        ctype = "application/json"
                    elif path == "/healthz":
                        state = (dict(health_fn()) if health_fn is not None
                                 else {"status": "ok"})
                        code = 200 if state.get("status", "ok") == "ok" \
                            else 503
                        # the diverged body carries the NaN loss itself
                        body = json.dumps(_definan(state), indent=2,
                                          default=str).encode()
                        ctype = "application/json"
                    elif path == "/slo":
                        if slo_fn is None:
                            self.send_error(
                                404, "no SLO tracker wired on this "
                                     "endpoint")
                            return
                        state = dict(slo_fn())
                        code = 200 if state.get("status", "ok") != \
                            "alarm" else 503
                        body = json.dumps(_definan(state), indent=2,
                                          default=str).encode()
                        ctype = "application/json"
                    elif path == "/fleet":
                        if fleet_fn is None:
                            self.send_error(
                                404, "no fleet source wired on this "
                                     "endpoint")
                            return
                        code = 200
                        body = json.dumps(_definan(dict(fleet_fn())),
                                          indent=2, default=str).encode()
                        ctype = "application/json"
                    else:
                        # send_error handles HEAD itself (headers, no body)
                        self.send_error(
                            404, "use /metrics, /snapshot, /healthz, "
                                 "/slo or /fleet")
                        return
                except Exception as e:  # noqa: BLE001 — a scrape bug
                    # must 500, not kill the handler thread silently
                    self.send_error(500, type(e).__name__)
                    return
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                self._handle()

            def do_HEAD(self):  # noqa: N802 — LB probes default to HEAD
                self._handle()

            def log_message(self, *args):  # scrapes are not stdout news
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = int(self._server.server_address[1])
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="obs-metrics-http",
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
