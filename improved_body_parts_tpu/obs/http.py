"""Background metrics endpoint over one :class:`Registry`.

A daemon-threaded ``ThreadingHTTPServer``; every route is declared once
in :data:`ROUTES` — the same table drives handler dispatch, the
module's route list below, and the unknown-path 404 body, so the three
can never drift (they used to be hand-enumerated in two places).

Route semantics beyond the table:

- ``/healthz`` answers 200 with ``{"status": "ok", ...}`` while
  healthy, 503 once the latest window diverged — the contract a stock
  load-balancer / liveness probe expects.  Without a health source it
  answers 200/"ok" (the endpoint being up is the only health there is);
- ``/slo`` answers 503 while any class's alarm fires, so a
  threshold-less consumer can gate on status alone; 404 when no
  tracker was wired;
- ``/fleet`` and ``/history`` answer 404 when their source was not
  wired;
- ``/query`` reads one history series over time: ``?series=<key>``
  (required; the key format is the snapshot key,
  ``name{label="v",…}``), optional ``since=<t>`` (monotonic seconds,
  same axis as event ``t``), ``step=<s>`` (0/absent = raw ring,
  otherwise the finest aggregate level at least that wide) and
  ``limit=`` (clamped to the store's bound) — responses are bounded no
  matter what retention the store carries.  400 on malformed
  parameters, 404 for an unknown series.

``HEAD`` is answered for every route with the same status and headers
and no body — LB probes default to HEAD, and an unanswered method must
not read as an unhealthy backend.

Port 0 binds an ephemeral port (read it back from ``.port`` / ``.url``);
the listener binds loopback by default — operators who want it exposed
front it with whatever ingress their deployment already has.  Serving is
scrape-time-only work: nothing is computed until a request arrives, so
an idle endpoint costs one parked thread.

Routes:
"""
from __future__ import annotations

import json
import threading
from typing import Callable, Optional
from urllib.parse import parse_qs

from .events import _definan
from .registry import Registry

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: the single source of truth for the route surface: path → one-line
#: description.  Handler dispatch, the module docstring's route list
#: and the unknown-path 404 body are all generated from it.
ROUTES = (
    ("/metrics", "Prometheus text exposition 0.0.4"),
    ("/snapshot", "registry JSON snapshot plus caller-supplied extras"),
    ("/healthz", "run-health state (503 once diverged)"),
    ("/slo", "SLO / error-budget document (503 while any alarm fires)"),
    ("/fleet", "per-worker fleet document"),
    ("/history", "telemetry-history store document"),
    ("/query", "one history series over time "
               "(?series=&since=&step=&limit=)"),
)

__doc__ += "".join(f"\n- ``{path}`` — {desc}" for path, desc in ROUTES)


def _unknown_route_message() -> str:
    paths = [p for p, _ in ROUTES]
    return "use " + ", ".join(paths[:-1]) + " or " + paths[-1]


class _Unavailable(Exception):
    """A declared route whose backing source was not wired → 404 with a
    per-route message."""


class MetricsServer:
    def __init__(self, registry: Registry, port: int = 0,
                 host: str = "127.0.0.1",
                 extra: Optional[Callable[[], dict]] = None,
                 health: Optional[Callable[[], dict]] = None,
                 slo: Optional[Callable[[], dict]] = None,
                 fleet: Optional[Callable[[], dict]] = None,
                 history=None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        reg = registry
        extra_fn = extra
        health_fn = health
        slo_fn = slo
        fleet_fn = fleet
        history_store = history  # obs.history.HistoryStore (doc/query)

        def _json_body(obj) -> bytes:
            # an empty histogram's quantiles are real NaNs; _definan
            # keeps every body strict JSON (JGL004)
            return json.dumps(_definan(obj), indent=2,
                              default=str).encode()

        # ------------------------------------------------ route handlers
        # each returns (code, body, content_type); raises _Unavailable
        # for a declared-but-unwired source (→ 404)
        def _r_metrics(query):
            return 200, reg.prometheus().encode(), PROMETHEUS_CONTENT_TYPE

        def _r_snapshot(query):
            snap = {"metrics": reg.snapshot()}
            if extra_fn is not None:
                snap.update(extra_fn())
            return 200, _json_body(snap), "application/json"

        def _r_healthz(query):
            state = (dict(health_fn()) if health_fn is not None
                     else {"status": "ok"})
            code = 200 if state.get("status", "ok") == "ok" else 503
            # the diverged body carries the NaN loss itself
            return code, _json_body(state), "application/json"

        def _r_slo(query):
            if slo_fn is None:
                raise _Unavailable("no SLO tracker wired on this endpoint")
            state = dict(slo_fn())
            code = 200 if state.get("status", "ok") != "alarm" else 503
            return code, _json_body(state), "application/json"

        def _r_fleet(query):
            if fleet_fn is None:
                raise _Unavailable("no fleet source wired on this endpoint")
            return 200, _json_body(dict(fleet_fn())), "application/json"

        def _r_history(query):
            if history_store is None:
                raise _Unavailable(
                    "no history store wired on this endpoint")
            return 200, _json_body(history_store.doc()), "application/json"

        def _r_query(query):
            if history_store is None:
                raise _Unavailable(
                    "no history store wired on this endpoint")
            params = parse_qs(query)
            series = params.get("series", [None])[0]
            if not series:
                return (400, _json_body({"error": "series= is required"}),
                        "application/json")
            try:
                since = (float(params["since"][0])
                         if "since" in params else None)
                step = (float(params["step"][0])
                        if "step" in params else None)
                limit = (int(params["limit"][0])
                         if "limit" in params else 2000)
            except (ValueError, IndexError):
                return (400, _json_body(
                    {"error": "since=/step= must be numbers, "
                              "limit= an integer"}), "application/json")
            try:
                doc = history_store.query(series, since=since, step=step,
                                          limit=limit)
            except KeyError:
                return (404, _json_body(
                    {"error": f"unknown series {series!r}",
                     "keys": history_store.keys()}), "application/json")
            return 200, _json_body(doc), "application/json"

        handlers = {"/metrics": _r_metrics, "/snapshot": _r_snapshot,
                    "/healthz": _r_healthz, "/slo": _r_slo,
                    "/fleet": _r_fleet, "/history": _r_history,
                    "/query": _r_query}
        # the dispatch table and the declared surface must be the same
        # set — a new route added to one place only fails loudly at
        # import, not silently at scrape time
        assert set(handlers) == {p for p, _ in ROUTES}, \
            "ROUTES and handler table drifted"

        class Handler(BaseHTTPRequestHandler):
            def _handle(self):
                path, _, query = self.path.partition("?")
                fn = handlers.get(path)
                try:
                    if fn is None:
                        # send_error handles HEAD itself (headers only)
                        self.send_error(404, _unknown_route_message())
                        return
                    code, body, ctype = fn(query)
                except _Unavailable as e:
                    self.send_error(404, str(e))
                    return
                except Exception as e:  # noqa: BLE001 — a scrape bug
                    # must 500, not kill the handler thread silently
                    self.send_error(500, type(e).__name__)
                    return
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                if self.command != "HEAD":
                    self.wfile.write(body)

            def do_GET(self):  # noqa: N802 — http.server API
                self._handle()

            def do_HEAD(self):  # noqa: N802 — LB probes default to HEAD
                self._handle()

            def log_message(self, *args):  # scrapes are not stdout news
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = int(self._server.server_address[1])
        self.url = f"http://{host}:{self.port}"
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="obs-metrics-http",
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
