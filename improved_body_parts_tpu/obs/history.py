"""Bounded in-process telemetry history: every point-in-time signal the
registry exposes, kept over time — with derived load signals a control
loop can actually consume.

The registry, ``/slo`` and the fleet scrape answer *what is p99 / queue
depth right now*; nothing in the process can answer *what was it a
minute ago* or *which way is it trending*, and ROADMAP item 1's
autoscaler needs exactly those. :class:`HistoryStore` closes the gap:

- a fixed-cadence sampler thread snapshots ``Registry.iter_samples()``
  (which already folds in every collector — ``ServeMetrics``, the pool,
  the PR 18 ``FleetRegistry`` merged view, SLO burn rates) into one
  per-series time series per sample family;
- **multi-resolution retention in bounded memory**: each series keeps a
  raw ring (default 1024 points ≈ 4.3 min at the 0.25 s cadence) plus
  min/max/sum/count/last aggregate rings at 5 s (720 buckets ≈ 1 h) and
  60 s (1440 buckets ≈ 24 h) — hours of history, O(series · capacity)
  memory, no allocation growth over a multi-day run;
- **gaps are marked, never interpolated**: a sampler stall (GIL
  convoy, suspended process, stopped thread) shows up as an explicit
  gap record — a controller reading a rate across a blackout must see
  the blackout, not a fabricated straight line;
- **strict-JSON shard persistence** following the ``*_events.jsonl`` /
  ``.pN`` precedent (header record, self-describing series
  declarations, one record per tick, ``shard_records`` ticks per file);
- :meth:`HistoryStore.replay` reconstructs the store — folds, gaps and
  every derived signal — **bit-identically** from committed shards, so
  a control law is regression-testable against recorded traffic with no
  fleet running.  Three properties make that exact rather than
  approximate: sample times are rounded to 1 µs *at ingestion* (live
  and replay fold the same float), values round-trip exactly through
  JSON (``repr`` shortest-round-trip floats), and live sampling and
  replay share one fold path (``_ingest``), including gap detection.

Derived-signals API (:meth:`rate`, :meth:`trend`,
:meth:`window_quantiles`, :meth:`burn_rate`, :meth:`signals`): the
inputs ROADMAP item 1 names — queue depth, admitted-depth, per-hop p99,
``hop_conservation_frac``, burn rate — plus rates and slopes over any
counter.  All default ``now`` to the **last sample time**, not the wall
clock: a sampled store's "now" is its newest tick, and it is what keeps
live-computed and replayed signal values identical.

Served at ``/history`` (store document) and ``/query`` (one series over
time, ``?series=&since=&step=``) by ``obs.http.MetricsServer``;
``serve.capacity.CapacityModel`` fits replica capacity from it;
``tools/history_audit.py`` proves the overhead/conservation/replay
contract and ``tools/history_report.py`` renders it.
"""
from __future__ import annotations

import collections
import glob
import math
import os
import threading
import time
import weakref
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from .events import read_events, strict_dumps
from .registry import Registry, _render_labels, _sanitize

HISTORY_SCHEMA = 1

#: (bucket_width_s, ring_capacity) per downsampling level, coarsest
#: last: 5 s buckets for 1 h, 1 min buckets for 24 h.
DEFAULT_LEVELS: Tuple[Tuple[float, int], ...] = ((5.0, 720), (60.0, 1440))

#: hard cap on points/buckets per ``query()`` response — the /query
#: route must stay bounded no matter what retention the store carries
QUERY_LIMIT = 2000


def history_path_for(events_path: str) -> str:
    """The conventional history-shard path next to a run's event stream:
    ``events.jsonl`` → ``events_history.jsonl`` (rotated shards append
    ``.p1``, ``.p2``, … — the same suffix scheme as worker sinks, so
    ``tools/telemetry_report.py`` discovers both the same way)."""
    base, ext = os.path.splitext(events_path)
    return base + "_history" + (ext or ".jsonl")


def discover_history_shards(path: str) -> List[str]:
    """``[path, path.p1, path.p2, …]`` — every shard of one history
    stream in write order (numeric suffix sort, not lexical: ``.p10``
    after ``.p9``).  Mirrors the worker-sink discovery contract."""
    out: List[str] = []
    if os.path.exists(path):
        out.append(path)
    extra: List[Tuple[int, str]] = []
    for p in glob.glob(glob.escape(path) + ".p*"):
        suffix = p[len(path) + 2:]
        if suffix.isdigit():
            extra.append((int(suffix), p))
    out.extend(p for _, p in sorted(extra))
    return out


def series_key(name: str, labels: Optional[Dict[str, str]] = None) -> str:
    """The store's series identity: the registry's snapshot key format,
    ``name{label="v",…}`` with sorted labels — so a /snapshot reader and
    a history reader name the same signal the same way."""
    return _sanitize(name) + _render_labels(dict(labels or {}))


def _percentile(sorted_vals: Sequence[float], q: float) -> float:
    """Linear-interpolated percentile of an already-sorted sequence,
    ``q`` in [0, 100] — the exact math of
    ``utils.meters.PercentileMeter.percentile``, so a window quantile
    and a reservoir quantile over the same points agree."""
    if not sorted_vals:
        return 0.0
    pos = (len(sorted_vals) - 1) * q / 100.0
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    return (sorted_vals[lo]
            + (sorted_vals[hi] - sorted_vals[lo]) * (pos - lo))


class _SeriesLevel:
    """One downsampling resolution of one series: a ring of finalized
    (t0, min, max, sum, count, last) buckets plus the open bucket.
    Folding is driven purely by the (t, v) stream — no clock reads — so
    replaying the same ticks rebuilds the same buckets bit-for-bit."""

    __slots__ = ("width", "buckets", "_idx", "_min", "_max", "_sum",
                 "_count", "_last")

    def __init__(self, width: float, capacity: int):
        self.width = float(width)
        self.buckets: collections.deque = collections.deque(
            maxlen=int(capacity))
        self._idx: Optional[int] = None

    def add(self, t: float, v: float) -> None:
        idx = int(t // self.width)
        if idx == self._idx:
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            self._sum += v
            self._count += 1
            self._last = v
            return
        if self._idx is not None:
            self.buckets.append(self._freeze())
        self._idx = idx
        self._min = self._max = self._sum = self._last = v
        self._count = 1

    def _freeze(self) -> Tuple[float, float, float, float, int, float]:
        return (self._idx * self.width, self._min, self._max, self._sum,
                self._count, self._last)

    def snapshot(self) -> List[Tuple[float, float, float, float, int,
                                     float]]:
        """Finalized buckets plus the open one (a query must see the
        current partial bucket, or the freshest ``width`` seconds of
        history would read as missing)."""
        out = list(self.buckets)
        if self._idx is not None:
            out.append(self._freeze())
        return out


class _Series:
    """One sample family over time: raw ring + every aggregate level."""

    __slots__ = ("key", "name", "labels", "kind", "raw", "levels")

    def __init__(self, key: str, name: str, labels: Dict[str, str],
                 kind: str, raw_capacity: int,
                 level_spec: Sequence[Tuple[float, int]]):
        self.key = key
        self.name = name
        self.labels = dict(labels)
        self.kind = kind
        self.raw: collections.deque = collections.deque(
            maxlen=int(raw_capacity))
        self.levels = [_SeriesLevel(w, c) for w, c in level_spec]

    def add(self, t: float, v: float) -> None:
        self.raw.append((t, v))
        for lv in self.levels:
            lv.add(t, v)


class HistoryStore:
    """Bounded multi-resolution time-series store over a telemetry
    registry (see the module docstring for the full design).

    ``registry=None`` builds a source-less store — what :meth:`replay`
    uses, and what a test feeds directly through :meth:`sample_now`
    sources.  ``slo=`` bridges an :class:`obs.slo.SLOTracker` that was
    *not* registered into the registry (when it was, its burn-rate
    series already arrive through ``iter_samples`` and the bridge must
    stay off or every SLO series would be ingested twice per tick).
    ``clock`` is injectable for tests; production leaves it on the
    monotonic clock, the same axis as the event sink's ``t``.
    """

    def __init__(self, registry: Optional[Registry] = None, *,
                 cadence_s: float = 0.25, raw_capacity: int = 1024,
                 levels: Sequence[Tuple[float, int]] = DEFAULT_LEVELS,
                 max_series: int = 512,
                 persist_path: Optional[str] = None,
                 shard_records: int = 4096,
                 run_id: Optional[str] = None,
                 slo=None,
                 sources: Optional[Iterable[Callable[[], Iterable]]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 gap_factor: float = 2.5):
        if cadence_s <= 0:
            raise ValueError(f"cadence_s must be > 0, got {cadence_s}")
        self.cadence_s = float(cadence_s)
        self.raw_capacity = int(raw_capacity)
        self.levels = tuple((float(w), int(c)) for w, c in levels)
        self.max_series = int(max_series)
        self.shard_records = int(shard_records)
        self.run_id = run_id
        self.gap_factor = float(gap_factor)
        self._clock = clock
        self._registry = registry
        self._sources: List[Callable[[], Iterable]] = list(sources or [])
        if slo is not None:
            # weakref, like every registry collector: a store that
            # outlives its tracker samples nothing instead of pinning it
            slo_ref = weakref.ref(slo)

            def _slo_source():
                tr = slo_ref()
                return tr.collect() if tr is not None else []

            self._sources.append(_slo_source)
        # reentrant: signals() composes rate()/latest() under one
        # consistent view without handing the lock back between them
        self._lock = threading.RLock()
        self._series: Dict[str, _Series] = {}
        self._last_t: Optional[float] = None
        self._samples = 0
        self._sample_errors = 0
        self._gaps: collections.deque = collections.deque(maxlen=256)
        self._gap_count = 0
        self._dropped_keys: set = set()
        self._dropped_overflow = 0
        # (name, sorted-label-items) → (key, sanitized name, labels):
        # key rendering is regex work and the identity never changes, so
        # paying it once per series instead of once per series per tick
        # is most of the sampler's steady-state cost; bounded like the
        # series map so a label explosion cannot grow it without limit
        self._key_memo: Dict[Tuple, Tuple[str, str, Dict[str, str]]] = {}
        # ------------------------------------------------- persistence
        self._base = persist_path
        self._f = None
        self._shard = 0
        self._shard_ticks = 0
        self._persist_records = 0
        if persist_path:
            self._open_shard()
        # ---------------------------------------------------- sampler
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()

    # ------------------------------------------------------- persistence
    def _shard_path(self, shard: int) -> str:
        return self._base if shard == 0 else f"{self._base}.p{shard}"

    def _write_line(self, rec: dict) -> None:
        self._f.write(strict_dumps(rec, separators=(",", ":")) + "\n")
        self._persist_records += 1

    def _open_shard(self) -> None:
        path = self._shard_path(self._shard)
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", buffering=1)  # line-buffered text
        self._write_line({
            "event": "history_start", "schema": HISTORY_SCHEMA,
            "time_unix": round(time.time(), 3), "pid": os.getpid(),
            "run_id": self.run_id, "cadence_s": self.cadence_s,
            "gap_factor": self.gap_factor,
            "raw_capacity": self.raw_capacity,
            "levels": [list(lv) for lv in self.levels],
            "max_series": self.max_series, "shard": self._shard})
        # re-declare every live series: each shard is self-describing
        # (the report tool can summarize one shard without its siblings)
        for s in self._series.values():
            self._write_line({"event": "history_series", "key": s.key,
                              "name": s.name, "labels": s.labels,
                              "kind": s.kind})
        self._shard_ticks = 0

    def _rotate_if_full(self) -> None:
        if self._f is not None and self._shard_ticks >= self.shard_records:
            self._f.close()
            self._shard += 1
            self._open_shard()

    # --------------------------------------------------------- ingestion
    def sample_now(self, t: Optional[float] = None) -> float:
        """Take one sample tick: gather every source's current samples,
        fold them in, persist the tick.  Returns the (rounded) tick
        time.  Thread-safe against every reader and against itself —
        gathering runs outside the store lock (a registry scrape in a
        collector must never wait on a history query)."""
        t = round(float(self._clock() if t is None else t), 6)
        items: Dict[str, Tuple[str, Dict[str, str], str, float]] = {}
        sources: List[Callable[[], Iterable]] = []
        if self._registry is not None:
            sources.append(self._registry.iter_samples)
        sources.extend(self._sources)
        memo = self._key_memo
        for src in sources:
            try:
                for tup in src():
                    name, labels, kind, value = tup[:4]
                    mk = (name, tuple(sorted(labels.items()))
                          if labels else ())
                    ent = memo.get(mk)
                    if ent is None:
                        labels = dict(labels or {})
                        ent = (series_key(name, labels),
                               _sanitize(name), labels)
                        if len(memo) < 8192:
                            memo[mk] = ent
                    items[ent[0]] = (ent[1], ent[2], kind, float(value))
            except Exception:  # noqa: BLE001 — one dead source must not
                with self._lock:  # kill the whole tick
                    self._sample_errors += 1
        with self._lock:
            self._ingest(t, items, persist=True)
        return t

    def _ingest(self, t: float,
                items: Dict[str, Tuple[str, Dict[str, str], str, float]],
                persist: bool) -> None:
        """Fold one tick — THE shared path between live sampling and
        :meth:`replay`, which is what makes replay bit-identical.
        Caller holds the lock; ``t`` is already µs-rounded."""
        if persist:
            self._rotate_if_full()
        if self._last_t is not None:
            dt = t - self._last_t
            if dt > self.gap_factor * self.cadence_s:
                gap = {"t_prev": self._last_t, "t": t,
                       "missed": max(1, int(dt / self.cadence_s) - 1)}
                self._gaps.append(gap)
                self._gap_count += 1
                if persist and self._f is not None:
                    self._write_line({"event": "history_gap", **gap})
        self._last_t = t
        vrec: Dict[str, float] = {}
        for key, (name, labels, kind, value) in items.items():
            s = self._series.get(key)
            if s is None:
                if len(self._series) >= self.max_series:
                    # bounded by design: a label-cardinality explosion
                    # drops NEW series (loudly, via the counter), never
                    # grows without limit
                    if len(self._dropped_keys) < 4096:
                        self._dropped_keys.add(key)
                    else:
                        self._dropped_overflow += 1
                    continue
                s = self._series[key] = _Series(
                    key, name, labels, kind, self.raw_capacity,
                    self.levels)
                if persist and self._f is not None:
                    self._write_line({"event": "history_series",
                                      "key": key, "name": name,
                                      "labels": labels, "kind": kind})
            s.add(t, value)
            vrec[key] = value
        self._samples += 1
        if persist and self._f is not None:
            self._write_line({"event": "history_sample", "t": t,
                              "v": vrec})
            self._shard_ticks += 1

    # ----------------------------------------------------------- sampler
    def start(self) -> "HistoryStore":
        """Start the fixed-cadence sampler thread (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return self
            self._stop_evt = threading.Event()
            self._thread = threading.Thread(
                target=self._run, name="obs-history-sampler", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop_evt.wait(self.cadence_s):
            try:
                self.sample_now()
            except Exception:  # noqa: BLE001 — a sampler bug must stall
                with self._lock:  # history, never kill the thread
                    self._sample_errors += 1

    def stop(self) -> None:
        """Stop the sampler thread (joined); the store stays queryable
        and :meth:`sample_now` still works (the audit's quiescent
        conservation check depends on exactly that)."""
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            self._stop_evt.set()
            thread.join(timeout=5.0)

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()

    def close(self) -> None:
        self.stop()
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def __enter__(self) -> "HistoryStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------- exposition
    def register_into(self, registry: Registry) -> "HistoryStore":
        """Export the store's own meta-signals through the registry —
        which the store then samples, so history is self-describing
        (gap/drop counters have history too).  Weakref collector, per
        the ServeMetrics/SLO/fleet precedent."""
        ref = weakref.ref(self)

        def _collect():
            st = ref()
            if st is None:
                return []
            with st._lock:
                return [
                    ("history_samples_total", {}, "counter",
                     float(st._samples), "history sample ticks taken"),
                    ("history_gaps_total", {}, "counter",
                     float(st._gap_count),
                     "sampler gaps detected (never interpolated)"),
                    ("history_series", {}, "gauge",
                     float(len(st._series)), "live series tracked"),
                    ("history_series_dropped_total", {}, "counter",
                     float(len(st._dropped_keys) + st._dropped_overflow),
                     "new series dropped at the max_series bound"),
                    ("history_sample_errors_total", {}, "counter",
                     float(st._sample_errors),
                     "sample ticks that raised (source or sampler bug)"),
                    ("history_persist_records_total", {}, "counter",
                     float(st._persist_records),
                     "records written across all shards"),
                    ("history_persist_shards", {}, "gauge",
                     float(st._shard + 1 if st._base else 0),
                     "shard files opened so far"),
                ]

        registry.register_collector(_collect)
        return self

    # ---------------------------------------------------------- readers
    def keys(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def latest(self, key: str) -> Optional[Tuple[float, float]]:
        """Newest ``(t, value)`` of one series, or None."""
        with self._lock:
            s = self._series.get(key)
            if s is None or not s.raw:
                return None
            return s.raw[-1]

    def _now(self, now: Optional[float]) -> Optional[float]:
        """Derived signals default "now" to the last sample tick — the
        sampled store's notion of the present, and the anchor that makes
        live and replayed derived values identical."""
        return self._last_t if now is None else now

    def _points(self, key: str, t_lo: float, t_hi: float
                ) -> List[Tuple[float, float]]:
        s = self._series.get(key)
        if s is None:
            return []
        return [(t, v) for t, v in s.raw if t_lo <= t <= t_hi]

    def rate_series(self, key: str
                    ) -> List[Tuple[float, float, float, bool]]:
        """Per-interval rates over the raw ring: ``(t, dt, rate,
        gap)`` for each consecutive sample pair, rate assigned at the
        interval's END.  ``gap`` marks intervals wider than the gap
        threshold — a consumer integrating across one knows it is
        bridging a blackout.  ``Σ rate·dt`` telescopes back to
        ``v_last − v_first`` (the audit's integral-conservation gate)."""
        with self._lock:
            s = self._series.get(key)
            raw = list(s.raw) if s is not None else []
        out: List[Tuple[float, float, float, bool]] = []
        thresh = self.gap_factor * self.cadence_s
        for (t0, v0), (t1, v1) in zip(raw, raw[1:]):
            dt = t1 - t0
            if dt <= 0:
                continue
            out.append((t1, dt, (v1 - v0) / dt, dt > thresh))
        return out

    def integrate_rate(self, key: str) -> float:
        """``Σ rate·dt`` over the raw ring (fsum — no accumulation
        drift); equals the counter delta across the ring by
        construction, which is what the audit asserts."""
        return math.fsum(r * dt for _, dt, r, _ in self.rate_series(key))

    def rate(self, key: str, window_s: float,
             now: Optional[float] = None) -> Optional[float]:
        """Average rate of change over the trailing window (units/s):
        ``(v_last − v_first) / (t_last − t_first)`` over the raw points
        in ``[now − window_s, now]``.  None with < 2 points — an
        unknown rate is not a zero rate."""
        with self._lock:
            now = self._now(now)
            if now is None:
                return None
            pts = self._points(key, now - window_s, now)
            if len(pts) < 2 or pts[-1][0] <= pts[0][0]:
                return None
            return ((pts[-1][1] - pts[0][1])
                    / (pts[-1][0] - pts[0][0]))

    def trend(self, key: str, window_s: float,
              now: Optional[float] = None) -> Optional[float]:
        """Least-squares slope (units/s) over the trailing window — the
        "which way is it going" signal for gauges, where :meth:`rate`'s
        endpoint difference would be hostage to two noisy samples."""
        with self._lock:
            now = self._now(now)
            if now is None:
                return None
            pts = self._points(key, now - window_s, now)
        if len(pts) < 2:
            return None
        tm = math.fsum(t for t, _ in pts) / len(pts)
        vm = math.fsum(v for _, v in pts) / len(pts)
        den = math.fsum((t - tm) * (t - tm) for t, _ in pts)
        if den <= 0:
            return None
        num = math.fsum((t - tm) * (v - vm) for t, v in pts)
        return num / den

    def window_quantiles(self, key: str, window_s: float,
                         qs: Sequence[float] = (50.0, 95.0, 99.0),
                         now: Optional[float] = None
                         ) -> Optional[Dict[str, float]]:
        """Exact quantiles of the raw samples in the trailing window
        (same interpolation as ``PercentileMeter``), keyed ``p50`` /
        ``p95`` / ``p99`` / ``p99.9``-style.  None with no points."""
        with self._lock:
            now = self._now(now)
            if now is None:
                return None
            pts = self._points(key, now - window_s, now)
        if not pts:
            return None
        vals = sorted(v for _, v in pts)
        return {"p%g" % q: _percentile(vals, q) for q in qs}

    def burn_rate(self, qos_class: str, window: str = "5m",
                  now: Optional[float] = None) -> Optional[float]:
        """Latest SLO burn rate for one class/window from the bridged
        ``slo_burn_rate{class=,window=}`` series (None when the tracker
        never reported it)."""
        key = series_key("slo_burn_rate",
                         {"class": qos_class, "window": window})
        with self._lock:
            now = self._now(now)
            if now is None:
                return None
            return self._value_at(key, now)

    def _value_at(self, key: str, now: float) -> Optional[float]:
        """Newest value at or before ``now`` (lock held)."""
        s = self._series.get(key)
        if s is None:
            return None
        for t, v in reversed(s.raw):
            if t <= now:
                return v
        return None

    def _scan(self, name: str) -> List[_Series]:
        return [s for s in self._series.values() if s.name == name]

    def _scan_suffix(self, suffix: str) -> List[_Series]:
        """Series whose family name ends with ``suffix`` — the serving
        stack exports one family set under layer prefixes (``serve_``
        for a batcher, ``pool_`` / ``pool_engine_`` for the replicated
        tiers), and the control-plane signals must not care which layer
        is deployed."""
        return [s for s in self._series.values()
                if s.name.endswith(suffix)]

    def signals(self, now: Optional[float] = None,
                rate_window_s: float = 10.0) -> dict:
        """The control-plane feed: exactly the autoscaler inputs ROADMAP
        item 1 names, derived from history at one consistent instant.
        Absent signals are None — a controller must know "not measured"
        from "zero".  Multi-model deployments sum depths and take the
        worst (max) per-hop p99 / worst (min) conservation across
        models: capacity decisions key off the binding constraint."""
        with self._lock:
            now = self._now(now)
            if now is None:
                return {"t": None}

            def _sum_over(name):
                vals = [self._value_at(s.key, now)
                        for s in self._scan(name)]
                vals = [v for v in vals if v is not None]
                return math.fsum(vals) if vals else None

            hop_p99: Dict[str, float] = {}
            for s in self._scan_suffix("_hop_latency_seconds"):
                if s.labels.get("quantile") != "0.99":
                    continue
                v = self._value_at(s.key, now)
                if v is None:
                    continue
                hop = s.labels.get("hop", "")
                if hop not in hop_p99 or v > hop_p99[hop]:
                    hop_p99[hop] = v
            cons = [self._value_at(s.key, now)
                    for s in self._scan_suffix("_hop_conservation_frac")]
            cons = [v for v in cons if v is not None]
            burn: Dict[str, Dict[str, float]] = {}
            for s in self._scan("slo_burn_rate"):
                v = self._value_at(s.key, now)
                if v is None:
                    continue
                cls = s.labels.get("class", "")
                burn.setdefault(cls, {})[s.labels.get("window", "")] = v
            # one family, many layer prefixes: count each request once
            # by preferring the engine-facing family and falling back a
            # tier only when it is absent (pool_engine_* and pool_*
            # describe the SAME traffic — summing both would double it)
            comp = (self._scan("serve_completed_total")
                    or self._scan("pool_completed_total"))
            rates = [self.rate(s.key, rate_window_s, now=now)
                     for s in comp]
            rates = [r for r in rates if r is not None]
            return {
                "t": now,
                "queue_depth": (_sum_over("serve_queue_depth")
                                if self._scan("serve_queue_depth")
                                else _sum_over("pool_engine_queue_depth")),
                "admitted_depth": _sum_over("pool_queue_depth"),
                "hop_p99_s": dict(sorted(hop_p99.items())),
                "hop_conservation_frac": min(cons) if cons else None,
                "burn_rate": {c: dict(sorted(w.items()))
                              for c, w in sorted(burn.items())},
                "completed_rate": (math.fsum(rates) if rates else None),
            }

    def query(self, key: str, since: Optional[float] = None,
              step: Optional[float] = None,
              limit: int = QUERY_LIMIT) -> dict:
        """One series over time, bounded.  ``step`` selects resolution:
        absent/0 reads the raw ring; otherwise the finest aggregate
        level with ``width ≥ step`` serves min/max/sum/count/last
        buckets (the coarsest level when every width is finer).  Always
        returns the NEWEST ``limit`` entries (``truncated`` flags a
        cut), plus the gap records overlapping the range.  Raises
        ``KeyError`` for an unknown series (the /query 404)."""
        limit = max(1, min(int(limit), QUERY_LIMIT))
        t_lo = float(since) if since is not None else float("-inf")
        with self._lock:
            s = self._series.get(key)
            if s is None:
                raise KeyError(key)
            if step and step > 0:
                level = None
                for lv in s.levels:
                    if lv.width >= step:
                        level = lv
                        break
                if level is None and s.levels:
                    level = s.levels[-1]
                buckets = [b for b in level.snapshot()
                           if b[0] + level.width > t_lo]
                truncated = len(buckets) > limit
                entries = [
                    {"t": b[0], "min": b[1], "max": b[2], "sum": b[3],
                     "count": b[4], "last": b[5]}
                    for b in buckets[-limit:]]
                step_used = level.width
            else:
                pts = [(t, v) for t, v in s.raw if t >= t_lo]
                truncated = len(pts) > limit
                entries = [[t, v] for t, v in pts[-limit:]]
                step_used = 0.0
            gaps = [dict(g) for g in self._gaps
                    if g["t"] >= t_lo]
            return {"series": key, "name": s.name, "labels": s.labels,
                    "kind": s.kind, "step": step_used,
                    "points": entries, "truncated": truncated,
                    "gaps": gaps}

    def doc(self) -> dict:
        """The /history document: configuration, retention, gap and
        persistence accounting, and the series index — everything an
        operator (or the audit) needs to know what the store holds."""
        with self._lock:
            return {
                "run_id": self.run_id,
                "cadence_s": self.cadence_s,
                "gap_factor": self.gap_factor,
                "raw_capacity": self.raw_capacity,
                "levels": [list(lv) for lv in self.levels],
                "max_series": self.max_series,
                "sampler_alive": self._thread is not None,
                "series": len(self._series),
                "series_dropped": (len(self._dropped_keys)
                                   + self._dropped_overflow),
                "samples": self._samples,
                "sample_errors": self._sample_errors,
                "last_t": self._last_t,
                "gaps": {"count": self._gap_count,
                         "recent": [dict(g)
                                    for g in list(self._gaps)[-10:]]},
                "persist": ({"path": self._base,
                             "shards": self._shard + 1,
                             "records": self._persist_records,
                             "shard_records": self.shard_records}
                            if self._base else None),
                "keys": sorted(self._series),
            }

    # ------------------------------------------------------------ replay
    @classmethod
    def replay(cls, path: str) -> "HistoryStore":
        """Rebuild a store offline from committed shards: read
        ``path`` (+ ``.pN`` siblings) in write order, re-ingest every
        tick through the SAME fold path live sampling used.  The result
        answers every derived-signal call bit-identically to the live
        store at its final tick — recorded traffic becomes a control-law
        regression fixture with no fleet running."""
        shards = discover_history_shards(path)
        if not shards:
            raise FileNotFoundError(
                f"no history shards at {path!r} (nor {path!r}.pN)")
        store: Optional[HistoryStore] = None
        decl: Dict[str, Tuple[str, Dict[str, str], str]] = {}
        for p in shards:
            for rec in read_events(p):
                ev = rec.get("event")
                if ev == "history_start":
                    if rec.get("schema", 0) > HISTORY_SCHEMA:
                        raise ValueError(
                            f"history shard {p!r} has schema "
                            f"{rec.get('schema')} > supported "
                            f"{HISTORY_SCHEMA}")
                    if store is None:
                        store = cls(
                            registry=None,
                            cadence_s=float(rec.get("cadence_s", 0.25)),
                            raw_capacity=int(rec.get("raw_capacity",
                                                     1024)),
                            levels=tuple(
                                (float(w), int(c)) for w, c in
                                rec.get("levels", DEFAULT_LEVELS)),
                            max_series=int(rec.get("max_series", 512)),
                            run_id=rec.get("run_id"),
                            gap_factor=float(rec.get("gap_factor",
                                                     2.5)))
                elif ev == "history_series":
                    decl[rec["key"]] = (
                        rec.get("name", rec["key"]),
                        dict(rec.get("labels") or {}),
                        rec.get("kind", "gauge"))
                elif ev == "history_sample" and store is not None:
                    items = {}
                    for key, v in rec.get("v", {}).items():
                        name, labels, kind = decl.get(
                            key, (key, {}, "gauge"))
                        items[key] = (name, labels, kind, float(v))
                    with store._lock:
                        # gap records in the stream are NOT consumed:
                        # _ingest re-detects them from tick spacing,
                        # which keeps gap accounting on the same shared
                        # path (the report tool cross-checks recorded
                        # vs re-detected gaps instead)
                        store._ingest(float(rec["t"]), items,
                                      persist=False)
        if store is None:
            raise ValueError(
                f"{path!r}: no history_start header in any shard")
        return store
