"""Request-scoped causal tracing across the multi-hop serve stack.

The registry answers *how the fleet is doing* and the span trace *where
each thread's time went*; neither can answer the question a slow
request raises: **which hop ate this request's budget?**  Since the
pool/policy/cascade layers landed, one serve request can traverse
admission → bucket wait → batched execute → fused decode → cascade
escalation → pool failover → hedge before delivery — and each of those
components only measures itself.  This module threads ONE causal
context through all of them:

- every component that handles a request opens a **node** — a unique id,
  its causal parent, the edge *kind* that created it (``submit`` /
  ``retry`` / ``hedge`` / ``failover`` / ``escalate`` / ``migrate``)
  and a reason annotation (the error that forced the failover, the
  signal that escalated the frame);
- each node records a **hop waterfall** — ordered ``(hop, seconds)``
  segments that partition its span (the batcher's
  queue / batch_formation / device / decode / deliver; a parent's
  route / deliver bookends around its child's window, plus the
  *gap hops* — ``hedge_wait``, ``prior_attempts``, ``student_lane`` —
  that keep the delivering chain's sum honest when the winning path is
  not the first one tried);
- when the LAST node of a request finishes, the recorder assembles one
  strict-JSON ``request`` record (the whole tree) and emits it through
  the process event sink, keeping a bounded in-memory copy for
  in-process consumers (``tools/request_report.py`` reconstructs trees
  and verifies causal completeness from either).

**Cross-component threading without signature changes.**  The engines
share one duck-typed ``submit(image, deadline_s=...)`` contract
(batcher, pool, cascade, and every test fake); threading a context
argument through it would fork that contract everywhere.  Instead the
parent layer wraps its *synchronous* inner ``submit`` call in
:meth:`ReqNode.child_scope`, which installs the parent on a
thread-local; the inner component's :meth:`ReqTrace.begin` picks it up
and becomes a child.  Completion callbacks, failover re-submissions and
hedges all call ``submit`` synchronously on whatever thread they run
on, so the handoff is race-free by construction.

**Delivering chain.**  Every non-leaf node records ``won_by`` — the
child whose outcome it delivered (a hedge's loser still completes and
still lands in the record, but only the winner is on the chain).
Following ``won_by`` from the root yields the request's *delivering
path*; causal completeness (exactly one delivering leaf, zero
orphan/duplicate nodes) is what ``tools/request_report.py`` verifies,
and the chain's hop sum over the root's end-to-end span is the
conservation discipline (≥95%, the StepPhases rule one level up).

**Cost.**  With no recorder installed (the default), every site hits
:class:`NullReqTrace` / ``NULL_NODE`` — attribute checks and no-ops.
With a recorder installed, unsampled requests get ``NULL_NODE`` at the
root and every child inherits it through the scope, so sampling bounds
the per-request cost to one modulo.  Per-hop *histograms* are not this
module's job — they live on ``serve.metrics.ServeMetrics`` and are
recorded for every request regardless of sampling.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from .events import get_sink
from .trace import get_tracer

#: causal edge kinds a child node can be created under (reason-annotated
#: where the edge encodes a decision: why the failover, why the
#: escalation)
EDGE_KINDS = ("submit", "retry", "hedge", "failover", "escalate",
              "migrate", "resubmit")


class _Scope:
    """One ``child_scope`` activation: carries the parent + edge kind
    down the thread-local, and carries the created child node back up
    (``scope.node``) so the parent can record ``won_by``."""

    __slots__ = ("parent", "kind", "reason", "node")

    def __init__(self, parent, kind: str, reason: Optional[str]):
        self.parent = parent
        self.kind = kind
        self.reason = reason
        self.node = None        # filled by the inner begin()


class _ScopeCtx:
    """Context manager installing a :class:`_Scope` on the thread-local
    (save/restore — scopes nest: policy → pool → batcher)."""

    __slots__ = ("_scope", "_prev")

    def __init__(self, scope: _Scope):
        self._scope = scope

    def __enter__(self) -> _Scope:
        self._prev = getattr(_TLS, "scope", None)
        _TLS.scope = self._scope
        return self._scope

    def __exit__(self, *exc) -> None:
        _TLS.scope = self._prev


_TLS = threading.local()


class NullReqNode:
    """The inert node: parents of unsampled requests and every site
    when no recorder is installed.  ``child_scope`` still nests (the
    scope machinery must stay balanced) but creates more nulls."""

    __slots__ = ()
    sampled = False
    node_id = 0
    req = 0

    def child_scope(self, kind: str, reason: Optional[str] = None
                    ) -> _ScopeCtx:
        return _ScopeCtx(_Scope(self, kind, reason))

    def finish(self, status: str = "ok",
               hops: Optional[List[Tuple[str, float]]] = None,
               won_by=None, **labels) -> None:
        pass


NULL_NODE = NullReqNode()


class ReqNode:
    """One component's handling of one request (one tree node)."""

    __slots__ = ("_rec", "req", "node_id", "parent_id", "comp", "kind",
                 "reason", "labels", "t0", "t1", "hops", "status",
                 "won_by_id", "_done")

    sampled = True

    def __init__(self, rec: "ReqTrace", req: int, node_id: int,
                 parent_id: Optional[int], comp: str, kind: str,
                 reason: Optional[str], labels: Dict[str, str]):
        self._rec = rec
        self.req = req
        self.node_id = node_id
        self.parent_id = parent_id
        self.comp = comp
        self.kind = kind
        self.reason = reason
        self.labels = labels
        self.t0 = rec.now()
        self.t1: Optional[float] = None
        self.hops: List[Tuple[str, float]] = []
        self.status = "open"
        self.won_by_id: Optional[int] = None
        self._done = False

    def child_scope(self, kind: str, reason: Optional[str] = None
                    ) -> _ScopeCtx:
        """Wrap the parent's synchronous inner ``submit`` call; the
        component reached inside the ``with`` attaches as a child under
        edge ``kind`` and the scope hands its node back via
        ``scope.node`` (``None`` when the inner submit shed or the
        inner component is an uninstrumented fake)."""
        return _ScopeCtx(_Scope(self, kind, reason))

    def finish(self, status: str = "ok",
               hops: Optional[List[Tuple[str, float]]] = None,
               won_by=None, **labels) -> None:
        """Complete this node exactly once.  ``hops`` is the ordered
        waterfall partition of the node's span; ``won_by`` the child
        node whose outcome this node delivered (chain link)."""
        rec = self._rec
        if self._done:      # exactly-once: late losers / double-finish
            return
        self._done = True
        self.t1 = rec.now()
        if hops:
            self.hops = [(str(n), max(float(d), 0.0)) for n, d in hops]
        if won_by is not None and isinstance(won_by, ReqNode):
            self.won_by_id = won_by.node_id
        if labels:
            self.labels = {**self.labels,
                           **{k: str(v) for k, v in labels.items()}}
        self.status = status
        rec._node_finished(self)

    def as_dict(self) -> dict:
        return {
            "node": self.node_id,
            "parent": self.parent_id,
            "comp": self.comp,
            "kind": self.kind,
            **({"reason": self.reason} if self.reason else {}),
            **self.labels,
            "t0_ms": round(self.t0 * 1e3, 3),
            "dur_ms": round(((self.t1 if self.t1 is not None else self.t0)
                             - self.t0) * 1e3, 3),
            "status": self.status,
            **({"won_by": self.won_by_id}
               if self.won_by_id is not None else {}),
            "hops_ms": {n: round(d * 1e3, 3) for n, d in self.hops},
        }


class _LiveReq:
    """Accounting for one in-flight request tree."""

    __slots__ = ("root", "nodes", "pending")

    def __init__(self, root: ReqNode):
        self.root = root
        self.nodes: List[ReqNode] = [root]
        self.pending = 1


class NullReqTrace:
    """Tracing disabled: every begin returns the null node."""

    enabled = False
    emitted = 0
    dropped = 0

    def begin(self, comp: str, **labels):
        return NULL_NODE

    def records(self) -> List[dict]:
        return []

    def now(self) -> float:
        return 0.0


class ReqTrace:
    """Per-request causal recorder for one process.

    ``sample``: every Nth root request is recorded (1 = all, the bench
    default; a high-QPS deployment thins here — the per-hop histograms
    on ``ServeMetrics`` see every request regardless).  ``t0`` anchors
    node timestamps; pass the event sink's ``t0`` so request records,
    spans and JSONL events share one axis (``RunTelemetry`` does).

    Completed request records are emitted through the process event
    sink as ``request`` events AND kept in a bounded deque
    (:meth:`records`).  A request whose tree never completes (a future
    the caller abandoned mid-teardown) is evicted once ``max_live``
    trees are in flight — counted in ``dropped``, never a leak.
    """

    enabled = True

    def __init__(self, sample: int = 1, capacity: int = 4096,
                 max_live: int = 4096, t0: Optional[float] = None,
                 emit_to_sink: bool = True):
        import time

        self.sample = max(1, int(sample))
        self._t0 = float(t0) if t0 is not None else time.monotonic()
        self._mono = time.monotonic
        self._lock = threading.Lock()
        self._req_counter = 0
        self._node_counter = 0
        self._live: "Dict[int, _LiveReq]" = {}
        self._records: deque = deque(maxlen=int(capacity))
        self.max_live = int(max_live)
        self.emit_to_sink = emit_to_sink
        self.emitted = 0
        self.dropped = 0

    # ------------------------------------------------------------- clock
    def now(self) -> float:
        return self._mono() - self._t0

    # ------------------------------------------------------------- begin
    def begin(self, comp: str, **labels):
        """Open a node for ``comp``'s handling of the current request.

        Inside an active :meth:`ReqNode.child_scope` this attaches as a
        child of the scope's parent (inheriting the request id and the
        scope's edge kind/reason, and handing itself back through
        ``scope.node``); otherwise it opens a new ROOT — where the
        sampling decision is made.
        """
        scope = getattr(_TLS, "scope", None)
        if scope is not None:
            parent = scope.parent
            if not parent.sampled:
                scope.node = NULL_NODE
                return NULL_NODE
            with self._lock:
                live = self._live.get(parent.req)
                if live is None:        # tree already evicted
                    scope.node = NULL_NODE
                    return NULL_NODE
                self._node_counter += 1
                node = ReqNode(self, parent.req, self._node_counter,
                               parent.node_id, comp, scope.kind,
                               scope.reason,
                               {k: str(v) for k, v in labels.items()})
                live.nodes.append(node)
                live.pending += 1
            scope.node = node
            trace = get_tracer()
            if trace.enabled:
                # the followable arc: one flow step per hop edge, on
                # whatever track the submitting thread records to
                trace.flow_step("reqpath", node.req, cat="reqpath")
            return node
        # root
        with self._lock:
            self._req_counter += 1
            if self._req_counter % self.sample:
                return NULL_NODE
            self._node_counter += 1
            node = ReqNode(self, self._req_counter, self._node_counter,
                           None, comp, "submit", None,
                           {k: str(v) for k, v in labels.items()})
            self._live[node.req] = _LiveReq(node)
            if len(self._live) > self.max_live:
                # evict the OLDEST in-flight tree (insertion order):
                # bounded memory beats a complete record for a request
                # someone abandoned
                evict = next(iter(self._live))
                del self._live[evict]
                self.dropped += 1
        trace = get_tracer()
        if trace.enabled:
            trace.flow_start("reqpath", node.req, cat="reqpath")
        return node

    # ------------------------------------------------------ node finish
    def _node_finished(self, node: ReqNode) -> None:
        record = None
        with self._lock:
            live = self._live.get(node.req)
            if live is None:
                return
            live.pending -= 1
            if live.pending <= 0 and live.root.t1 is not None:
                del self._live[node.req]
                record = self._assemble(live)
                self._records.append(record)
                self.emitted += 1
        if record is None:
            return
        trace = get_tracer()
        if trace.enabled:
            trace.flow_finish("reqpath", node.req, cat="reqpath",
                              ts=live.root.t1)
        if self.emit_to_sink:
            get_sink().emit("request", **record)

    # ---------------------------------------------------------- assembly
    @staticmethod
    def delivering_chain(nodes: List[dict]) -> List[dict]:
        """Follow ``won_by`` from the root: the path whose outcome the
        caller actually received.  The chain ends at the first node with
        no ``won_by`` — a leaf when a component resolved it, the
        interior node itself when a client-side timer did."""
        by_id = {n["node"]: n for n in nodes}
        root = next((n for n in nodes if n["parent"] is None), None)
        chain = []
        cur = root
        while cur is not None:
            chain.append(cur)
            cur = by_id.get(cur.get("won_by"))
        return chain

    def _assemble(self, live: _LiveReq) -> dict:
        # caller holds the lock
        root = live.root
        nodes = [n.as_dict() for n in live.nodes]
        e2e_ms = nodes[0]["dur_ms"] if nodes else 0.0
        chain = self.delivering_chain(nodes)
        covered_ms = sum(sum(n["hops_ms"].values()) for n in chain)
        return {
            "req": root.req,
            "t": round(root.t0, 6),
            "e2e_ms": e2e_ms,
            "status": root.status,
            "sampled_1_in": self.sample,
            "chain": [n["node"] for n in chain],
            "chain_hops_ms": round(covered_ms, 3),
            "hop_coverage": (round(covered_ms / e2e_ms, 4)
                             if e2e_ms > 0 else 1.0),
            "nodes": nodes,
        }

    # ----------------------------------------------------------- readout
    def records(self) -> List[dict]:
        """The bounded in-memory copy of emitted request records
        (newest last)."""
        with self._lock:
            return list(self._records)

    @property
    def live(self) -> int:
        with self._lock:
            return len(self._live)

    def attach_registry(self, registry) -> None:
        """Expose emitted/dropped/live through a shared ``obs.Registry``
        (weakref collector — the ServeMetrics discipline)."""
        import weakref

        ref = weakref.ref(self)

        def _collect():
            r = ref()
            if r is None:
                return []
            return [
                ("reqtrace_requests_total", {}, "counter",
                 float(r.emitted)),
                ("reqtrace_dropped_total", {}, "counter",
                 float(r.dropped)),
                ("reqtrace_live_requests", {}, "gauge", float(r.live)),
            ]

        registry.register_collector(_collect)


_reqtrace_lock = threading.Lock()
_reqtrace = NullReqTrace()


def get_reqtrace():
    """The process's current request recorder (``NullReqTrace`` when no
    run installed one) — instrumentation sites record through this
    unconditionally, like ``get_tracer``/``get_sink``."""
    return _reqtrace


def set_reqtrace(rec):
    """Install ``rec`` as the process default; returns the previous one
    so callers can restore it (``RunTelemetry`` does)."""
    global _reqtrace
    with _reqtrace_lock:
        prev = _reqtrace
        _reqtrace = rec if rec is not None else NullReqTrace()
        return prev
