"""Measured capacity model: per-replica saturation fitted from
telemetry history, answering ``replicas_needed(target_qps, objective)``.

The autoscaler question is never "what is the load" — the history store
answers that — it is "how many replicas does THIS load need to stay
inside THIS objective".  Guessing that from specs is how fleets end up
sized by folklore; this model fits it from what the router actually
measured:

- :meth:`CapacityModel.fit` slices an ``obs.history.HistoryStore`` into
  fixed windows and derives, per window, the *exact-counter* load line:
  QPS from the ``<prefix>_completed_total`` delta, mean latency from
  the ``<prefix>_latency_seconds_sum/_count`` deltas (both exact —
  counter differences, no reservoir involved), the last-sampled p99
  gauge, and mean batch occupancy (``prefix`` picks the serving layer:
  ``serve`` for one batcher, ``pool`` for the replicated rollup);
- the **knee** is the highest measured QPS whose latency still met the
  objective (explicit ``objective_ms``, or ``knee_factor ×`` the
  unloaded base latency — the classic hockey-stick definition).  No
  curve family is assumed: the model interpolates measurements, it does
  not extrapolate a queueing formula;
- :meth:`replicas_needed` divides the target through the knee-derived
  per-replica capacity with a headroom derate, and FLAGS what it cannot
  know: ``extrapolated`` when the target exceeds anything measured,
  ``objective_unmet`` when no measured window met the objective at all
  (the honest answer is "add replicas and re-measure", not a number
  dressed up as one).

Windowed p99 is *not* derivable from the registry's cumulative
reservoir gauge (it summarizes the whole run, not the window) — the
model records the last-sampled p99 per window as a reference signal and
fits the knee on whichever latency signal the caller names
(``objective_on="mean"`` by default, the exact one).
"""
from __future__ import annotations

import math
import weakref
from typing import Dict, List, Optional, Sequence

from ..obs.history import series_key

#: latency multiple over the unloaded base above which a window counts
#: as saturated when no explicit objective is given
DEFAULT_KNEE_FACTOR = 2.0

#: default derate on the knee when sizing: run fleets at ≤85% of the
#: measured saturation point so transient bursts land in margin, not in
#: the queue
DEFAULT_HEADROOM = 0.85


class CapacityModel:
    """Measured (qps → latency) points for one deployment and the
    capacity answers derived from them.  Build via :meth:`fit` (from a
    history store) or :meth:`fit_from_points` (tests, offline
    analysis)."""

    def __init__(self, points: List[dict], *, replicas: int = 1,
                 objective_ms: Optional[float] = None,
                 objective_on: str = "mean",
                 knee_factor: float = DEFAULT_KNEE_FACTOR,
                 max_batch: Optional[int] = None,
                 meta: Optional[dict] = None):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if objective_on not in ("mean", "p99"):
            raise ValueError(
                f"objective_on must be 'mean' or 'p99', got "
                f"{objective_on!r}")
        #: per-window measurements, qps-ascending:
        #: {qps, mean_ms, p99_ms?, occupancy?, t0?, t1?, completed?}
        self.points = sorted((dict(p) for p in points),
                             key=lambda p: p["qps"])
        self.replicas = int(replicas)
        self.objective_on = objective_on
        self.knee_factor = float(knee_factor)
        self.max_batch = max_batch
        self.meta = dict(meta or {})
        # unloaded base latency: median of the lowest-qps quartile —
        # robust to one weird window, defined whenever any point exists
        self.base_ms: Optional[float] = None
        if self.points:
            q = self.points[:max(1, len(self.points) // 4)]
            lat = sorted(self._lat(p) for p in q)
            self.base_ms = lat[len(lat) // 2]
        self.objective_ms = (float(objective_ms)
                             if objective_ms is not None else
                             (self.base_ms * self.knee_factor
                              if self.base_ms is not None else None))
        self.knee_qps: Optional[float] = None
        self.knee_occupancy: Optional[float] = None
        if self.objective_ms is not None:
            met = [p for p in self.points
                   if self._lat(p) <= self.objective_ms]
            if met:
                knee = max(met, key=lambda p: p["qps"])
                self.knee_qps = knee["qps"]
                self.knee_occupancy = knee.get("occupancy")
        self.measured_max_qps = (self.points[-1]["qps"]
                                 if self.points else None)

    def _lat(self, p: dict) -> float:
        if self.objective_on == "p99" and p.get("p99_ms") is not None:
            return p["p99_ms"]
        return p["mean_ms"]

    # ------------------------------------------------------------ fitting
    @classmethod
    def fit(cls, store, *, window_s: float = 5.0, replicas: int = 1,
            model: Optional[str] = None, prefix: str = "serve",
            objective_ms: Optional[float] = None,
            objective_on: str = "mean",
            knee_factor: float = DEFAULT_KNEE_FACTOR,
            max_batch: Optional[int] = None) -> "CapacityModel":
        """Fit from a history store's raw rings.  ``model`` selects the
        per-tier label dimension of a multi-model deployment (None = the
        unlabeled single-model series); ``prefix`` selects the serving
        layer whose families to read — ``"serve"`` for a single
        batcher, ``"pool"`` for the replicated rollup (``EnginePool`` /
        ``ProcessRouter`` export the same family set under that
        prefix).  Windows with no completions are dropped — an idle
        window measures nothing about capacity."""
        base = {"model": model} if model else {}
        completed = cls._raw(store, f"{prefix}_completed_total", base)
        lat_sum = cls._raw(store, f"{prefix}_latency_seconds_sum", base)
        lat_count = cls._raw(store, f"{prefix}_latency_seconds_count",
                             base)
        p99 = cls._raw(store, f"{prefix}_latency_seconds",
                       {**base, "quantile": "0.99"})
        occ = cls._raw(store, f"{prefix}_batch_occupancy_mean", base)
        points: List[dict] = []
        if completed and window_s > 0:
            t0 = completed[0][0]
            t_end = completed[-1][0]
            n_windows = max(1, int(math.ceil((t_end - t0) / window_s)))
            for i in range(n_windows):
                lo, hi = t0 + i * window_s, t0 + (i + 1) * window_s
                w = [(t, v) for t, v in completed if lo <= t <= hi]
                if len(w) < 2:
                    continue
                (ta, ca), (tb, cb) = w[0], w[-1]
                dt, dc = tb - ta, cb - ca
                if dt <= 0 or dc <= 0:
                    continue
                ls = cls._delta(lat_sum, ta, tb)
                lc = cls._delta(lat_count, ta, tb)
                if ls is None or lc is None or lc <= 0:
                    continue
                pt = {"t0": ta, "t1": tb, "completed": dc,
                      "qps": dc / dt, "mean_ms": ls / lc * 1e3}
                p99_w = [v for t, v in p99 if lo <= t <= hi]
                if p99_w:
                    pt["p99_ms"] = p99_w[-1] * 1e3
                occ_w = [v for t, v in occ if lo <= t <= hi]
                if occ_w:
                    pt["occupancy"] = math.fsum(occ_w) / len(occ_w)
                points.append(pt)
        return cls(points, replicas=replicas, objective_ms=objective_ms,
                   objective_on=objective_on, knee_factor=knee_factor,
                   max_batch=max_batch,
                   meta={"window_s": window_s, "model": model,
                         "prefix": prefix,
                         "run_id": getattr(store, "run_id", None)})

    @classmethod
    def fit_from_points(cls, pts: Sequence, **kw) -> "CapacityModel":
        """From bare ``(qps, mean_ms)`` pairs (or ready dicts) — the
        test/offline entry that skips the history slicing."""
        points = [p if isinstance(p, dict)
                  else {"qps": float(p[0]), "mean_ms": float(p[1])}
                  for p in pts]
        return cls(points, **kw)

    @staticmethod
    def _raw(store, name: str, labels: Dict[str, str]) -> List:
        try:
            return store.query(series_key(name, labels))["points"]
        except KeyError:
            return []

    @staticmethod
    def _delta(pts: List, ta: float, tb: float) -> Optional[float]:
        """Counter delta between the newest samples at or before each
        endpoint — exact, because the underlying signals are counters."""
        va = vb = None
        for t, v in pts:
            if t <= ta:
                va = v
            if t <= tb:
                vb = v
            else:
                break
        if va is None or vb is None:
            return None
        return vb - va

    # ------------------------------------------------------------ answers
    def per_replica_qps(self) -> Optional[float]:
        """Measured per-replica saturation throughput (the knee split
        across the replicas that produced it)."""
        if self.knee_qps is None:
            return None
        return self.knee_qps / self.replicas

    def occupancy_headroom(self) -> Optional[float]:
        """``1 − occupancy_at_knee / max_batch`` — how much batch room
        was left at the knee (None without occupancy or ``max_batch``).
        Near-zero headroom says the knee is batch-bound: bigger batches,
        not more replicas, may be the cheaper lever."""
        if (self.knee_occupancy is None or not self.max_batch
                or self.max_batch <= 0):
            return None
        return max(0.0, 1.0 - self.knee_occupancy / self.max_batch)

    def replicas_needed(self, target_qps: float,
                        objective_ms: Optional[float] = None,
                        headroom: float = DEFAULT_HEADROOM) -> dict:
        """Replicas required to serve ``target_qps`` inside the
        objective, derated by ``headroom``.  ``replicas`` is None when
        the model cannot honestly answer (no measurements, or no
        measured window met the objective) — the flags say why."""
        if objective_ms is not None and objective_ms != self.objective_ms:
            # re-evaluate the knee under the caller's objective
            m = CapacityModel(self.points, replicas=self.replicas,
                              objective_ms=objective_ms,
                              objective_on=self.objective_on,
                              knee_factor=self.knee_factor,
                              max_batch=self.max_batch, meta=self.meta)
            return m.replicas_needed(target_qps, headroom=headroom)
        per = self.per_replica_qps()
        out = {
            "target_qps": float(target_qps),
            "objective_ms": self.objective_ms,
            "objective_on": self.objective_on,
            "knee_qps": self.knee_qps,
            "per_replica_qps": per,
            "headroom": float(headroom),
            "measured_max_qps": self.measured_max_qps,
            "objective_unmet": (bool(self.points)
                                and self.knee_qps is None),
            "extrapolated": (
                self.measured_max_qps is not None
                and float(target_qps) > self.measured_max_qps),
            "replicas": None,
        }
        if per is not None and per > 0 and headroom > 0:
            out["replicas"] = max(
                1, int(math.ceil(float(target_qps) / (per * headroom))))
        return out

    def to_dict(self) -> dict:
        """JSON-ready model document (the audit artifact embeds it)."""
        return {
            "replicas": self.replicas,
            "objective_ms": self.objective_ms,
            "objective_on": self.objective_on,
            "knee_factor": self.knee_factor,
            "max_batch": self.max_batch,
            "base_ms": self.base_ms,
            "knee_qps": self.knee_qps,
            "per_replica_qps": self.per_replica_qps(),
            "knee_occupancy": self.knee_occupancy,
            "occupancy_headroom": self.occupancy_headroom(),
            "measured_max_qps": self.measured_max_qps,
            "windows": len(self.points),
            "points": [dict(p) for p in self.points],
            "meta": dict(self.meta),
        }

    # --------------------------------------------------------- telemetry
    def register_into(self, registry) -> "CapacityModel":
        """Export the fitted answers as ``capacity_*`` gauges (weakref
        collector, per the subsystem precedent) so /metrics — and the
        history store sampling it — carries the capacity picture the
        fleet was last sized from."""
        ref = weakref.ref(self)

        def _collect():
            m = ref()
            if m is None:
                return []
            out = [
                ("capacity_windows", {}, "gauge", float(len(m.points)),
                 "measured (qps, latency) windows in the fit"),
                ("capacity_replicas", {}, "gauge", float(m.replicas),
                 "replica count the measurements were taken at"),
            ]
            if m.base_ms is not None:
                out.append(("capacity_base_latency_ms", {}, "gauge",
                            m.base_ms, "unloaded base latency"))
            if m.objective_ms is not None:
                out.append(("capacity_objective_ms", {}, "gauge",
                            m.objective_ms, "latency objective in force"))
            if m.knee_qps is not None:
                out.append(("capacity_knee_qps", {}, "gauge",
                            m.knee_qps,
                            "highest measured QPS inside the objective"))
            per = m.per_replica_qps()
            if per is not None:
                out.append(("capacity_per_replica_qps", {}, "gauge",
                            per, "knee split per replica"))
            if m.measured_max_qps is not None:
                out.append(("capacity_measured_max_qps", {}, "gauge",
                            m.measured_max_qps,
                            "highest QPS measured at all"))
            hr = m.occupancy_headroom()
            if hr is not None:
                out.append(("capacity_occupancy_headroom", {}, "gauge",
                            hr, "batch room left at the knee"))
            return out

        registry.register_collector(_collect)
        return self
