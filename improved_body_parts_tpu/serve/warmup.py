"""Startup warmup: precompile every program the configured traffic can
touch, before the first request arrives.

First-compile of a compact batch program costs tens of seconds on a
relay-attached chip; paid lazily it lands as a tail-latency spike on the
first unlucky request in each shape bucket.  Paid here — at startup,
through the persistent compilation cache (``utils.platform
.enable_compile_cache``) — the first process of a deployment compiles
once and every later process loads from the cache in milliseconds.

The unit of work is (bucket shape × batch size):
``Predictor.enumerate_bucket_shapes`` maps the deployment's expected
image sizes onto padded lane shapes, and :func:`pow2_batch_sizes` lists
every chunk size the batcher's binary-decomposition dispatch can emit.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple


def pow2_batch_sizes(max_batch: int) -> Tuple[int, ...]:
    """Every power of two ≤ ``max_batch`` — the complete set of chunk
    sizes ``predict_compact_batch_async``'s binary decomposition can
    dispatch for any occupancy ≤ ``max_batch``; precompiling exactly
    these makes every possible flush compile-free."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    return tuple(1 << i for i in range((max_batch).bit_length())
                 if (1 << i) <= max_batch)


def precompile(predictors, image_sizes: Sequence[Tuple[int, int]],
               max_batch: int = 8, params=None,
               batch_sizes: Optional[Sequence[int]] = None,
               decode: bool = False) -> dict:
    """Warm a predictor — or a whole predictor SET — for serving:
    compile (or cache-load) the compact-batch program for every bucket
    the given (H, W) image sizes land in, at every batch size
    ``max_batch``-occupancy dispatch can emit.  Blocks until all
    executables exist.  ``decode=True`` warms the FUSED device-decode
    programs instead — what the batcher's default device-decode lane
    dispatches.

    ``predictors`` may be one predictor or a sequence: the batcher's
    device replicas and the cascade's student/teacher tiers
    (``serve.cascade``) all warm through THIS one path, so a new
    program family added here warms every deployment shape at once
    instead of growing per-caller warmup loops.  Bucket shapes are
    enumerated PER predictor (tiers may bucket differently) and the
    summary reports their union.

    Returns ``{"bucket_shapes", "batch_sizes", "newly_compiled"}`` —
    ``newly_compiled == 0`` means every predictor was already fully
    warm (the signal the no-compile-stall test asserts on; replicas
    sharing one program cache report their programs once).
    """
    preds = (list(predictors) if isinstance(predictors, (list, tuple))
             else [predictors])
    sizes = (tuple(batch_sizes) if batch_sizes is not None
             else pow2_batch_sizes(max_batch))
    all_shapes = set()
    compiled = 0
    for predictor in preds:
        shapes = predictor.enumerate_bucket_shapes(image_sizes, params)
        all_shapes.update(shapes)
        compiled += predictor.precompile_compact(shapes, sizes,
                                                 params=params,
                                                 decode=decode)
    return {"bucket_shapes": sorted(all_shapes), "batch_sizes": sizes,
            "newly_compiled": compiled}
