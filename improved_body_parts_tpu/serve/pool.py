"""Fault-tolerant replica pool: N shared-nothing ``DynamicBatcher``
engines behind one health-checked, failover-capable router.

One ``DynamicBatcher`` is one failure domain: a wedged fetcher, a dying
decode pool or one slow device takes every request and every stream
down with it.  :class:`EnginePool` is the control plane above it
(ROADMAP "fleet-scale serving"): each replica is a whole batcher with
its own dispatcher/fetcher/decode threads (shared-nothing — replicas
never share mutable state, only the process), and the pool adds:

- **health-checked routing** — a probe thread samples each replica's
  :meth:`DynamicBatcher.health` (thread liveness + the ``ServeMetrics``
  stall clock: queue depth stuck above zero with no completions for
  ``wedge_timeout_s`` means wedged) and requests route to the
  least-loaded LIVE replica;
- **circuit breaking** — per-replica :class:`serve.breaker
  .CircuitBreaker` fed by request outcomes; a replica whose failure
  rate trips the breaker is treated exactly like a crashed one;
- **fencing + failover** — a replica that wedges, crashes a stage
  thread, stops out from under the pool, or trips its breaker is
  FENCED: routing stops, a drain thread runs the batcher's bounded
  graceful stop, and every in-flight request the drain fails is
  **re-submitted to a healthy replica**.  The pool hands out its own
  futures, so failover is invisible to callers: every ``submit()``
  resolves with a result or a typed error, never silently lost;
- **recovery** — :meth:`restart` (or ``restart_after_s`` for automatic
  probation) brings a fenced replica back: the batcher restarts, and a
  breaker-fenced replica re-enters through HALF-OPEN probes instead of
  full traffic.

``stream.SessionManager`` runs unchanged on top of a pool (same
``submit``/``draining`` contract as a single batcher), which is what
makes live streams survive a replica death mid-stream: the session's
in-order delivery machinery doesn't care which replica resolved a
frame.  Proven end to end by ``tools/chaos_serve.py`` →
``SERVE_CHAOS.json``.
"""
from __future__ import annotations

import itertools
import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs.reqtrace import NULL_NODE, get_reqtrace
from .batcher import DeadlineExceeded, DynamicBatcher, ServerOverloaded
from .breaker import CircuitBreaker
from .metrics import ServeMetrics

_PRID = itertools.count(1)

#: replica lifecycle states -> gauge codes
REPLICA_STATE_CODES = {"live": 0.0, "fenced": 1.0, "restarting": 2.0}


class _PoolRequest:
    __slots__ = ("image", "future", "t_submit", "deadline", "attempts",
                 "tried", "finished", "rid", "ctx", "attempt_log",
                 "last_error_type")

    def __init__(self, image, deadline_s: Optional[float]):
        self.image = image
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = (None if deadline_s is None
                         else self.t_submit + deadline_s)
        self.attempts = 0          # failover re-submissions so far
        self.tried: set = set()    # replica indices that failed it
        self.finished = False
        self.rid = next(_PRID)
        self.ctx = NULL_NODE       # reqtrace node (obs.reqtrace)
        # (child_node, t_admitted) per engine attempt, in order — what
        # lets the finish hop account name the time burned on attempts
        # that failed over before the winner's
        self.attempt_log: list = []
        self.last_error_type: Optional[str] = None

    def remaining(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - time.perf_counter()


class _Replica:
    __slots__ = ("engine", "breaker", "state", "fence_reason",
                 "fenced_at", "drain")

    def __init__(self, engine, breaker: CircuitBreaker):
        self.engine = engine
        self.breaker = breaker
        self.state = "live"
        self.fence_reason: Optional[str] = None
        self.fenced_at = 0.0
        self.drain: Optional[threading.Thread] = None  # fence's drain


class EnginePool:
    """Health-checked router over N ``DynamicBatcher`` replicas.

    ::

        engines = [DynamicBatcher(pred_a, ...), DynamicBatcher(pred_b, ...)]
        with EnginePool(engines, wedge_timeout_s=2.0) as pool:
            pool.warmup([(256, 256)])
            fut = pool.submit(image)           # same contract as a batcher
            skeletons = fut.result()

    Replicas must be SHARED-NOTHING: each engine gets its own predictor
    (``Predictor.device_replica`` per device, or independent predictors
    on one host) — two batchers driving one predictor object would race
    its program cache from two dispatcher threads.

    Knobs: ``probe_interval_s`` (health sampling cadence),
    ``wedge_timeout_s`` (stall age past which an in-flight replica is
    wedged), ``drain_timeout_s`` (bound on a fenced replica's graceful
    drain — past it the batcher fails stranded futures and the pool
    fails them over), ``max_failovers`` (re-submission bound per
    request, default one try per replica), ``breaker_kw`` (forwarded to
    each replica's :class:`CircuitBreaker`), ``fence_on_breaker``
    (a tripped breaker fences the replica instead of merely gating
    routing), ``restart_after_s`` (automatic probation for fenced
    replicas; ``None`` = :meth:`restart` is manual).
    """

    def __init__(self, engines: Sequence[DynamicBatcher], *,
                 probe_interval_s: float = 0.2,
                 wedge_timeout_s: float = 10.0,
                 drain_timeout_s: float = 5.0,
                 max_failovers: Optional[int] = None,
                 breaker_kw: Optional[dict] = None,
                 fence_on_breaker: bool = True,
                 restart_after_s: Optional[float] = None,
                 on_fence: Optional[Callable[[int, str], None]] = None,
                 metrics: Optional[ServeMetrics] = None,
                 registry=None, slo=None,
                 qos_class: str = "interactive"):
        if not engines:
            raise ValueError("EnginePool needs at least one engine")
        kw = dict(breaker_kw or {})
        self._replicas = [_Replica(e, CircuitBreaker(**kw))
                          for e in engines]
        self.probe_interval_s = probe_interval_s
        self.wedge_timeout_s = wedge_timeout_s
        self.drain_timeout_s = drain_timeout_s
        self.max_failovers = (len(engines) if max_failovers is None
                              else max_failovers)
        self.fence_on_breaker = fence_on_breaker
        self.restart_after_s = restart_after_s
        self._on_fence = on_fence
        # pool-level request accounting rides the same ServeMetrics
        # conservation contract as a single engine: submitted ==
        # completed + failed + depth, across any number of failovers
        # (one pool request is ONE submit no matter how many replicas
        # it visited)
        self.metrics = metrics or ServeMetrics()
        # optional SLO wiring: pool-level outcomes are what the caller
        # experiences (failover absorbed), so this is the natural SLO
        # attachment point for a replicated deployment WITHOUT a
        # hedging PolicyClient above — every hedge is a SECOND pool
        # submit, so under hedging the pool records attempts, not
        # caller requests: attach to the PolicyClient there instead
        # (attach at ONE layer — see DynamicBatcher)
        self._slo = slo
        self._qos_class = qos_class
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {
            "failovers": 0,      # replica attempts that failed over
            "resubmitted": 0,    # re-submissions that were admitted
            "fenced": 0,
            "restarts": 0,
        }
        self._running = False
        self._draining = False
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._drain_threads: List[threading.Thread] = []
        # the batcher's stop discipline, one level up: concurrent
        # stop() callers serialize; the first drains, the rest wait
        self._stop_lock = threading.Lock()
        if registry is not None:
            self.register_into(registry)

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "EnginePool":
        if self._running:
            return self
        for r in self._replicas:
            r.engine.start()
        self._running = True
        self._probe_stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="pool-probe", daemon=True)
        self._probe_thread.start()
        return self

    def stop(self, drain_timeout_s: Optional[float] = None) -> None:
        """Drain the whole pool: admission closes first (the
        ``ServerOverloaded`` rolling-restart contract), every replica
        runs its bounded graceful stop against ONE shared deadline, and
        in-flight pool requests resolve — with results where the drains
        complete, with the drain error where they don't (no failover
        during pool shutdown: there is nowhere left to go).  Idempotent
        and thread-safe under concurrent callers."""
        with self._stop_lock:
            self._stop_locked(drain_timeout_s)

    def _stop_locked(self, drain_timeout_s: Optional[float]) -> None:
        if not self._running and self._probe_thread is None:
            return
        self._draining = True
        self._running = False
        deadline = (None if drain_timeout_s is None
                    else time.perf_counter() + drain_timeout_s)

        def remaining() -> Optional[float]:
            if deadline is None:
                return None
            return max(0.0, deadline - time.perf_counter())

        self._probe_stop.set()
        probe, self._probe_thread = self._probe_thread, None
        if probe is not None:
            probe.join(remaining())
        for r in self._replicas:
            r.engine.stop(drain_timeout_s=remaining())
        with self._lock:
            drains = list(self._drain_threads)
            self._drain_threads = []
        for t in drains:
            t.join(remaining())
        self._draining = False

    def __enter__(self) -> "EnginePool":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def draining(self) -> bool:
        """True once a pool-wide stop began (the session/policy layers
        read this through the same duck-typed contract as a batcher)."""
        return self._draining

    @property
    def engines(self) -> List[DynamicBatcher]:
        return [r.engine for r in self._replicas]

    def replica_states(self) -> List[dict]:
        """Snapshot of every replica's routing state (JSON-ready)."""
        out = []
        with self._lock:
            replicas = list(self._replicas)
        for i, r in enumerate(replicas):
            out.append({
                "replica": i,
                "state": r.state,
                "fence_reason": r.fence_reason,
                "breaker": r.breaker.state,
                "queue_depth": r.engine.metrics.depth,
            })
        return out

    # ------------------------------------------------------------- warmup
    def warmup(self, image_sizes: Sequence[Tuple[int, int]],
               batch_sizes: Optional[Sequence[int]] = None) -> dict:
        """Precompile every replica's bucket programs (replicas share
        the process program cache, so the first replica pays and the
        rest warm their executables from it)."""
        out = None
        for r in self._replicas:
            info = r.engine.warmup(image_sizes, batch_sizes=batch_sizes)
            out = out or info
        return out

    # ------------------------------------------------------------- submit
    def submit(self, image, *,
               deadline_s: Optional[float] = None) -> Future:
        """Route one request to the least-loaded healthy replica;
        returns a POOL future that always resolves — with the decoded
        skeletons, with :class:`DeadlineExceeded`, or with the last
        replica error once failover is exhausted.  A replica failure
        mid-flight is retried on another healthy replica without the
        caller noticing.

        :raises ServerOverloaded: every healthy replica shed the
            request (or none is healthy) — the retry-with-backoff
            status, exactly as from a single batcher.
        :raises DeadlineExceeded: ``deadline_s`` non-positive at submit.
        :raises RuntimeError: the pool is not running.
        """
        if self._draining:
            self.metrics.on_reject()
            raise ServerOverloaded(
                "pool is draining (shutdown in progress); retry "
                "against a live pool")
        if not self._running:
            raise RuntimeError("EnginePool is not running "
                               "(use `with pool:` or call start())")
        if deadline_s is not None and deadline_s <= 0:
            self.metrics.on_expire_rejected()
            raise DeadlineExceeded(
                f"deadline_s={deadline_s} already expired at submit")
        preq = _PoolRequest(image, deadline_s)
        rt = get_reqtrace()
        if rt.enabled:
            preq.ctx = rt.begin("pool")
        if not self._route(preq, first=True):
            # the node opened above MUST close on this raise path too:
            # an unfinished node wedges its request's tree forever (the
            # record never emits, the recorder's live entry leaks)
            preq.ctx.finish("error:ServerOverloaded")
            self.metrics.on_reject()
            raise ServerOverloaded(
                "no healthy replica admitted the request (all fenced, "
                "open-breaker, or shedding); retry with backoff")
        return preq.future

    # ------------------------------------------------------------ routing
    def _candidates(self, exclude: set) -> List[int]:
        with self._lock:
            live = [i for i, r in enumerate(self._replicas)
                    if r.state == "live" and i not in exclude]
        # least-loaded first: the replica ServeMetrics depth is the
        # admitted-not-done count, the same signal the dispatcher's
        # in-flight routing uses one level down
        return sorted(live,
                      key=lambda i: self._replicas[i].engine.metrics.depth)

    def _route(self, preq: _PoolRequest, *, first: bool) -> bool:
        """Try to place ``preq`` on a healthy replica.  Returns True
        when the request was admitted somewhere (or resolved on the
        spot); False when every candidate refused — the caller decides
        whether that is a submit-time ``ServerOverloaded`` (first
        placement) or a failover give-up."""
        # the causal hop edge this placement creates: a first placement
        # is a plain submit; a re-placement after a replica failure is
        # a FAILOVER edge annotated with the error that forced it
        kind = "submit" if first else "failover"
        reason = None if first else preq.last_error_type
        for idx in self._candidates(preq.tried):
            r = self._replicas[idx]
            if not r.breaker.allow():
                continue
            with preq.ctx.child_scope(kind, reason) as scope:
                try:
                    fut = r.engine.submit(preq.image,
                                          deadline_s=preq.remaining())
                except ServerOverloaded:
                    # shed is backpressure, not a fault: no breaker
                    # outcome — but give back the half-open probe slot
                    # it consumed
                    r.breaker.release_probe()
                    continue
                except DeadlineExceeded as e:
                    # the GLOBAL deadline lapsed while routing: resolve
                    r.breaker.release_probe()
                    self._finish(preq, error=e, first=first)
                    return True
                except RuntimeError:
                    # replica stopped between the health read and
                    # submit; the probe loop will fence it — move on
                    r.breaker.release_probe()
                    continue
            preq.attempt_log.append((scope.node, time.perf_counter()))
            if first:
                self.metrics.on_submit()
            else:
                with self._lock:
                    self._counters["resubmitted"] += 1
            # attach AFTER the pool-level on_submit so completion
            # accounting can never run ahead of submission accounting
            fut.add_done_callback(
                lambda f, i=idx, nd=scope.node:
                self._on_replica_done(preq, i, f, nd))
            return True
        return False

    def _on_replica_done(self, preq: _PoolRequest, idx: int,
                         fut: Future, node=None) -> None:
        """One replica attempt resolved (runs on that replica's
        completion threads): deliver, or fail over.  ``node`` is the
        attempt's reqtrace child — the ``won_by`` chain link when this
        attempt's outcome is the one delivered."""
        t_done = time.perf_counter()
        try:
            result = fut.result()
            error = None
        except BaseException as e:  # noqa: BLE001 — classified below
            result, error = None, e
        r = self._replicas[idx]
        if error is None:
            r.breaker.record_success()
            self._finish(preq, result=result, node=node, t_done=t_done)
            return
        if isinstance(error, DeadlineExceeded):
            # the deadline is global to the request: another replica
            # cannot un-expire it, and a deadline says nothing about
            # THIS replica's health — no breaker outcome, no failover.
            # But a half-open probe slot consumed at routing must come
            # back (no outcome will ever be recorded for it), or
            # enough expiring probes would wedge the breaker half-open
            r.breaker.release_probe()
            self._finish(preq, error=error, node=node, t_done=t_done)
            return
        r.breaker.record_failure()
        if self.fence_on_breaker and r.breaker.state == "open":
            self.fence(idx, "breaker_open")
        preq.tried.add(idx)
        preq.attempts += 1
        preq.last_error_type = type(error).__name__
        with self._lock:
            self._counters["failovers"] += 1
        if self._draining or preq.attempts > self.max_failovers or \
                (preq.deadline is not None and preq.remaining() <= 0):
            self._finish(preq, error=error, node=node, t_done=t_done)
            return
        try:
            placed = self._route(preq, first=False)
        except Exception as e:  # noqa: BLE001 — a routing bug must fail
            # THIS request, never strand it or kill a fetch thread
            self._finish(preq, error=e, node=node, t_done=t_done)
            return
        if not placed:
            # nowhere healthy left: the caller gets the replica error
            # (typed), not a hang
            self._finish(preq, error=error, node=node, t_done=t_done)

    def _finish(self, preq: _PoolRequest, result=None,
                error: Optional[BaseException] = None,
                first: bool = False, node=None,
                t_done: Optional[float] = None) -> None:
        """Resolve one pool request exactly once (the `_finish`
        discipline one level up: callbacks from a drained replica and a
        successful failover may race here)."""
        with self._lock:
            if preq.finished:
                return
            preq.finished = True
        if preq.ctx.sampled:
            # the pool node's hop bookends around its children's
            # windows: route (candidate selection + admission before
            # the first placement), prior_attempts (the gap hop — time
            # burned on attempts that failed over before the winning
            # one was even submitted), deliver (winner's resolution →
            # pool future).  The winner's own span covers the middle.
            t_fin = time.perf_counter()
            hops = []
            log = preq.attempt_log
            if log:
                hops.append(("route", log[0][1] - preq.t_submit))
                if node is not None:
                    widx = next((i for i, (nd, _) in enumerate(log)
                                 if nd is node), None)
                    if widx:
                        hops.append(("prior_attempts",
                                     log[widx][1] - log[0][1]))
            if t_done is not None:
                hops.append(("deliver", t_fin - t_done))
            preq.ctx.finish(
                "ok" if error is None
                else f"error:{type(error).__name__}",
                hops=hops, won_by=node, failovers=preq.attempts)
        if first:
            # resolved during its own submit() call, before the pool
            # counted it submitted: count both sides so conservation
            # (submitted == completed + failed + depth) stays exact
            self.metrics.on_submit()
        if self._slo is not None:
            self._slo.record(self._qos_class,
                             time.perf_counter() - preq.t_submit,
                             error=error is not None)
        try:
            if error is not None:
                self.metrics.on_fail(
                    expired=isinstance(error, DeadlineExceeded))
                preq.future.set_exception(error)
            else:
                self.metrics.on_complete(time.perf_counter()
                                         - preq.t_submit)
                preq.future.set_result(result)
        except Exception:  # noqa: BLE001 — future cancelled by caller;
            # the outcome is still accounted
            pass

    # ----------------------------------------------------- fence / revive
    def fence(self, idx: int, reason: str) -> bool:
        """Take replica ``idx`` out of routing and drain it in the
        background: the batcher's bounded graceful stop completes what
        it can, fails the rest, and those failures arrive at
        :meth:`_on_replica_done` — which re-submits them to healthy
        replicas.  Idempotent per fence; returns True when this call
        did the fencing."""
        with self._lock:
            r = self._replicas[idx]
            if r.state != "live":
                return False
            r.state = "fenced"
            r.fence_reason = reason
            r.fenced_at = time.monotonic()
            self._counters["fenced"] += 1
            if not self._draining:
                # pool stop() drains every replica itself — a fence
                # racing it must not spawn a drain thread the join
                # snapshot already missed.  The thread is STARTED
                # before it becomes visible (r.drain / the join list /
                # the fenced state other threads react to): a restart
                # or pool stop joining a not-yet-started Thread raises.
                # Dead threads from earlier fence cycles are pruned
                # here so a long-lived pool's join list stays bounded.
                drain = threading.Thread(
                    target=self._drain_replica, args=(idx,),
                    name=f"pool-drain-{idx}", daemon=True)
                drain.start()
                self._drain_threads = [t for t in self._drain_threads
                                       if t.is_alive()] + [drain]
                r.drain = drain
        from ..obs.events import get_sink

        get_sink().emit("replica_fenced", replica=idx, reason=reason)
        cb = self._on_fence
        if cb is not None:
            try:
                cb(idx, reason)
            except Exception:  # noqa: BLE001 — an observer bug must not
                pass           # break fencing
        return True

    def _drain_replica(self, idx: int) -> None:
        try:
            self._replicas[idx].engine.stop(
                drain_timeout_s=self.drain_timeout_s)
        except Exception:  # noqa: BLE001 — a drain crash leaves the
            # replica fenced; its futures were failed by the batcher's
            # own machinery or will fail at pool stop
            pass

    def restart(self, idx: int) -> bool:
        """Bring a fenced replica back into routing.  The batcher
        restarts (its program cache survives, so no recompiles), and a
        breaker-fenced replica re-enters on HALF-OPEN probation —
        bounded probe traffic until the breaker closes — while other
        fences reset the breaker outright.

        The engine starts BEFORE routing resumes, through a transient
        ``restarting`` state the router and probe both skip: flipping
        to live first would let the probe read a not-yet-running engine
        and instantly re-fence it as ``stopped`` (and ``start()`` itself
        waits out any still-draining stop under the engine's stop
        lock, so a restart racing the fence drain cannot have its fresh
        pipeline torn down by the old drain's tail)."""
        with self._lock:
            r = self._replicas[idx]
            if r.state != "fenced":
                return False
            reason, r.fence_reason = r.fence_reason, None
            r.state = "restarting"
            drain, r.drain = r.drain, None
        if drain is not None:
            # the fence's drain may not even have ENTERED engine.stop()
            # yet — starting before it completes would hand the old
            # drain's tail a fresh pipeline to tear down.  The drain is
            # bounded (drain_timeout_s), so this join is too.
            drain.join()
        r.engine.start()
        if reason == "breaker_open":
            r.breaker.probation()
        else:
            r.breaker.reset()
        with self._lock:
            r.state = "live"
            self._counters["restarts"] += 1
        from ..obs.events import get_sink

        get_sink().emit("replica_restarted", replica=idx,
                        after=reason)
        return True

    # -------------------------------------------------------- health loop
    def _probe_loop(self) -> None:
        while not self._probe_stop.wait(self.probe_interval_s):
            for idx in range(len(self._replicas)):
                try:
                    self._probe_one(idx)
                except Exception:  # noqa: BLE001 — a probe bug must not
                    continue       # kill the health loop

    def _probe_one(self, idx: int) -> None:
        r = self._replicas[idx]
        if r.state == "fenced":
            if self.restart_after_s is not None and \
                    time.monotonic() - r.fenced_at >= self.restart_after_s:
                self.restart(idx)
            return
        if r.state != "live":
            return      # restarting: engine mid-start, not probe-able
        if self.fence_on_breaker and r.breaker.state == "open":
            self.fence(idx, "breaker_open")
            return
        h = r.engine.health()
        if not h["running"] and not h["draining"]:
            # stopped out from under the pool (a crash-equivalent):
            # fence so routing stops; the batcher's own stop already
            # failed its in-flight futures into failover
            self.fence(idx, "stopped")
            return
        if h["running"] and (not h["dispatcher_alive"]
                             or h["fetchers_alive"]
                             < h["fetchers_expected"]):
            self.fence(idx, "thread_crashed")
            return
        stall = h["stall_age_s"]
        if stall is not None and stall >= self.wedge_timeout_s:
            self.fence(idx, "wedged")

    # ---------------------------------------------------------- telemetry
    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    def register_into(self, registry) -> "EnginePool":
        """Export pool request accounting, per-replica routing/breaker
        state and every replica's own ServeMetrics (labeled
        ``{replica=N}``) through a shared ``obs.Registry`` — the
        weakref-collector discipline of ``ServeMetrics.register_into``.
        """
        import weakref

        ref = weakref.ref(self)

        def _collect():
            p = ref()
            return p.collect() if p is not None else []

        registry.register_collector(_collect)
        return self

    def collect(self, prefix: str = "pool"):
        """(name, labels, kind, value) samples for ``obs.Registry``."""
        samples = list(self.metrics.collect(prefix))
        counters = self.counters()
        for name, v in counters.items():
            samples.append((f"{prefix}_{name}_total", {}, "counter",
                            float(v)))
        with self._lock:
            replicas = list(self._replicas)
        for i, r in enumerate(replicas):
            labels = {"replica": str(i)}
            samples += [
                (f"{prefix}_replica_state_code", labels, "gauge",
                 REPLICA_STATE_CODES.get(r.state, -1.0)),
                (f"{prefix}_breaker_state_code", labels, "gauge",
                 r.breaker.state_code),
                (f"{prefix}_breaker_trips_total", labels, "counter",
                 float(r.breaker.trips)),
            ]
            for name, lbl, kind, value in r.engine.metrics.collect(
                    f"{prefix}_engine"):
                samples.append((name, {**lbl, **labels}, kind, value))
        return samples

    def snapshot(self) -> dict:
        """JSON-ready pool state (the chaos-artifact shape)."""
        return {
            "pool": self.metrics.snapshot(),
            "counters": self.counters(),
            "replicas": [
                {**state,
                 "metrics": r.engine.metrics.snapshot()}
                for state, r in zip(self.replica_states(),
                                    self._replicas)],
        }
