"""Asynchronous dynamic batcher: many concurrent single-image requests →
shape-bucketed compact batches on the chip.

Every inference entry point below this layer (``Predictor.predict*``,
``pipelined_inference``) consumes a pre-known iterable; this is the path
from independently-arriving requests to the chip.  E2E_BENCH.json shows
the compact path is forward-bound on-chip but only wins when the 2N
forward lanes are full, so throughput under real load hinges on batch
occupancy — the serving twin of the large-effective-batch principle the
training side exploits.

Design:

- **Admission** is bounded by ``max_queue`` in-flight requests (a
  semaphore held from submit to completion).  When full, :meth:`submit`
  raises :class:`ServerOverloaded` immediately — explicit load-shedding,
  never unbounded growth, and in-flight work keeps draining.
- **Coalescing**: a single dispatcher thread groups requests by
  ``Predictor.compact_lane_shape`` (the same ``pad_right_down`` bucket
  geometry every compact program is compiled against, so one jitted
  ``predict_compact_batch_async`` program per bucket serves all
  traffic).  A bucket flushes when it reaches ``max_batch`` occupancy or
  when its oldest request has waited ``max_wait_ms`` — the classic
  throughput/latency knob pair.
- **Completion**: the device program is dispatched asynchronously.  On
  the DEFAULT device-decode lane the program is the FUSED end-to-end
  decode (``Predictor.predict_decoded_batch_async``: forward + peak
  top-K + limb candidates + greedy assembly — ``ops.assembly`` — in one
  XLA program per batch); each request finishes with an O(people)
  coordinate lookup right on the fetch thread.  The decode thread pool
  (the plumbing shared with ``infer.pipeline.compact_decode_fn``,
  GIL-released under the native decoder) is demoted to the overflow
  fallback — and remains the whole completion stage on the host-pool
  lane (``device_decode=False``).  ``ServeMetrics`` splits
  ``decode_fused`` from ``decode_host_fallback`` so the fallback rate
  is observable.  Results always map back to their own request (batch
  dispatch returns input order), so arrival order is preserved per
  caller.
- **Warmup**: :meth:`warmup` precompiles every configured bucket shape at
  every power-of-two batch size ≤ ``max_batch`` through the persistent
  compilation cache (``utils.platform``), so the first request in each
  bucket never eats a compile stall.
"""
from __future__ import annotations

import itertools
import queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config import InferenceParams, SkeletonConfig
from ..infer.pipeline import compact_decode_fn
from ..obs.reqtrace import NULL_NODE, get_reqtrace
from ..obs.trace import get_tracer
from .metrics import HOPS, ServeMetrics
from .warmup import precompile

_STOP = object()
_KICK = object()   # device went idle — wake the dispatcher to flush
# process-wide request ids: the trace keys each request's async span and
# submit->execute flow arrow on these (next() is atomic under the GIL)
_RID = itertools.count(1)


class ServerOverloaded(RuntimeError):
    """Admission queue full — the request was rejected (load shed).

    The explicit fail-fast status: callers retry with backoff or surface
    a 503; the server keeps serving everything already admitted."""


class DeadlineExceeded(RuntimeError):
    """The request's ``deadline_s`` passed before its batch reached the
    device — the request is failed fast instead of occupying a bucket
    slot with work the caller already gave up on.  The policy layer
    (``serve.policy``) is the intended producer of deadlines; a retry
    against another replica is pointless (the deadline is global), so
    the pool never fails this over."""


class _Request:
    __slots__ = ("image", "future", "t_submit", "deadline", "finished",
                 "rid", "ctx", "t_bucket", "t_dispatch", "t_exec",
                 "t_decode", "replica")

    def __init__(self, image: np.ndarray,
                 deadline_s: Optional[float] = None):
        self.image = image
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        # absolute perf_counter instant past which the request is dead
        # weight (None = no deadline): checked by the dispatcher before
        # bucketing AND again at dispatch, never on the submit hot path
        self.deadline = (None if deadline_s is None
                         else self.t_submit + deadline_s)
        self.finished = False  # server-side once-flag (see _finish)
        self.rid = next(_RID)  # trace flow/async-span key
        self.ctx = NULL_NODE   # reqtrace node (obs.reqtrace)
        # hop-waterfall boundary stamps (perf_counter): each stage
        # stamps its exit, so consecutive differences PARTITION the
        # submit→finish window — see serve.metrics.HOPS
        self.t_bucket: Optional[float] = None
        self.t_dispatch: Optional[float] = None
        self.t_exec: Optional[float] = None
        self.t_decode: Optional[float] = None
        self.replica = 0

    def expired(self, now: Optional[float] = None) -> bool:
        return (self.deadline is not None
                and (now if now is not None
                     else time.perf_counter()) >= self.deadline)


class DynamicBatcher:
    """Dynamic-batching compact-inference server around one Predictor.

    ::

        with DynamicBatcher(pred, max_batch=8, max_wait_ms=5) as server:
            server.warmup([(512, 512)])
            fut = server.submit(image_bgr)       # from any thread
            skeletons = fut.result()             # list[(coco_kps, score)]

    Restricted to the trivial (single-scale, no-rotation) grid — the
    protocol whose bucket geometry lets one compiled batch program per
    shape serve all traffic; grid ensembles dispatch per image and
    belong on the offline paths.

    The predictor itself is driven only from the internal dispatcher
    thread (plus the decode pool's overflow fallback, which re-runs
    single images); callers never touch it concurrently.
    """

    def __init__(self, predictor, params: Optional[InferenceParams] = None,
                 skeleton: Optional[SkeletonConfig] = None, *,
                 max_batch: int = 8, max_wait_ms: float = 25.0,
                 max_queue: int = 64, decode_workers: int = 2,
                 use_native: bool = True, devices: Optional[Sequence] = None,
                 eager_idle_flush: bool = True,
                 metrics: Optional[ServeMetrics] = None,
                 registry=None, device_decode: bool = True,
                 emit_signals: bool = False, slo=None,
                 qos_class: str = "interactive"):
        from ..infer.predict import trivial_grid

        self.predictor = predictor
        self.params = params or predictor.params
        self.skeleton = skeleton or predictor.skeleton
        if not trivial_grid(self.params):
            raise ValueError(
                "DynamicBatcher serves the single-scale protocol; "
                "scale/rotation grids dispatch per image — use "
                "predict_compact_ms / pipelined_inference for those")
        if max_batch < 1 or max_queue < 1:
            raise ValueError(f"max_batch={max_batch} and max_queue="
                             f"{max_queue} must be >= 1")
        self.max_batch = max_batch
        self.max_wait_s = max_wait_ms / 1e3
        self.max_queue = max_queue
        # True: flush pending work the moment a device goes idle (hide
        # the coalescing wait behind in-flight work — the throughput
        # default).  False: only max_batch / deadline flush — maximizes
        # occupancy at the cost of idle device time; also what makes
        # flush behavior deterministic for tests.
        self.eager_idle_flush = eager_idle_flush
        self.metrics = metrics or ServeMetrics()
        # optional SLO wiring (obs.slo.SLOTracker): every finished
        # request recorded under this engine's QoS class.  Attach at
        # ONE layer per deployment — a pool/policy above an slo-wired
        # batcher would double-count the same request.
        self._slo = slo
        self._qos_class = qos_class
        if registry is not None:
            # one exposition path for serve + train: the batcher's
            # counters/reservoirs surface on the shared /metrics endpoint
            self.metrics.register_into(registry)
        # True (default): dispatch the FUSED device-decode programs —
        # forward + compact extraction + greedy assembly in one XLA
        # program per batch; the decode pool is demoted to the overflow
        # fallback.  False: the pre-fusion host-pool lane (every decode
        # runs decode_compact on the pool) — the parity/A-B arm.
        self.device_decode = device_decode
        # True: every future resolves to (skeletons, EscalationSignals)
        # instead of bare skeletons — the cascade layer's input
        # (serve.cascade).  The signals are free: person count, overflow
        # flags and the min assembly score already ride the fused decode
        # payload's single fetch.  Requires the device-decode lane (the
        # host-pool lane never sees the device assembly).
        self.emit_signals = emit_signals
        if emit_signals and not device_decode:
            raise ValueError(
                "emit_signals needs the fused device-decode lane "
                "(device_decode=True): the escalation signals live in "
                "the device assembly's payload")
        # compact_decode_fn serves BOTH lanes: the host-pool lane's
        # per-request decoder, and the device lane's overflow fallback
        # (fed the compact records the fused buffer ships alongside)
        self._decode_one = compact_decode_fn(predictor, self.params,
                                             self.skeleton, use_native)
        self._decode_workers = max(1, decode_workers)
        # device replicas: data-parallel serving — each batch runs whole
        # on the least-loaded replica's device (a pod's chips, or a CPU
        # host's virtual devices).  The serial per-image paths can only
        # ever drive one device; this is throughput the engine alone
        # unlocks.
        if devices:
            self._replicas = [predictor.device_replica(d) for d in devices]
        else:
            self._replicas = [predictor]
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._fetchqs = [queue.SimpleQueue() for _ in self._replicas]
        self._slots = threading.BoundedSemaphore(max_queue)
        self._pool: Optional[ThreadPoolExecutor] = None
        self._dispatcher: Optional[threading.Thread] = None
        self._fetchers: "list[threading.Thread]" = []
        self._running = False
        self._draining = False
        # every admitted, unfinished request — what the bounded-deadline
        # drain fails explicitly instead of stranding (guarded by
        # _finish_lock, the same lock that makes _finish exactly-once)
        self._inflight_reqs: "set[_Request]" = set()
        # per-replica batches dispatched whose device results are not yet
        # fetched — the dispatcher's "is a device idle" signal for idle
        # flushes and its least-loaded routing key
        self._in_flight = [0] * len(self._replicas)
        self._in_flight_lock = threading.Lock()
        self._finish_lock = threading.Lock()
        # serializes stop() AND start(): double-stop (router fencing
        # racing a user shutdown) must not raise or double-join — the
        # first caller does the drain, concurrent callers block until
        # it finishes and then see the already-clean state — and a
        # restart waits for an in-progress drain's tail
        self._stop_lock = threading.Lock()
        # start generation: stage threads carry their token so one a
        # wedged drain left parked cannot feed or account against a
        # later generation's pipeline when it finally resumes
        self._gen = 0

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "DynamicBatcher":
        # serialized against stop(): a restart racing an in-progress
        # bounded drain (the pool's fence drain vs an early restart)
        # must wait for the drain's tail, or the drain would tear down
        # the NEW generation's queues/threads it never owned
        with self._stop_lock:
            if self._running:
                return self
            # fresh queues per start GENERATION: a restart after stop()
            # must not share queues with a previous generation's threads
            # — a stale _STOP sentinel (or a thread a wedged drain left
            # parked mid-stage) would otherwise kill or starve the new
            # pipeline.  Every stage thread carries its generation
            # token and queue objects; a prior-generation thread that
            # resumes after a restart no-ops instead of feeding or
            # accounting against the live pipeline.
            self._gen += 1
            self._queue = queue.SimpleQueue()
            self._fetchqs = [queue.SimpleQueue() for _ in self._replicas]
            self._in_flight = [0] * len(self._replicas)
            self._pool = ThreadPoolExecutor(
                max_workers=self._decode_workers,
                thread_name_prefix="serve-decode")
            self._running = True
            self._dispatcher = threading.Thread(
                target=self._run, args=(self._gen, self._queue,
                                        self._fetchqs),
                name="serve-dispatcher", daemon=True)
            self._fetchers = [
                threading.Thread(target=self._run_fetcher,
                                 args=(i, self._fetchqs[i], self._gen),
                                 name=f"serve-fetcher-{i}", daemon=True)
                for i in range(len(self._replicas))]
            self._dispatcher.start()
            for t in self._fetchers:
                t.start()
            return self

    @property
    def draining(self) -> bool:
        """True once a graceful stop began: new submits are rejected
        with :class:`ServerOverloaded` while admitted work drains."""
        return self._draining

    def stop(self, drain_timeout_s: Optional[float] = None) -> None:
        """Graceful drain, then shut down.

        Admission closes FIRST (new submits are rejected with
        :class:`ServerOverloaded` — the status a load-balancer already
        handles during rollout, unlike the old hard ``RuntimeError``),
        then the queued buckets flush, the fetch pipelines drain, and
        the decode pool joins.  With ``drain_timeout_s`` the whole drain
        is bounded: past the deadline the remaining in-flight futures
        fail with an explicit error instead of the caller hanging on a
        wedged device — every future returned by :meth:`submit` always
        completes, on time or by deadline.

        Idempotent and thread-safe: concurrent callers (the pool's
        fence drain racing a user shutdown) serialize on a stop lock —
        the first caller drains, the rest wait and return.
        """
        with self._stop_lock:
            self._stop_locked(drain_timeout_s)

    def _stop_locked(self, drain_timeout_s: Optional[float]) -> None:
        if not self._running and self._dispatcher is None \
                and not self._fetchers:
            return  # never started, or a previous stop() finished
        deadline = (None if drain_timeout_s is None
                    else time.perf_counter() + drain_timeout_s)

        def remaining() -> Optional[float]:
            if deadline is None:
                return None
            return max(0.0, deadline - time.perf_counter())

        # order matters: reject new admissions BEFORE the stop sentinel,
        # so nothing can enqueue behind it and strand
        self._draining = True
        self._running = False
        self._queue.put(_STOP)
        self._dispatcher.join(remaining())
        expired = self._dispatcher.is_alive()  # daemon; dies with us
        self._dispatcher = None
        # the dispatcher flushed everything before exiting; now drain the
        # fetch pipelines behind it
        for q in self._fetchqs:
            q.put(_STOP)
        for t in self._fetchers:
            t.join(remaining())
            expired = expired or t.is_alive()
        self._fetchers = []
        # a submit that raced the _running flip may have enqueued behind
        # the sentinel; fail those futures rather than hang their callers
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not _STOP and req is not _KICK:
                self._finish(req, error=RuntimeError("batcher stopped"))
        if deadline is not None:
            # bounded decode drain: poll the admitted-set down instead
            # of an unbounded pool.shutdown(wait=True)
            while remaining() > 0:
                with self._finish_lock:
                    if not self._inflight_reqs:
                        break
                time.sleep(0.005)
            with self._finish_lock:
                stranded = list(self._inflight_reqs)
            # deadline hit with work still wedged in a stage (a hung
            # device resolve, a stuck decode): fail every remaining
            # future explicitly — _finish is exactly-once, so a stage
            # that later completes one anyway is a harmless no-op
            for req in stranded:
                self._finish(req, error=RuntimeError(
                    f"batcher stopped before completion (drain deadline "
                    f"{drain_timeout_s}s exceeded)"))
            wedged = bool(expired or stranded)
            self._pool.shutdown(wait=not wedged)
            # a wedged stage thread may still recover later and call
            # self._pool.submit — keep the SHUT-DOWN executor so that
            # raises the RuntimeError its inline-decode fallback
            # handles (None would AttributeError and kill the thread);
            # start() replaces the pool unconditionally
            if not wedged:
                self._pool = None
        else:
            self._pool.shutdown(wait=True)
            self._pool = None
        self._draining = False

    def __enter__(self) -> "DynamicBatcher":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- submit
    def submit(self, image_bgr: np.ndarray, *,
               deadline_s: Optional[float] = None) -> Future:
        """Enqueue one BGR image; returns a future resolving to the
        decoded skeletons (``decode_compact`` output: a list of
        (coco_keypoints, score) tuples).

        ``deadline_s`` bounds the request's useful life: a request whose
        deadline passes before its bucket reaches the device fails fast
        with :class:`DeadlineExceeded` instead of occupying a batch
        lane (checked by the dispatcher at bucketing and again at
        dispatch — a caller that already gave up must not cost device
        time).

        :raises ServerOverloaded: ``max_queue`` requests already in
            flight (fail-fast backpressure, nothing is queued) — or the
            batcher is DRAINING toward shutdown (same retry-with-backoff
            contract: during a rolling restart the replacement instance
            takes the retry).
        :raises DeadlineExceeded: ``deadline_s`` is already non-positive
            at submit time (nothing is admitted).
        :raises RuntimeError: the batcher is not running.
        """
        if self._draining:
            self.metrics.on_reject()
            raise ServerOverloaded(
                "batcher is draining (shutdown in progress); retry "
                "against a live instance")
        if not self._running:
            raise RuntimeError("DynamicBatcher is not running "
                               "(use `with batcher:` or call start())")
        if deadline_s is not None and deadline_s <= 0:
            self.metrics.on_expire_rejected()
            raise DeadlineExceeded(
                f"deadline_s={deadline_s} already expired at submit")
        if not self._slots.acquire(blocking=False):
            self.metrics.on_reject()
            raise ServerOverloaded(
                f"{self.max_queue} requests in flight (max_queue); "
                "retry with backoff")
        req = _Request(image_bgr, deadline_s)
        rt = get_reqtrace()
        if rt.enabled:
            # root when the caller is a bare client; child of the
            # submitting layer's node (pool route, policy attempt,
            # cascade lane, stream frame) when this submit runs inside
            # its child_scope — the cross-hop causal link
            req.ctx = rt.begin(
                "batcher", **({"model": self.metrics.model}
                              if self.metrics.model else {}))
        with self._finish_lock:
            self._inflight_reqs.add(req)
        trace = get_tracer()
        if trace.enabled:
            # one async span per request (enqueue -> fulfilment) plus a
            # flow arrow from this submit to the batch that executes it:
            # in Perfetto, batching fan-in is N arrows converging on one
            # `execute` slice
            trace.async_begin("request", req.rid, cat="serve",
                             args={"shape": list(np.shape(image_bgr))})
            trace.flow_start("serve_req", req.rid)
        self.metrics.on_submit()
        q = self._queue
        q.put(req)
        if not self._running or q is not self._queue:
            # raced stop() — or a whole stop()+start() cycle, in which
            # case the request landed in the PREVIOUS generation's
            # orphaned queue that no dispatcher will ever read (the
            # `q is not self._queue` arm; _running alone would look
            # fine again after the restart).  _finish is idempotent, so
            # if a dispatcher did catch it, this no-ops.
            self._finish(req, error=RuntimeError("batcher stopped"))
        return req.future

    # ------------------------------------------------------------- warmup
    def warmup(self, image_sizes: Sequence[Tuple[int, int]],
               batch_sizes: Optional[Sequence[int]] = None) -> dict:
        """Precompile the batch programs the configured traffic needs:
        every bucket the given (H, W) image sizes land in × every
        power-of-two batch size ≤ ``max_batch`` (or an explicit
        ``batch_sizes``), on EVERY device replica — plus one untimed
        dispatch of every NON-pow2 occupancy, whose pow2 chunks join
        through an on-device row-concat program the (bucket × pow2)
        precompile cannot reach (the PR 10 stream-bench finding, now
        covered here for every caller).  Call before accepting traffic;
        see :func:`serve.warmup.precompile` for the returned summary."""
        # ONE warmup path (serve.warmup.precompile over a predictor
        # set) shared with the pool's per-replica warmup and the
        # cascade tiers; replicas share the program cache, so only the
        # first pass reports new programs while later passes still
        # build/warm each device's executable
        info = precompile(self._replicas, image_sizes, self.max_batch,
                          params=self.params, batch_sizes=batch_sizes,
                          decode=self.device_decode)
        # an explicit batch_sizes is the caller's occupancy cap (the
        # pool warms singleton flushes with (1,)): the chunk-join loop
        # must not dispatch — and compile — the pow2 chunk programs
        # that restriction just excluded
        occupancy_cap = (max(batch_sizes) if batch_sizes
                         else self.max_batch)
        for replica in self._replicas:
            dispatch = (replica.predict_decoded_batch_async
                        if self.device_decode
                        else replica.predict_compact_batch_async)
            for h, w in image_sizes:
                img = np.zeros((int(h), int(w), 3), np.uint8)
                for n in range(3, occupancy_cap + 1):
                    if n & (n - 1):  # non-pow2: chunk-join flush shape
                        dispatch([img] * n, thre1=self.params.thre1,
                                 params=self.params)()
        return info

    # ------------------------------------------------------------- health
    def health(self) -> dict:
        """One consistent liveness read for a router's health probe
        (``serve.pool.EnginePool``), built from signals that already
        exist: thread liveness plus the ``ServeMetrics`` stall clock.

        A replica is *wedged* when work is admitted but nothing has
        completed for longer than the router's patience
        (``stall_age_s``), and *crashed* when its dispatcher or a
        fetcher thread died — both observable here without touching the
        device."""
        dispatcher = self._dispatcher
        fetchers = list(self._fetchers)
        with self._in_flight_lock:
            batches_in_flight = sum(self._in_flight)
        return {
            "running": self._running,
            "draining": self._draining,
            "dispatcher_alive": bool(dispatcher is not None
                                     and dispatcher.is_alive()),
            "fetchers_alive": sum(1 for t in fetchers if t.is_alive()),
            "fetchers_expected": len(fetchers),
            "queue_depth": self.metrics.depth,
            "batches_in_flight": batches_in_flight,
            "stall_age_s": self.metrics.stall_age_s(),
        }

    # --------------------------------------------------------- dispatcher
    def _run(self, gen: int, inq: "queue.SimpleQueue",
             fetchqs: "list[queue.SimpleQueue]") -> None:
        """The coalescing loop.  A bucket flushes when any of:

        - it reached ``max_batch`` occupancy (full lanes — always);
        - its oldest request waited out ``max_wait_ms`` (the latency
          promise — always);
        - the device went idle (no batch in flight): holding requests
          back can only raise occupancy if the wait is hidden behind
          in-flight work, so an idle device flushes whatever exists
          immediately.  This makes throughput insensitive to
          ``max_wait_ms`` — the deadline buys occupancy only out of
          time the device was busy anyway.
        """
        pending: Dict[Tuple[int, int], List[_Request]] = {}
        stop = False
        while not stop:
            timeout = None
            if pending:
                oldest = min(reqs[0].t_submit for reqs in pending.values())
                timeout = max(0.0, oldest + self.max_wait_s
                              - time.perf_counter())
            try:
                item = inq.get(timeout=timeout)
            except queue.Empty:
                item = None
            if item is _STOP:
                stop = True
            elif item is not None and item is not _KICK:
                if item.expired():
                    # dead on arrival at the dispatcher: fail fast
                    # BEFORE the request can occupy a bucket slot
                    self._finish(item, error=DeadlineExceeded(
                        "request deadline passed before dispatch"))
                    continue
                try:
                    key = self.predictor.compact_lane_shape(item.image,
                                                            self.params)
                except Exception as e:  # noqa: BLE001 — a malformed
                    # image must fail ITS future, never the dispatcher
                    self._finish(item, error=e)
                    continue
                item.t_bucket = time.perf_counter()  # queue hop ends
                bucket = pending.setdefault(key, [])
                bucket.append(item)
                if len(bucket) >= self.max_batch:
                    self._dispatch(pending.pop(key), gen, fetchqs)
            now = time.perf_counter()
            with self._in_flight_lock:
                idle = (self.eager_idle_flush
                        and min(self._in_flight) == 0)
            # oldest bucket first: deadline and idle flushes drain in
            # arrival order
            for key in sorted(pending,
                              key=lambda k: pending[k][0].t_submit):
                if stop or idle or (now - pending[key][0].t_submit
                                    >= self.max_wait_s):
                    self._dispatch(pending.pop(key), gen, fetchqs)
                    with self._in_flight_lock:
                        idle = (self.eager_idle_flush
                                and min(self._in_flight) == 0)

    def _dispatch(self, reqs: List[_Request], gen: int,
                  fetchqs: "list[queue.SimpleQueue]") -> None:
        """Dispatch one shape bucket's batch to the least-loaded device
        replica (async) and queue its fetch.  Runs on the dispatcher
        thread; a dispatch failure fails exactly this batch's futures and
        the loop keeps serving."""
        if gen != self._gen:
            # a prior-generation dispatcher resumed after a restart:
            # its requests were already failed by that generation's
            # drain (exactly-once _finish no-ops) — don't burn device
            # time or touch the live generation's accounting
            for r in reqs:
                self._finish(r, error=RuntimeError("batcher restarted"))
            return
        if any(r.deadline is not None for r in reqs):
            # last check before device work: expired requests fall out
            # of the batch here (a bucket that waited out max_wait_ms
            # can outlive a tight deadline)
            now = time.perf_counter()
            live = []
            for r in reqs:
                if r.expired(now):
                    self._finish(r, error=DeadlineExceeded(
                        "request deadline passed before dispatch"))
                else:
                    live.append(r)
            reqs = live
            if not reqs:
                return
        with self._in_flight_lock:
            idx = min(range(len(self._replicas)),
                      key=self._in_flight.__getitem__)
        t_dispatch = time.perf_counter()  # batch_formation hop ends
        for r in reqs:
            r.t_dispatch = t_dispatch
            r.replica = idx
        replica = self._replicas[idx]
        if self.device_decode:
            dispatch_one = replica.predict_decoded_async
            dispatch_batch = replica.predict_decoded_batch_async
        else:
            dispatch_one = replica.predict_compact_async
            dispatch_batch = replica.predict_compact_batch_async
        try:
            if len(reqs) == 1:
                # singleton flush: the single-image program skips the
                # batch path's stack/group/concat machinery
                resolve_one = dispatch_one(
                    reqs[0].image, thre1=self.params.thre1,
                    params=self.params)
                resolve = lambda: [resolve_one()]  # noqa: E731
            else:
                resolve = dispatch_batch(
                    [r.image for r in reqs], thre1=self.params.thre1,
                    params=self.params)
        except Exception as e:  # noqa: BLE001 — delivered per request
            for r in reqs:
                self._finish(r, error=e)
            return
        if gen != self._gen:
            # the dispatch call itself can block (a wedged device); a
            # restart may have happened while this thread was parked in
            # it — re-check before touching the live generation's
            # accounting or enqueueing to a dead fetcher
            for r in reqs:
                self._finish(r, error=RuntimeError("batcher restarted"))
            return
        trace = get_tracer()
        if trace.enabled:
            # dispatcher-track marker: when the bucket left coalescing
            trace.instant("dispatch", args={"batch": len(reqs),
                                            "replica": idx})
        self.metrics.on_dispatch(len(reqs))
        with self._in_flight_lock:
            self._in_flight[idx] += 1
        fetchqs[idx].put((reqs, resolve))

    def _run_fetcher(self, idx: int, inq: "queue.SimpleQueue",
                     gen: int) -> None:
        """One replica's fetch stage: block on each batch's single
        device→host transfer (FIFO per replica — a device executes its
        dispatches in order, so waiting in dispatch order is optimal),
        then fan the per-image decodes out to the pool.  Dedicated
        threads so a resolve wait can never occupy a decode worker —
        with every worker stuck fetching, nothing would decode and the
        pipeline would stall."""
        while True:
            item = inq.get()
            if item is _STOP:
                return
            reqs, resolve = item
            trace = get_tracer()
            t_exec = trace.now() if trace.enabled else 0.0
            try:
                results = resolve()
            except Exception as e:  # noqa: BLE001 — delivered per request
                self._batch_done(idx, gen)
                for r in reqs:
                    self._finish(r, error=e)
                continue
            t_fetched = time.perf_counter()  # device hop ends
            for r in reqs:
                r.t_exec = t_fetched
            if trace.enabled:
                trace.add_span_rel("execute", t_exec,
                                   trace.now() - t_exec,
                                   args={"batch": len(reqs),
                                         "replica": idx})
                for r in reqs:
                    # arrowheads bind to the execute slice (ts at its
                    # start): each admitted request's flow ends here
                    trace.flow_finish("serve_req", r.rid, ts=t_exec)
            self._batch_done(idx, gen)
            for r, res in zip(reqs, results):
                signals = None
                if self.device_decode:
                    if self.emit_signals:
                        from ..infer.decode import device_signals

                        # captured BEFORE the overflow demotion below:
                        # the flags are exactly what tells the cascade
                        # WHY a fallback-decoded frame is hard
                        signals = device_signals(res)
                    if res.ok:
                        # fused result: the remaining work is an
                        # O(people) coordinate lookup — finish INLINE on
                        # this device-program track (no pool hop; the
                        # `decode` span lands next to `execute`)
                        self.metrics.on_decode(fused=True)
                        self._finish_fused(r, res, signals)
                        continue
                    # overflow flag: demote to the host decode pool on
                    # the compact records the fused buffer shipped
                    self.metrics.on_decode(fused=False)
                    res = res.compact
                else:
                    self.metrics.on_decode(fused=False)
                try:
                    self._pool.submit(self._decode_and_finish, r, res,
                                      signals)
                except RuntimeError:  # pool draining (stop()) — inline
                    self._decode_and_finish(r, res, signals)

    def _batch_done(self, idx: int, gen: int) -> None:
        """One batch's device results landed: drop the replica's
        in-flight count and wake the dispatcher so an idle device gets
        fed at once.  Generation-guarded: a prior-generation fetcher
        resuming after a restart must not decrement (or kick) the live
        pipeline's accounting."""
        if gen != self._gen:
            return
        with self._in_flight_lock:
            self._in_flight[idx] -= 1
            idle = self._in_flight[idx] == 0
        if idle and self._running:
            self._queue.put(_KICK)

    def _finish_fused(self, req: _Request, res, signals=None) -> None:
        """Finish one fused device-decode result on the calling (fetch)
        thread: coordinate lookup + COCO reorder only."""
        from ..infer.decode import decode_device

        try:
            with get_tracer().span("decode", args={"rid": req.rid,
                                                   "lane": "device"}):
                result = decode_device(res, self.skeleton)
            req.t_decode = time.perf_counter()  # decode hop ends
            if self.emit_signals:
                result = (result, signals)
            self._finish(req, result=result)
        except Exception as e:  # noqa: BLE001 — delivered per request
            self._finish(req, error=e)

    def _decode_and_finish(self, req: _Request, res,
                           signals=None) -> None:
        try:
            with get_tracer().span("decode", args={"rid": req.rid,
                                                   "lane": "host"}):
                result = self._decode_one(res, req.image)
            req.t_decode = time.perf_counter()  # decode hop ends
            if self.emit_signals:
                result = (result, signals)
            self._finish(req, result=result)
        except Exception as e:  # noqa: BLE001 — delivered per request
            self._finish(req, error=e)

    def _finish(self, req: _Request, result=None, error=None) -> None:
        """Fulfil one request exactly once: metrics, future, admission
        slot.  Keyed on the request's own once-flag, NOT future.done():
        a caller may cancel() the pending future, and that must not leak
        the admission slot or the metrics depth — the slot is released
        exactly once per admitted request, no matter what."""
        with self._finish_lock:  # atomic once-flag: a double release
            # would blow the bounded admission semaphore
            if req.finished:
                return
            req.finished = True
            self._inflight_reqs.discard(req)
        # ONE end-of-life stamp shared by the hop waterfall, the e2e
        # reservoir and the SLO record: measuring them at different
        # instants would charge this function's own record-assembly
        # work to the request and break the exact hop↔e2e conservation
        t_fin = time.perf_counter()
        if error is None and req.t_decode is not None:
            # the hop waterfall: consecutive boundary stamps partition
            # submit→here, so the five segments sum to the measured e2e
            # by construction (the conservation discipline); fed for
            # EVERY completed request — reqtrace sampling only thins
            # the per-request records, never these reservoirs
            durs = (req.t_bucket - req.t_submit,
                    req.t_dispatch - req.t_bucket,
                    req.t_exec - req.t_dispatch,
                    req.t_decode - req.t_exec,
                    t_fin - req.t_decode)
            if req.ctx.sampled:
                # finish BEFORE the reservoir updates: the node's end
                # stamp must sit next to t_fin, not after ten meter
                # updates — on sub-ms requests that gap alone would
                # break the per-request conservation readout
                req.ctx.finish("ok", hops=list(zip(HOPS, durs)),
                               replica=req.replica)
            self.metrics.on_hops(req.replica, durs)
        elif req.ctx.sampled:
            # error path: record what the request got through before it
            # died (partial waterfall, stamps that exist)
            stamps = [("queue", req.t_submit, req.t_bucket),
                      ("batch_formation", req.t_bucket, req.t_dispatch),
                      ("device", req.t_dispatch, req.t_exec),
                      ("decode", req.t_exec, req.t_decode)]
            hops = [(name, t1 - t0) for name, t0, t1 in stamps
                    if t0 is not None and t1 is not None]
            req.ctx.finish(
                "ok" if error is None
                else f"error:{type(error).__name__}",
                hops=hops, replica=req.replica)
        trace = get_tracer()
        if trace.enabled:
            trace.async_end("request", req.rid, cat="serve",
                            args={"error": error is not None})
        if self._slo is not None:
            self._slo.record(self._qos_class, t_fin - req.t_submit,
                             error=error is not None)
        try:
            if error is not None:
                self.metrics.on_fail(
                    expired=isinstance(error, DeadlineExceeded))
                req.future.set_exception(error)
            else:
                self.metrics.on_complete(t_fin - req.t_submit)
                req.future.set_result(result)
        except Exception:  # noqa: BLE001 — future cancelled by caller;
            # the server-side work still completed and is accounted
            pass
        finally:
            self._slots.release()
