"""Client-side request policy: deadlines, bounded retry-with-backoff,
hedged dispatch.

The server side already speaks the right statuses — ``ServerOverloaded``
is the explicit load-shed/draining signal and ``DeadlineExceeded`` the
server-side fail-fast for stale work — but every caller so far
re-implemented the client half by hand (the benches' fixed 2 ms retry
sleep, the stream session's overload loop).  This module is that half,
once:

- :func:`jittered_backoff` / :func:`submit_with_retry` — the shared
  retry discipline for ``ServerOverloaded``: exponential backoff with
  multiplicative jitter (a fleet of shedding clients must not re-arrive
  in lockstep), bounded attempts, abort hook for draining targets.
- :class:`PolicyClient` — per-request deadlines (enforced client-side
  by a timer AND server-side via ``submit(deadline_s=)``), admission
  retry, and an optional **hedged second dispatch**: past
  ``hedge_after_s`` with no result, the same image is submitted again
  (through the pool's least-loaded routing that usually lands on a
  different replica) and the first result wins — the classic
  tail-latency-at-scale trade of a little extra work for a bounded p99.

Everything here is host-side bookkeeping around futures; no device
state, no threads beyond ``threading.Timer`` fired per armed deadline/
hedge (cancelled on completion).
"""
from __future__ import annotations

import random
import threading
import time
from concurrent.futures import Future
from typing import Callable, Optional, Tuple

from ..obs.reqtrace import NULL_NODE, get_reqtrace
from .batcher import DeadlineExceeded, ServerOverloaded


def jittered_backoff(attempt: int, base_s: float = 0.002,
                     max_s: float = 0.25, jitter: float = 0.5,
                     rng: Optional[random.Random] = None) -> float:
    """Delay before retry ``attempt`` (1-based): exponential growth
    capped at ``max_s``, scaled by a uniform multiplicative jitter in
    ``[1 - jitter, 1 + jitter]`` so retrying clients decorrelate."""
    if attempt < 1:
        raise ValueError(f"attempt={attempt} is 1-based")
    delay = min(base_s * (2.0 ** (attempt - 1)), max_s)
    r = rng.random() if rng is not None else random.random()
    return delay * (1.0 - jitter + 2.0 * jitter * r)


def submit_with_retry(submit: Callable[..., Future], *args,
                      max_attempts: Optional[int] = None,
                      base_s: float = 0.002, max_s: float = 0.25,
                      jitter: float = 0.5,
                      rng: Optional[random.Random] = None,
                      should_abort: Optional[Callable[[], bool]] = None,
                      **kwargs) -> Tuple[Future, int]:
    """Call ``submit(*args, **kwargs)``, retrying ``ServerOverloaded``
    with jittered exponential backoff; returns ``(future, retries)`` so
    load generators can report how often they were shed instead of
    counting a shed as a failure.

    ``max_attempts=None`` retries until admitted (the closed-loop bench
    contract); ``should_abort`` (e.g. ``lambda: server.draining``) stops
    retrying against a target that will never admit again and re-raises
    the last ``ServerOverloaded``.
    """
    attempt = 0
    while True:
        try:
            return submit(*args, **kwargs), attempt
        except ServerOverloaded:
            if should_abort is not None and should_abort():
                raise
            attempt += 1
            if max_attempts is not None and attempt >= max_attempts:
                raise
            time.sleep(jittered_backoff(attempt, base_s, max_s, jitter,
                                        rng))


class PolicyStats:
    """Thread-safe counters for one :class:`PolicyClient` (snapshot is
    the JSON-artifact shape; ``register_into`` follows the ServeMetrics
    collector discipline)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.submitted = 0
        self.admission_retries = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.deadline_expired = 0

    def add(self, **deltas: int) -> None:
        with self._lock:
            for name, d in deltas.items():
                setattr(self, name, getattr(self, name) + d)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "submitted": self.submitted,
                "admission_retries": self.admission_retries,
                "hedges": self.hedges,
                "hedge_wins": self.hedge_wins,
                "deadline_expired": self.deadline_expired,
            }

    def register_into(self, registry, prefix: str = "policy"
                      ) -> "PolicyStats":
        import weakref

        ref = weakref.ref(self)

        def _collect():
            s = ref()
            if s is None:
                return []
            return [(f"{prefix}_{name}_total", {}, "counter", float(v))
                    for name, v in s.snapshot().items()]

        registry.register_collector(_collect)
        return self


class _Flight:
    """One policy-level request: its caller-facing future, the set of
    engine attempts still outstanding, and the timers armed for it."""

    __slots__ = ("future", "lock", "outstanding", "last_error", "timers",
                 "won_by", "ctx", "t0", "t_admitted", "t_hedge",
                 "last_node")

    def __init__(self):
        self.future: Future = Future()
        self.lock = threading.Lock()
        self.outstanding = 0
        self.last_error: Optional[BaseException] = None
        self.timers: list = []
        self.won_by: Optional[str] = None
        self.ctx = NULL_NODE            # reqtrace node (obs.reqtrace)
        self.t0 = time.perf_counter()
        self.t_admitted: Optional[float] = None
        self.t_hedge: Optional[float] = None  # hedge admission instant
        self.last_node = None           # last attempt's child node


class PolicyClient:
    """Deadline / retry / hedge wrapper around anything with the
    ``submit(image, deadline_s=...)`` contract (a ``DynamicBatcher`` or
    an ``EnginePool``).

    ::

        client = PolicyClient(pool, deadline_s=2.0, hedge_after_s=0.5)
        skeletons = client.submit(img).result()

    - **deadline**: the remaining budget rides into every engine submit
      (server-side fail-fast before device dispatch) AND a client timer
      fails the caller's future with :class:`DeadlineExceeded` the
      moment the budget lapses — the latency promise holds even when
      the engine is wedged.
    - **retry**: admission (``ServerOverloaded``) retries with jittered
      backoff on the caller's thread, bounded by ``max_attempts`` and
      the deadline.
    - **hedge**: with ``hedge_after_s`` set, a request still unresolved
      past that age dispatches a second copy; first RESULT wins, an
      error only surfaces once every outstanding attempt failed.  At
      most one hedge per request — the tail is the target, not a
      retry storm.
    """

    def __init__(self, engine, *, deadline_s: Optional[float] = None,
                 max_attempts: int = 4, backoff_base_s: float = 0.002,
                 backoff_max_s: float = 0.25, jitter: float = 0.5,
                 hedge_after_s: Optional[float] = None, seed: int = 0,
                 stats: Optional[PolicyStats] = None, slo=None,
                 qos_class: str = "interactive"):
        if max_attempts < 1:
            raise ValueError(f"max_attempts={max_attempts} must be >= 1")
        if hedge_after_s is not None and hedge_after_s <= 0:
            raise ValueError(f"hedge_after_s={hedge_after_s} must be > 0")
        self.engine = engine
        self.deadline_s = deadline_s
        self.max_attempts = max_attempts
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.jitter = jitter
        self.hedge_after_s = hedge_after_s
        self.stats = stats or PolicyStats()
        # optional SLO wiring: the policy client is the outermost layer
        # — what it resolves is the caller's experienced outcome, the
        # deadline/hedge machinery included (attach at ONE layer — see
        # DynamicBatcher)
        self._slo = slo
        self._qos_class = qos_class
        self._locked_rng = self._LockedRng(random.Random(seed),
                                           threading.Lock())

    # ------------------------------------------------------------ submit
    def submit(self, image, *,
               deadline_s: Optional[float] = None) -> Future:
        """Submit under policy; returns a future that ALWAYS resolves —
        with the decoded result, the engine's error once every attempt
        failed, or :class:`DeadlineExceeded`.

        :raises ServerOverloaded: admission still shed after
            ``max_attempts`` (nothing in flight — the caller's cue to
            back off at its own layer).
        :raises DeadlineExceeded: the deadline lapsed while still
            retrying admission (nothing was ever admitted).
        """
        budget = self.deadline_s if deadline_s is None else deadline_s
        deadline = (None if budget is None
                    else time.perf_counter() + budget)
        flight = _Flight()
        rt = get_reqtrace()
        if rt.enabled:
            flight.ctx = rt.begin("policy")
        try:
            # raises if never admitted
            fut, node = self._admit(flight, image, deadline)
        except BaseException as e:  # noqa: BLE001 — re-raised below
            flight.ctx.finish(f"error:{type(e).__name__}",
                              hops=[("admit", time.perf_counter()
                                     - flight.t0)])
            raise
        flight.t_admitted = time.perf_counter()
        self.stats.add(submitted=1)
        with flight.lock:
            flight.outstanding += 1
        fut.add_done_callback(
            lambda f: self._on_attempt_done(flight, f, "primary", node))
        if deadline is not None:
            self._arm(flight, max(0.0, deadline - time.perf_counter()),
                      lambda: self._on_deadline(flight))
        if self.hedge_after_s is not None:
            self._arm(flight, self.hedge_after_s,
                      lambda: self._hedge(flight, image, deadline))
        return flight.future

    def call(self, image, *, deadline_s: Optional[float] = None):
        """Blocking convenience: ``submit(...).result()``."""
        return self.submit(image, deadline_s=deadline_s).result()

    # ---------------------------------------------------------- plumbing
    class _LockedRng:
        """Thread-safe ``random()`` view over the client's seeded RNG
        (submits come from many caller threads)."""

        def __init__(self, rng: random.Random, lock: threading.Lock):
            self._rng, self._lock = rng, lock

        def random(self) -> float:
            with self._lock:
                return self._rng.random()

    def _admit(self, flight: _Flight, image,
               deadline: Optional[float]) -> Tuple[Future, object]:
        """Engine admission with bounded jittered retry; the caller's
        thread sleeps the backoff (a closed-loop client by design).
        Returns ``(engine_future, reqtrace_child_node)`` — a retried
        admission lands as a reason-annotated ``retry`` edge naming how
        many sheds preceded it."""
        attempt = 0
        while True:
            if deadline is not None:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    self.stats.add(deadline_expired=1)
                    raise DeadlineExceeded(
                        "deadline lapsed before admission")
            else:
                remaining = None
            try:
                with flight.ctx.child_scope(
                        "submit" if attempt == 0 else "retry",
                        None if attempt == 0
                        else f"sheds={attempt}") as scope:
                    fut = self.engine.submit(image, deadline_s=remaining)
                return fut, scope.node
            except ServerOverloaded:
                attempt += 1
                if attempt >= self.max_attempts or \
                        getattr(self.engine, "draining", False):
                    raise
                self.stats.add(admission_retries=1)
                # the ONE retry discipline (no inline fork of the
                # formula that could drift from the helper's)
                delay = jittered_backoff(
                    attempt, self.backoff_base_s, self.backoff_max_s,
                    self.jitter, rng=self._locked_rng)
                if remaining is not None:
                    delay = min(delay, max(0.0, remaining))
                time.sleep(delay)

    def _arm(self, flight: _Flight, delay_s: float,
             fire: Callable[[], None]) -> None:
        timer = threading.Timer(delay_s, fire)
        timer.daemon = True
        with flight.lock:
            if flight.future.done():
                return
            flight.timers.append(timer)
        timer.start()

    @staticmethod
    def _cancel_timers(flight: _Flight) -> None:
        # caller holds flight.lock
        for t in flight.timers:
            t.cancel()
        flight.timers.clear()

    def _resolve(self, flight: _Flight, kind: str, result=None,
                 error: Optional[BaseException] = None, node=None,
                 t_done: Optional[float] = None) -> bool:
        with flight.lock:
            if flight.future.done():
                return False
            self._cancel_timers(flight)
            flight.won_by = kind
            if flight.ctx.sampled:
                # policy-node hop bookends: admit (admission incl. shed
                # backoff), hedge_wait (the gap hop — time spent
                # waiting on the primary before the winning hedge was
                # even dispatched), deliver (attempt resolution → this
                # future).  The winning attempt's span covers the rest.
                now = time.perf_counter()
                hops = []
                if flight.t_admitted is not None:
                    hops.append(("admit",
                                 flight.t_admitted - flight.t0))
                if kind == "hedge" and flight.t_hedge is not None \
                        and flight.t_admitted is not None:
                    hops.append(("hedge_wait",
                                 flight.t_hedge - flight.t_admitted))
                if t_done is not None:
                    hops.append(("deliver", now - t_done))
                flight.ctx.finish(
                    "ok" if error is None
                    else f"error:{type(error).__name__}",
                    hops=hops, won_by=node, won_kind=kind)
            if self._slo is not None:
                self._slo.record(self._qos_class,
                                 time.perf_counter() - flight.t0,
                                 error=error is not None)
            try:
                if error is not None:
                    flight.future.set_exception(error)
                else:
                    flight.future.set_result(result)
            except Exception:  # noqa: BLE001 — caller cancelled; the
                # outcome is still accounted below
                pass
        return True

    def _on_attempt_done(self, flight: _Flight, fut: Future,
                         kind: str, node=None) -> None:
        t_done = time.perf_counter()
        try:
            result = fut.result()
            error = None
        except BaseException as e:  # noqa: BLE001 — delivered or held
            result, error = None, e
        if error is None:
            if self._resolve(flight, kind, result=result, node=node,
                             t_done=t_done) and kind == "hedge":
                self.stats.add(hedge_wins=1)
            return
        with flight.lock:
            flight.outstanding -= 1
            flight.last_error = error
            flight.last_node = node if node is not None \
                else flight.last_node
            deliver = flight.outstanding <= 0
        if deliver:
            # every outstanding attempt failed: surface the last error
            self._resolve(flight, kind, error=error, node=node,
                          t_done=t_done)

    def _on_deadline(self, flight: _Flight) -> None:
        if self._resolve(flight, "deadline", error=DeadlineExceeded(
                "request deadline exceeded (client policy)")):
            self.stats.add(deadline_expired=1)

    def _hedge(self, flight: _Flight, image,
               deadline: Optional[float]) -> None:
        remaining = (None if deadline is None
                     else deadline - time.perf_counter())
        if remaining is not None and remaining <= 0:
            return
        with flight.lock:
            if flight.future.done():
                return
            # RESERVE the attempt slot before the submit window: a
            # primary failing while this hedge is mid-admission must
            # wait for it (the hedge exists exactly to cover that
            # failure), not race past outstanding==0 and deliver the
            # error while a winnable attempt is seconds from flight
            flight.outstanding += 1
        try:
            with flight.ctx.child_scope(
                    "hedge",
                    f"hedge_after_s={self.hedge_after_s}") as scope:
                fut = self.engine.submit(image, deadline_s=remaining)
        except Exception:  # noqa: BLE001 — a shed/draining hedge is
            # simply not taken; release the reservation, and if the
            # primary already failed while waiting on us, deliver now
            self._attempt_abandoned(flight)
            return
        flight.t_hedge = time.perf_counter()
        self.stats.add(hedges=1)
        fut.add_done_callback(
            lambda f, nd=scope.node:
            self._on_attempt_done(flight, f, "hedge", nd))

    def _attempt_abandoned(self, flight: _Flight) -> None:
        with flight.lock:
            flight.outstanding -= 1
            error = flight.last_error
            # the failed attempt whose error we are delivering: the
            # chain must end at ITS leaf, not dangle at the policy
            # root (an interior chain end is a completeness violation)
            node = flight.last_node
            deliver = flight.outstanding <= 0 and error is not None
        if deliver:
            self._resolve(flight, "primary", error=error, node=node)
