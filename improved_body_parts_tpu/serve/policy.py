"""Client-side request policy: deadlines, bounded retry-with-backoff,
hedged dispatch.

The server side already speaks the right statuses — ``ServerOverloaded``
is the explicit load-shed/draining signal and ``DeadlineExceeded`` the
server-side fail-fast for stale work — but every caller so far
re-implemented the client half by hand (the benches' fixed 2 ms retry
sleep, the stream session's overload loop).  This module is that half,
once:

- :func:`jittered_backoff` / :func:`submit_with_retry` — the shared
  retry discipline for ``ServerOverloaded``: exponential backoff with
  multiplicative jitter (a fleet of shedding clients must not re-arrive
  in lockstep), bounded attempts, abort hook for draining targets.
- :class:`PolicyClient` — per-request deadlines (enforced client-side
  by a timer AND server-side via ``submit(deadline_s=)``), admission
  retry, and an optional **hedged second dispatch**: past
  ``hedge_after_s`` with no result, the same image is submitted again
  (through the pool's least-loaded routing that usually lands on a
  different replica) and the first result wins — the classic
  tail-latency-at-scale trade of a little extra work for a bounded p99.

Everything here is host-side bookkeeping around futures; no device
state, no threads beyond ``threading.Timer`` fired per armed deadline/
hedge (cancelled on completion).
"""
from __future__ import annotations

import random
import threading
import time
from concurrent.futures import Future
from typing import Callable, Optional, Tuple

from .batcher import DeadlineExceeded, ServerOverloaded


def jittered_backoff(attempt: int, base_s: float = 0.002,
                     max_s: float = 0.25, jitter: float = 0.5,
                     rng: Optional[random.Random] = None) -> float:
    """Delay before retry ``attempt`` (1-based): exponential growth
    capped at ``max_s``, scaled by a uniform multiplicative jitter in
    ``[1 - jitter, 1 + jitter]`` so retrying clients decorrelate."""
    if attempt < 1:
        raise ValueError(f"attempt={attempt} is 1-based")
    delay = min(base_s * (2.0 ** (attempt - 1)), max_s)
    r = rng.random() if rng is not None else random.random()
    return delay * (1.0 - jitter + 2.0 * jitter * r)


def submit_with_retry(submit: Callable[..., Future], *args,
                      max_attempts: Optional[int] = None,
                      base_s: float = 0.002, max_s: float = 0.25,
                      jitter: float = 0.5,
                      rng: Optional[random.Random] = None,
                      should_abort: Optional[Callable[[], bool]] = None,
                      **kwargs) -> Tuple[Future, int]:
    """Call ``submit(*args, **kwargs)``, retrying ``ServerOverloaded``
    with jittered exponential backoff; returns ``(future, retries)`` so
    load generators can report how often they were shed instead of
    counting a shed as a failure.

    ``max_attempts=None`` retries until admitted (the closed-loop bench
    contract); ``should_abort`` (e.g. ``lambda: server.draining``) stops
    retrying against a target that will never admit again and re-raises
    the last ``ServerOverloaded``.
    """
    attempt = 0
    while True:
        try:
            return submit(*args, **kwargs), attempt
        except ServerOverloaded:
            if should_abort is not None and should_abort():
                raise
            attempt += 1
            if max_attempts is not None and attempt >= max_attempts:
                raise
            time.sleep(jittered_backoff(attempt, base_s, max_s, jitter,
                                        rng))


class PolicyStats:
    """Thread-safe counters for one :class:`PolicyClient` (snapshot is
    the JSON-artifact shape; ``register_into`` follows the ServeMetrics
    collector discipline)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.submitted = 0
        self.admission_retries = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.deadline_expired = 0

    def add(self, **deltas: int) -> None:
        with self._lock:
            for name, d in deltas.items():
                setattr(self, name, getattr(self, name) + d)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "submitted": self.submitted,
                "admission_retries": self.admission_retries,
                "hedges": self.hedges,
                "hedge_wins": self.hedge_wins,
                "deadline_expired": self.deadline_expired,
            }

    def register_into(self, registry, prefix: str = "policy"
                      ) -> "PolicyStats":
        import weakref

        ref = weakref.ref(self)

        def _collect():
            s = ref()
            if s is None:
                return []
            return [(f"{prefix}_{name}_total", {}, "counter", float(v))
                    for name, v in s.snapshot().items()]

        registry.register_collector(_collect)
        return self


class _Flight:
    """One policy-level request: its caller-facing future, the set of
    engine attempts still outstanding, and the timers armed for it."""

    __slots__ = ("future", "lock", "outstanding", "last_error", "timers",
                 "won_by")

    def __init__(self):
        self.future: Future = Future()
        self.lock = threading.Lock()
        self.outstanding = 0
        self.last_error: Optional[BaseException] = None
        self.timers: list = []
        self.won_by: Optional[str] = None


class PolicyClient:
    """Deadline / retry / hedge wrapper around anything with the
    ``submit(image, deadline_s=...)`` contract (a ``DynamicBatcher`` or
    an ``EnginePool``).

    ::

        client = PolicyClient(pool, deadline_s=2.0, hedge_after_s=0.5)
        skeletons = client.submit(img).result()

    - **deadline**: the remaining budget rides into every engine submit
      (server-side fail-fast before device dispatch) AND a client timer
      fails the caller's future with :class:`DeadlineExceeded` the
      moment the budget lapses — the latency promise holds even when
      the engine is wedged.
    - **retry**: admission (``ServerOverloaded``) retries with jittered
      backoff on the caller's thread, bounded by ``max_attempts`` and
      the deadline.
    - **hedge**: with ``hedge_after_s`` set, a request still unresolved
      past that age dispatches a second copy; first RESULT wins, an
      error only surfaces once every outstanding attempt failed.  At
      most one hedge per request — the tail is the target, not a
      retry storm.
    """

    def __init__(self, engine, *, deadline_s: Optional[float] = None,
                 max_attempts: int = 4, backoff_base_s: float = 0.002,
                 backoff_max_s: float = 0.25, jitter: float = 0.5,
                 hedge_after_s: Optional[float] = None, seed: int = 0,
                 stats: Optional[PolicyStats] = None):
        if max_attempts < 1:
            raise ValueError(f"max_attempts={max_attempts} must be >= 1")
        if hedge_after_s is not None and hedge_after_s <= 0:
            raise ValueError(f"hedge_after_s={hedge_after_s} must be > 0")
        self.engine = engine
        self.deadline_s = deadline_s
        self.max_attempts = max_attempts
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.jitter = jitter
        self.hedge_after_s = hedge_after_s
        self.stats = stats or PolicyStats()
        self._locked_rng = self._LockedRng(random.Random(seed),
                                           threading.Lock())

    # ------------------------------------------------------------ submit
    def submit(self, image, *,
               deadline_s: Optional[float] = None) -> Future:
        """Submit under policy; returns a future that ALWAYS resolves —
        with the decoded result, the engine's error once every attempt
        failed, or :class:`DeadlineExceeded`.

        :raises ServerOverloaded: admission still shed after
            ``max_attempts`` (nothing in flight — the caller's cue to
            back off at its own layer).
        :raises DeadlineExceeded: the deadline lapsed while still
            retrying admission (nothing was ever admitted).
        """
        budget = self.deadline_s if deadline_s is None else deadline_s
        deadline = (None if budget is None
                    else time.perf_counter() + budget)
        flight = _Flight()
        fut = self._admit(image, deadline)   # raises if never admitted
        self.stats.add(submitted=1)
        with flight.lock:
            flight.outstanding += 1
        fut.add_done_callback(
            lambda f: self._on_attempt_done(flight, f, "primary"))
        if deadline is not None:
            self._arm(flight, max(0.0, deadline - time.perf_counter()),
                      lambda: self._on_deadline(flight))
        if self.hedge_after_s is not None:
            self._arm(flight, self.hedge_after_s,
                      lambda: self._hedge(flight, image, deadline))
        return flight.future

    def call(self, image, *, deadline_s: Optional[float] = None):
        """Blocking convenience: ``submit(...).result()``."""
        return self.submit(image, deadline_s=deadline_s).result()

    # ---------------------------------------------------------- plumbing
    class _LockedRng:
        """Thread-safe ``random()`` view over the client's seeded RNG
        (submits come from many caller threads)."""

        def __init__(self, rng: random.Random, lock: threading.Lock):
            self._rng, self._lock = rng, lock

        def random(self) -> float:
            with self._lock:
                return self._rng.random()

    def _admit(self, image, deadline: Optional[float]) -> Future:
        """Engine admission with bounded jittered retry; the caller's
        thread sleeps the backoff (a closed-loop client by design)."""
        attempt = 0
        while True:
            if deadline is not None:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    self.stats.add(deadline_expired=1)
                    raise DeadlineExceeded(
                        "deadline lapsed before admission")
            else:
                remaining = None
            try:
                return self.engine.submit(image, deadline_s=remaining)
            except ServerOverloaded:
                attempt += 1
                if attempt >= self.max_attempts or \
                        getattr(self.engine, "draining", False):
                    raise
                self.stats.add(admission_retries=1)
                # the ONE retry discipline (no inline fork of the
                # formula that could drift from the helper's)
                delay = jittered_backoff(
                    attempt, self.backoff_base_s, self.backoff_max_s,
                    self.jitter, rng=self._locked_rng)
                if remaining is not None:
                    delay = min(delay, max(0.0, remaining))
                time.sleep(delay)

    def _arm(self, flight: _Flight, delay_s: float,
             fire: Callable[[], None]) -> None:
        timer = threading.Timer(delay_s, fire)
        timer.daemon = True
        with flight.lock:
            if flight.future.done():
                return
            flight.timers.append(timer)
        timer.start()

    @staticmethod
    def _cancel_timers(flight: _Flight) -> None:
        # caller holds flight.lock
        for t in flight.timers:
            t.cancel()
        flight.timers.clear()

    def _resolve(self, flight: _Flight, kind: str, result=None,
                 error: Optional[BaseException] = None) -> bool:
        with flight.lock:
            if flight.future.done():
                return False
            self._cancel_timers(flight)
            flight.won_by = kind
            try:
                if error is not None:
                    flight.future.set_exception(error)
                else:
                    flight.future.set_result(result)
            except Exception:  # noqa: BLE001 — caller cancelled; the
                # outcome is still accounted below
                pass
        return True

    def _on_attempt_done(self, flight: _Flight, fut: Future,
                         kind: str) -> None:
        try:
            result = fut.result()
            error = None
        except BaseException as e:  # noqa: BLE001 — delivered or held
            result, error = None, e
        if error is None:
            if self._resolve(flight, kind, result=result) \
                    and kind == "hedge":
                self.stats.add(hedge_wins=1)
            return
        with flight.lock:
            flight.outstanding -= 1
            flight.last_error = error
            deliver = flight.outstanding <= 0
        if deliver:
            # every outstanding attempt failed: surface the last error
            self._resolve(flight, kind, error=error)

    def _on_deadline(self, flight: _Flight) -> None:
        if self._resolve(flight, "deadline", error=DeadlineExceeded(
                "request deadline exceeded (client policy)")):
            self.stats.add(deadline_expired=1)

    def _hedge(self, flight: _Flight, image,
               deadline: Optional[float]) -> None:
        remaining = (None if deadline is None
                     else deadline - time.perf_counter())
        if remaining is not None and remaining <= 0:
            return
        with flight.lock:
            if flight.future.done():
                return
            # RESERVE the attempt slot before the submit window: a
            # primary failing while this hedge is mid-admission must
            # wait for it (the hedge exists exactly to cover that
            # failure), not race past outstanding==0 and deliver the
            # error while a winnable attempt is seconds from flight
            flight.outstanding += 1
        try:
            fut = self.engine.submit(image, deadline_s=remaining)
        except Exception:  # noqa: BLE001 — a shed/draining hedge is
            # simply not taken; release the reservation, and if the
            # primary already failed while waiting on us, deliver now
            self._attempt_abandoned(flight)
            return
        self.stats.add(hedges=1)
        fut.add_done_callback(
            lambda f: self._on_attempt_done(flight, f, "hedge"))

    def _attempt_abandoned(self, flight: _Flight) -> None:
        with flight.lock:
            flight.outstanding -= 1
            error = flight.last_error
            deliver = flight.outstanding <= 0 and error is not None
        if deliver:
            self._resolve(flight, "primary", error=error)
