"""Per-stage serving metrics: admission counters, queue depth, batch
occupancy histogram, end-to-end latency percentiles.

Built on ``utils.meters`` (``PercentileMeter`` reservoir for tail
latency); every mutator is thread-safe — submit happens on N client
threads, dispatch on the batcher thread, completion on the decode pool.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..utils.meters import PercentileMeter

#: the batcher's per-request hop waterfall, in pipeline order.  The
#: five segments PARTITION the submit→finish window with shared
#: boundary stamps, so their sum equals the measured e2e latency by
#: construction — the conservation discipline (hop sums must account
#: for ≥95% of e2e) holds exactly at this layer and the cross-hop
#: layers above it only lose callback-handoff microseconds.
#:
#: - ``queue``: submit → the dispatcher buckets the request;
#: - ``batch_formation``: bucketed → the bucket flushes to a device;
#: - ``device``: dispatch → the batch's single fetch lands (forward +
#:   compact extraction + on-device assembly on the fused lane);
#: - ``decode``: fetch → skeletons (inline O(people) finish on the
#:   fused lane; the decode pool's queue+work on the host-pool lane
#:   and for overflow fallbacks);
#: - ``deliver``: decoded → the future resolves.
HOPS = ("queue", "batch_formation", "device", "decode", "deliver")


class ServeMetrics:
    """Counters and histograms for one :class:`serve.DynamicBatcher`.

    Stages and their signals (ISSUE: queue depth, batch occupancy
    histogram, p50/p95/p99 latency, imgs/sec):

    - admission: ``submitted`` / ``rejected`` (load-shed) counts and the
      current/peak in-flight depth;
    - coalescing: ``occupancy`` — dispatched-batch-size → batch count
      (full ``max_batch`` entries mean the deadline never fired; a spike
      at 1 means traffic is too sparse for the configured wait);
    - completion: ``completed`` / ``failed`` counts, a latency reservoir
      (submit → decoded-result, seconds), and the wall-clock window for
      the imgs/sec readout;
    - decode routing: ``decode_fused`` (the request's skeletons came out
      of the fused device program) vs ``decode_host_fallback`` (an
      overflow flag routed it to the host decode pool) — the observable
      fallback rate of the device-decode lane.  The host-pool lane
      (``device_decode=False``) counts everything as fallback.

    ``model`` adds a ``{model="..."}`` label dimension to every exported
    sample: a multi-model deployment (the cascade's student and teacher
    tiers, ``serve.cascade``) registers one ServeMetrics per tier into
    the SAME registry and the traffic split stays separable in
    ``/metrics`` without a second registry or prefix forks.
    """

    def __init__(self, latency_reservoir: int = 4096,
                 model: Optional[str] = None):
        self.model = model
        self._lock = threading.Lock()
        self.latency = PercentileMeter(latency_reservoir)
        self.submitted = 0
        self.rejected = 0
        self.completed = 0
        self.failed = 0
        # deadline accounting: requests that died of DeadlineExceeded.
        # An ADMITTED request that expires is also counted in `failed`
        # (conservation: submitted == completed + failed + depth must
        # keep holding); a submit-time expiry is counted here only —
        # like `rejected`, it was never admitted.
        self.expired = 0
        self.decode_fused = 0
        self.decode_host_fallback = 0
        self.depth = 0              # in-flight requests (admitted, not done)
        self.depth_peak = 0
        self.occupancy: Dict[int, int] = {}
        # per-hop latency reservoirs: aggregate (the snapshot/bench
        # block) + per-replica (the {model=,replica=,hop=} labeled
        # exposition) — both fed once per COMPLETED request
        self.hops: Dict[str, PercentileMeter] = {
            h: PercentileMeter(latency_reservoir) for h in HOPS}
        self._hops_by_replica: Dict[int, Dict[str, PercentileMeter]] = {}
        self._hop_reservoir = latency_reservoir
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        self._t_busy: Optional[float] = None  # last idle->busy instant

    # ------------------------------------------------------------- hooks
    def on_submit(self) -> None:
        with self._lock:
            self.submitted += 1
            self.depth += 1
            self.depth_peak = max(self.depth_peak, self.depth)
            if self.depth == 1:
                # idle -> busy transition: the stall clock anchors HERE,
                # never at the last completion of a previous busy period
                # — or a request admitted after an idle gap would be
                # born with stall_age == the idle time, and a router
                # would false-fence a healthy replica the instant a
                # failover re-submission lands on it (the serve chaos
                # harness caught exactly that cascade)
                self._t_busy = time.perf_counter()
            if self._t_first is None:
                self._t_first = time.perf_counter()

    def on_reject(self) -> None:
        with self._lock:
            self.rejected += 1

    def on_dispatch(self, batch_size: int) -> None:
        with self._lock:
            self.occupancy[batch_size] = self.occupancy.get(
                batch_size, 0) + 1

    def on_decode(self, fused: bool) -> None:
        """One request routed to its decode stage: the fused device
        program's inline finish, or the host decode pool (overflow
        fallback / host-pool lane)."""
        with self._lock:
            if fused:
                self.decode_fused += 1
            else:
                self.decode_host_fallback += 1

    def on_hops(self, replica: int, durations) -> None:
        """One completed request's hop waterfall: ``durations`` aligned
        with :data:`HOPS` (seconds).  Fed alongside ``on_complete`` so
        hop sums and the e2e reservoir describe the same request set —
        what makes the conservation check (Σ hop sums ≥ 95% of
        Σ e2e) well-defined."""
        with self._lock:
            per = self._hops_by_replica.get(replica)
            if per is None:
                per = self._hops_by_replica[replica] = {
                    h: PercentileMeter(self._hop_reservoir)
                    for h in HOPS}
            for hop, d in zip(HOPS, durations):
                d = max(float(d), 0.0)
                self.hops[hop].update(d)
                per[hop].update(d)

    def on_complete(self, latency_s: float) -> None:
        with self._lock:
            self.completed += 1
            self.depth -= 1
            self.latency.update(latency_s)
            self._t_last = time.perf_counter()

    def on_fail(self, expired: bool = False) -> None:
        with self._lock:
            self.failed += 1
            if expired:
                self.expired += 1
            self.depth -= 1
            self._t_last = time.perf_counter()

    def on_expire_rejected(self) -> None:
        """A submit whose deadline was already non-positive: refused at
        the door, never admitted (no depth/submitted movement)."""
        with self._lock:
            self.expired += 1

    def stall_age_s(self) -> Optional[float]:
        """Seconds since the pipeline last made progress while work is
        IN FLIGHT — ``None`` when idle.  Progress is a completion or
        failure; the anchor is the LATER of the last progress and the
        start of the current busy period (idle time before the current
        work was admitted is not a stall).  The health-probe signal a
        router uses to call a replica wedged: depth stuck above zero
        with a growing stall age means admitted work stopped moving."""
        with self._lock:
            if self.depth <= 0:
                return None
            anchor = self._t_busy
            if self._t_last is not None and (anchor is None
                                             or self._t_last > anchor):
                anchor = self._t_last
            if anchor is None:
                return None
            return time.perf_counter() - anchor

    # --------------------------------------------------------- telemetry
    def register_into(self, registry, prefix: str = "serve"
                      ) -> "ServeMetrics":
        """Export every signal through a shared ``obs.Registry`` so
        serve and train ride ONE exposition path (``/metrics``).

        Registered as a scrape-time collector rather than mirrored
        metric objects: the counters already live behind this object's
        lock, so sampling at scrape time adds zero hot-path cost and
        can never drift from :meth:`snapshot`.  The collector holds
        only a weakref — a registry that outlives its batcher (the
        process-global one) scrapes a dead source as no samples instead
        of pinning it forever.
        """
        import weakref

        ref = weakref.ref(self)

        def _collect():
            m = ref()
            return m.collect(prefix) if m is not None else []

        registry.register_collector(_collect)
        return self

    def collect(self, prefix: str = "serve"):
        """(name, labels, kind, value) samples for ``obs.Registry``."""
        with self._lock:
            counts = (("submitted", self.submitted),
                      ("rejected", self.rejected),
                      ("completed", self.completed),
                      ("failed", self.failed),
                      ("expired", self.expired),
                      ("decode_fused", self.decode_fused),
                      ("decode_host_fallback", self.decode_host_fallback))
            depth, peak = self.depth, self.depth_peak
            occupancy = dict(self.occupancy)
            lat = self.latency.summary()   # seconds
            lat_sum = self.latency.sum
            hop_samples = [
                (str(replica), hop, m.summary(), m.sum)
                for replica, per in sorted(self._hops_by_replica.items())
                for hop, m in per.items()]
            hop_sum_total = sum(m.sum for m in self.hops.values())
        # the per-tier label dimension: one dict merged into EVERY
        # sample's labels, so a shared registry separates student vs
        # teacher traffic without a second registry or prefix fork
        base = {"model": self.model} if self.model else {}
        samples = [(f"{prefix}_{name}_total", dict(base), "counter",
                    float(v))
                   for name, v in counts]
        samples += [
            (f"{prefix}_queue_depth", dict(base), "gauge", float(depth)),
            (f"{prefix}_queue_depth_peak", dict(base), "gauge",
             float(peak)),
        ]
        for size, n in sorted(occupancy.items()):
            samples.append((f"{prefix}_batches_total",
                            {**base, "size": str(size)}, "counter",
                            float(n)))
        for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
            samples.append((f"{prefix}_latency_seconds",
                            {**base, "quantile": q}, "gauge", lat[key]))
        samples += [
            (f"{prefix}_latency_seconds_sum", dict(base), "counter",
             lat_sum),
            (f"{prefix}_latency_seconds_count", dict(base), "counter",
             float(lat["count"])),
            (f"{prefix}_imgs_per_sec", dict(base), "gauge",
             self.throughput()),
            # the conservation invariant as a scrapeable gauge (ROADMAP
            # item 1 names it as an autoscaler input; snapshot() alone
            # kept it off /metrics and out of the history store).  1.0
            # is the vacuous reading — before any completion, and at
            # layers that never receive on_hops (the pool-level rollup:
            # hop attribution lives on the engines) — because a 0.0
            # would read as a hard accounting break
            (f"{prefix}_hop_conservation_frac", dict(base), "gauge",
             (hop_sum_total / lat_sum
              if lat_sum > 0 and hop_sum_total > 0 else 1.0)),
            # mean images per dispatched batch — the occupancy-headroom
            # input of serve.capacity.CapacityModel
            (f"{prefix}_batch_occupancy_mean", dict(base), "gauge",
             (sum(k * v for k, v in occupancy.items())
              / sum(occupancy.values()) if occupancy else 0.0)),
        ]
        # the per-hop attribution families: {model=,replica=,hop=}
        # labeled quantiles + _sum/_count, one series set per hop per
        # replica — the registry-level half of the request waterfall
        # (the per-request half is obs.reqtrace)
        for replica, hop, s, hop_sum in hop_samples:
            labels = {**base, "replica": replica, "hop": hop}
            for q, key in (("0.5", "p50"), ("0.95", "p95"),
                           ("0.99", "p99")):
                samples.append((f"{prefix}_hop_latency_seconds",
                                {**labels, "quantile": q}, "gauge",
                                s[key]))
            samples += [
                (f"{prefix}_hop_latency_seconds_sum", labels, "counter",
                 hop_sum),
                (f"{prefix}_hop_latency_seconds_count", labels,
                 "counter", float(s["count"])),
            ]
        return samples

    # ----------------------------------------------------------- readout
    def mean_occupancy(self) -> float:
        """Mean images per dispatched batch (0.0 before any dispatch)."""
        with self._lock:
            n_batches = sum(self.occupancy.values())
            n_images = sum(k * v for k, v in self.occupancy.items())
        return n_images / n_batches if n_batches else 0.0

    def throughput(self) -> float:
        """Completed imgs/sec over the first-submit → last-completion
        window (0.0 until at least one request completed)."""
        with self._lock:
            if (self._t_first is None or self._t_last is None
                    or self._t_last <= self._t_first):
                return 0.0
            return self.completed / (self._t_last - self._t_first)

    def snapshot(self) -> dict:
        """One JSON-ready dict of every signal (latencies in ms)."""
        with self._lock:
            occupancy = dict(sorted(self.occupancy.items()))
            out = {
                **({"model": self.model} if self.model else {}),
                "submitted": self.submitted,
                "rejected": self.rejected,
                "completed": self.completed,
                "failed": self.failed,
                "expired": self.expired,
                "decode_fused": self.decode_fused,
                "decode_host_fallback": self.decode_host_fallback,
                "queue_depth": self.depth,
                "queue_depth_peak": self.depth_peak,
                "occupancy_histogram": {str(k): v
                                        for k, v in occupancy.items()},
                "latency_ms": self.latency.summary(scale=1e3),
                # the per-hop decomposition block (ms): p50/p95/p99 +
                # exact mean/count/sum per hop, aggregated over
                # replicas — what the bench artifacts commit alongside
                # their e2e numbers
                "hops_ms": {
                    h: {**m.summary(scale=1e3),
                        "sum": round(m.sum * 1e3, 3)}
                    for h, m in self.hops.items()},
                "hop_conservation_frac": (
                    round(sum(m.sum for m in self.hops.values())
                          / self.latency.sum, 4)
                    if self.latency.sum > 0 else None),
            }
        out["mean_batch_occupancy"] = round(self.mean_occupancy(), 3)
        out["imgs_per_sec"] = round(self.throughput(), 3)
        return out
