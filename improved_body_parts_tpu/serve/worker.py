"""Process-isolated serving worker: the slot-row serve loop one
:class:`~improved_body_parts_tpu.serve.router.ProcessWorkerEngine`
drives over the PR 2 shared-memory wire.

One worker process = one predictor = one jax runtime.  The router
writes each request's image into a preallocated shared-memory slot row
and posts ``("req", slot, seq)`` on the task channel; the worker
serves it (fused device decode with the documented host overflow
fallback) and writes the FIXED-SHAPE person table — ``(max_people,
num_parts, 3)`` float64 keypoints + per-person scores + the free
escalation signals — back into the same slot's response fields.  No
pickling of payloads on either hop: only tiny ``(kind, slot, seq)``
tokens cross the token channels, exactly the ``data.shm_ring``
discipline (seqlock headers, spawn workers, orphan watchdog,
resource-tracker-quiet attach) — with one deliberate upgrade: the
channels are raw one-way ``multiprocessing.Pipe`` connections instead
of ``mp.Queue``.  A Queue puts a FEEDER THREAD on every hop (put →
feeder wake → pipe → reader), and on the serve request path each
thread wake is a scheduler round-trip that lands straight in the
latency budget; a bare pipe sends the token synchronously in the
caller.

The worker's predictor comes from an importable **factory spec**
(``"module:callable"`` + JSON-safe kwargs) so the child process builds
its own instance — tests and the chaos harness point the spec at
:func:`constant_predictor` (deterministic, zero XLA compiles), the
bench at a planted-weights real predictor.  A factory result that
exposes ``serve_one(image) -> (people, signals)`` is used directly;
anything with the ``Predictor.predict_decoded`` contract gets the
fused-decode + overflow-fallback serve path built around it.

Timestamps on the wire are ``time.perf_counter()`` from the worker
process: on Linux that is CLOCK_MONOTONIC, which is system-wide, so
worker-side hop boundaries land on the same axis as the router's
submit/finish stamps (the ``data.shm_ring`` render-span precedent).
"""
import importlib
import json
import os
import time
import traceback
from typing import Optional, Tuple

import numpy as np

from ..data.shm_ring import (
    _align,
    _attach_shm,
    _HEADER_INTS,
    _quiet_close,
    _slot_layout,
    _slot_views,
)
from ..obs.fleet import (
    REC_DONE,
    REC_EXEC_DONE,
    REC_FLOATS,
    REC_PICKUP,
    REC_WARMUP,
    TELEM_FLOATS,
    WorkerTelemetry,
    flow_id,
)
from ..train.supervisor import chaos_kill_point

#: wire schema version — bumped whenever the slot field list changes;
#: router and worker are always the same build (spawned, not network
#: peers) so this is a debugging aid, not a negotiation.
#: v2: the region grew two trailing blocks after the heartbeat row —
#: the worker telemetry snapshot block and the crash-persistent flight
#: recorder ring (``obs.fleet`` owns both layouts); the 4-float
#: heartbeat survives at its v1 offset as the degenerate case.
WIRE_VERSION = 2

#: response status codes (meta_out[0])
STATUS_OK = 0.0
STATUS_ERROR = 1.0
STATUS_EXPIRED = 2.0

#: bytes reserved for a worker-side error message (utf-8, truncated)
ERR_BYTES = 256

#: trailing per-worker heartbeat block (after the slot rows):
#: [perf_counter stamp, served_total, recompiles_post_warmup, pid]
HB_FLOATS = 4


def wire_format(max_hw: Tuple[int, int], num_parts: int,
                max_people: int):
    """(names, shapes, dtypes) of one request/response slot.

    Request fields: the uint8 image row (padded to the worker's max
    bucket) + ``meta_in`` = [h, w, deadline_abs (0 = none), t_submit].
    Response fields: the fixed-shape person table (``kps`` rows are
    (x, y, present) — float64 so the table is bit-identical to the
    in-process decode), per-person ``scores``, the escalation-signal
    vector ``sig`` = [has, n_people, peak_ovf, cand_ovf, person_ovf,
    min_mean_score, fused, reserved], ``meta_out`` = [status, n_encoded,
    t_pickup, t_exec0, t_exec1, t_decode, n_truncated, reserved] and an
    ``err`` utf-8 message row.
    """
    h, w = max_hw
    names = ("img", "meta_in", "kps", "scores", "sig", "meta_out", "err")
    shapes = ((h, w, 3), (4,), (max_people, num_parts, 3),
              (max_people,), (8,), (8,), (ERR_BYTES,))
    dtypes = ("uint8", "float64", "float64", "float64", "float64",
              "float64", "uint8")
    return names, shapes, dtypes


def region_size(slots: int, shapes, dtypes) -> int:
    """Total shared-memory bytes: seqlock headers + slot rows + the
    trailing heartbeat block + the telemetry snapshot block + the
    flight-recorder ring (wire v2)."""
    _, slot_bytes = _slot_layout(shapes, dtypes)
    return (_align(slots * _HEADER_INTS * 8) + slots * slot_bytes
            + _align(HB_FLOATS * 8) + _align(TELEM_FLOATS * 8)
            + _align(REC_FLOATS * 8))


def hb_view(buf, slots: int, shapes, dtypes, writeable: bool):
    """The 4-float heartbeat row after the slot rows (its v1 offset —
    the degenerate case when telemetry is off)."""
    _, slot_bytes = _slot_layout(shapes, dtypes)
    off = _align(slots * _HEADER_INTS * 8) + slots * slot_bytes
    v = np.frombuffer(buf, np.float64, HB_FLOATS, offset=off)
    v.flags.writeable = writeable
    return v


def telem_view(buf, slots: int, shapes, dtypes, writeable: bool):
    """The worker telemetry snapshot block (``obs.fleet`` layout,
    seqlock-parity word at index 0) after the heartbeat row."""
    _, slot_bytes = _slot_layout(shapes, dtypes)
    off = (_align(slots * _HEADER_INTS * 8) + slots * slot_bytes
           + _align(HB_FLOATS * 8))
    v = np.frombuffer(buf, np.float64, TELEM_FLOATS, offset=off)
    v.flags.writeable = writeable
    return v


def rec_view(buf, slots: int, shapes, dtypes, writeable: bool):
    """The crash-persistent flight-recorder ring at the region tail —
    what the router exhumes after a worker death."""
    _, slot_bytes = _slot_layout(shapes, dtypes)
    off = (_align(slots * _HEADER_INTS * 8) + slots * slot_bytes
           + _align(HB_FLOATS * 8) + _align(TELEM_FLOATS * 8))
    v = np.frombuffer(buf, np.float64, REC_FLOATS, offset=off)
    v.flags.writeable = writeable
    return v


def encode_people(people, signals, kps, scores, sig, meta_out) -> None:
    """Write one request's decoded people into the slot's response
    views.  ``people`` is the engine result shape (``decode_device`` /
    ``decode_compact`` output: a list of ``(keypoints, score)`` with
    ``keypoints`` a per-part list of ``None`` or ``(x, y)``); entries
    past the table capacity are dropped and counted in
    ``meta_out[6]``."""
    max_people, num_parts = kps.shape[:2]
    kps[:] = 0.0
    scores[:] = 0.0
    n = min(len(people), max_people)
    for p in range(n):
        parts, score = people[p]
        scores[p] = float(score)
        for j in range(min(len(parts), num_parts)):
            kp = parts[j]
            if kp is not None:
                kps[p, j, 0] = float(kp[0])
                kps[p, j, 1] = float(kp[1])
                kps[p, j, 2] = 1.0
    sig[:] = 0.0
    if signals is not None:
        sig[0] = 1.0
        sig[1] = float(signals.n_people)
        sig[2] = float(signals.peak_overflow)
        sig[3] = float(signals.cand_overflow)
        sig[4] = float(signals.person_overflow)
        sig[5] = float(signals.min_mean_score)
        sig[6] = float(signals.fused)
    meta_out[1] = float(n)
    meta_out[6] = float(len(people) - n)


def decode_people(kps, scores, sig):
    """Inverse of :func:`encode_people`: the engine result (list of
    ``(keypoints, score)``) plus the :class:`EscalationSignals` (or
    ``None``) — copies out of the shared views so the slot can be
    recycled."""
    from ..infer.decode import EscalationSignals

    # n_encoded rides meta_out; infer from the table alone so decoding
    # needs only the three payload views
    present = kps[:, :, 2] != 0.0
    used = np.flatnonzero(present.any(axis=1) | (scores != 0.0))
    n = int(used[-1] + 1) if used.size else 0
    people = []
    for p in range(n):
        parts = []
        for j in range(kps.shape[1]):
            if kps[p, j, 2] != 0.0:
                parts.append((float(kps[p, j, 0]), float(kps[p, j, 1])))
            else:
                parts.append(None)
        people.append((parts, float(scores[p])))
    signals = None
    if sig[0] != 0.0:
        signals = EscalationSignals(
            n_people=int(sig[1]), peak_overflow=bool(sig[2]),
            cand_overflow=bool(sig[3]), person_overflow=bool(sig[4]),
            min_mean_score=float(sig[5]), fused=bool(sig[6]))
    return people, signals


# --------------------------------------------------------------------- #
# predictor factories (importable from the spawned child)               #
# --------------------------------------------------------------------- #
class _ConstantPredictor:
    """Deterministic fake worker predictor: people derived from integer
    image content only (bit-identical in any process), optional per-
    request delay to hold work in flight for crash/drain tests."""

    def __init__(self, num_parts: int = 18, n_people: int = 2,
                 delay_s: float = 0.0, fail_every: int = 0):
        self.num_parts = num_parts
        self.n_people = n_people
        self.delay_s = delay_s
        self.fail_every = fail_every
        self._calls = 0

    def serve_one(self, image):
        from ..infer.decode import EscalationSignals

        if self.delay_s:
            time.sleep(self.delay_s)
        self._calls += 1
        if self.fail_every and self._calls % self.fail_every == 0:
            raise ValueError("injected predictor failure "
                             f"(call {self._calls})")
        base = float(int(image[0, 0, 0])) if image.size else 0.0
        h, w = image.shape[:2]
        people = []
        for p in range(self.n_people):
            parts = []
            for j in range(self.num_parts):
                if (p + j) % 5 == 4:
                    parts.append(None)     # a missing part per person
                else:
                    parts.append((base + p * 7.0 + j * 3.0,
                                  float(h - p) + j * 2.0))
            people.append((parts, base + float(w % 97) + p))
        signals = EscalationSignals(
            n_people=len(people), peak_overflow=False,
            cand_overflow=False, person_overflow=False,
            min_mean_score=base + 1.0, fused=True)
        return people, signals


def constant_predictor(num_parts: int = 18, n_people: int = 2,
                       delay_s: float = 0.0,
                       fail_every: int = 0) -> _ConstantPredictor:
    """Factory spec target for tests/chaos: zero XLA, bit-deterministic
    output from the image's integer content alone.  ``delay_s`` holds
    each request in flight (crash/drain windows); ``fail_every=n``
    raises on every n-th call (error-delivery path)."""
    return _ConstantPredictor(num_parts=num_parts, n_people=n_people,
                              delay_s=delay_s, fail_every=fail_every)


def load_predictor(spec: str, kwargs: Optional[dict] = None):
    """Build the worker's predictor from an importable factory spec
    ``"module:callable"`` — the child process owns its own instance
    (and its own jax runtime when the factory builds a real one)."""
    mod_name, _, fn_name = spec.partition(":")
    if not mod_name or not fn_name:
        raise ValueError(f"predictor spec {spec!r} is not "
                         "'module:callable'")
    fn = getattr(importlib.import_module(mod_name), fn_name)
    return fn(**(kwargs or {}))


def _build_serve_fn(pred):
    """``serve(image) -> (people, signals)`` for either worker
    predictor contract: ``serve_one`` (fakes) or the real
    ``predict_decoded`` fused path with the documented host overflow
    fallback."""
    if hasattr(pred, "serve_one"):
        return pred.serve_one
    from ..infer.decode import device_signals
    from ..infer.pipeline import device_decode_fn

    decode_one = device_decode_fn(pred)

    def serve(image):
        dev = pred.predict_decoded(image)
        signals = device_signals(dev)
        return decode_one(dev, image), signals

    return serve


def _warmup(pred, image_sizes, batch_sizes, max_batch: int) -> dict:
    if not hasattr(pred, "predict_decoded"):
        return {"bucket_shapes": [], "batch_sizes": [],
                "newly_compiled": 0}
    from .warmup import precompile

    return precompile([pred], [tuple(s) for s in image_sizes],
                      max_batch, batch_sizes=batch_sizes, decode=True)


# --------------------------------------------------------------------- #
# worker main (spawn target)                                             #
# --------------------------------------------------------------------- #
def worker_main(worker_idx: int, shm_name: str, slots: int,
                shapes, dtypes, spec: str, spec_kwargs_json: str,
                task_rx, done_tx, parent_pid: int,
                sink_path: Optional[str] = None,
                max_batch: int = 4,
                telemetry: bool = True,
                trace_path: Optional[str] = None,
                run_id: Optional[str] = None) -> None:
    """Worker process entry (spawn target — module importable).

    ``task_rx`` / ``done_tx`` are the one-way pipe connections of the
    token channels (read tasks, write answers).  Serve loop: poll the
    task channel (2 s timeout doubling as the orphan watchdog +
    heartbeat tick), serve each ``("req", slot, seq)`` under the slot
    seqlock, answer with ``("done", worker_idx, slot, seq)``.
    ``("warmup", sizes, batch_sizes)`` precompiles the predictor's
    bucket programs and arms the worker's own ``CompileWatch`` so
    post-warmup recompiles are counted IN the process that would pay
    them (published through the telemetry block; mirrored into the
    heartbeat row for the degenerate case).  A factory/attach failure
    answers ``("init_err", worker_idx, tb)`` and exits — the router's
    lifecycle discipline decides whether to respawn.

    ``telemetry=False`` is the explicit OFF arm of the fleet-obs A/B:
    null sink, null tracer, no snapshot publishes, no flight records —
    only the PR 16 4-float heartbeat moves.  ``trace_path`` names this
    worker's span-file shard (the parent composes the ``.pN`` suffix);
    ``run_id`` stamps the sink shard with the parent run's identity so
    the report tools can refuse a stray shard from another run.
    """
    shm = None
    try:
        try:
            import cv2

            cv2.setNumThreads(0)
        except Exception:  # noqa: BLE001 — cv2 optional in the child
            pass
        from ..obs.events import EventSink, NullSink, set_sink

        sink = None
        if sink_path and telemetry:
            # the PR 3 multi-process rule: non-lead processes write
            # their own sink shard so streams never interleave
            meta = {"role": "serve_worker", "worker": worker_idx}
            if run_id:
                meta["run_id"] = run_id
            sink = EventSink(sink_path + f".p{worker_idx + 1}",
                             run_meta=meta)
            set_sink(sink)
            sink.emit("worker_start", worker=worker_idx,
                      pid=os.getpid(), spec=spec)
        else:
            # the OFF arm installs the null sink EXPLICITLY (not "no
            # sink happened to be configured") — the A/A hazard rule
            set_sink(NullSink())
        pred = load_predictor(spec, json.loads(spec_kwargs_json))
        serve = _build_serve_fn(pred)
        shm = _attach_shm(shm_name)
        header, views = _slot_views(shm.buf, slots, shapes, dtypes,
                                    writeable=True)
        hb = hb_view(shm.buf, slots, shapes, dtypes, writeable=True)
        hb[3] = float(os.getpid())
        telem = telem_view(shm.buf, slots, shapes, dtypes,
                           writeable=True)
        rec = rec_view(shm.buf, slots, shapes, dtypes, writeable=True)
        wt = WorkerTelemetry(worker_idx, telem, rec, enabled=telemetry,
                             sink=sink,
                             trace_t0=sink.t0 if sink is not None
                             else None)
        from ..obs.trace import set_tracer

        # worker-process tracer: the bounded ring the trace shard
        # flushes from; the null recorder on the OFF arm
        set_tracer(wt.trace)
    except BaseException:  # noqa: BLE001 — surfaced to the router
        try:
            done_tx.send(("init_err", worker_idx,
                          traceback.format_exc()))
        except (OSError, ValueError, BrokenPipeError):
            pass            # router already gone
        if shm is not None:
            _quiet_close(shm)
        return

    try:
        _serve_loop(worker_idx, header, views, hb, task_rx, done_tx,
                    parent_pid, sink, serve, pred, wt, max_batch,
                    trace_path)
    finally:
        # live views make a plain close() raise BufferError at
        # interpreter teardown; detach quietly (the shm_ring worker
        # exit discipline) — the router owns the region's lifetime
        _quiet_close(shm)


#: seconds between periodic worker trace-shard flushes (busy path);
#: idle beats and the poison-pill exit also flush, so a clean stop
#: never loses spans — only a crash does, which is what the flight
#: recorder ring is for
TRACE_FLUSH_S = 5.0


def _serve_loop(worker_idx, header, views, hb, task_rx, done_tx,
                parent_pid, sink, serve, pred, wt,
                max_batch: int, trace_path: Optional[str]) -> None:
    served = 0
    watch = wt.watch
    tracer = wt.trace
    track = f"worker{worker_idx}-serve"
    last_flush = time.perf_counter()
    burst = 0

    def beat(force: bool = False) -> None:
        hb[0] = time.perf_counter()
        hb[1] = float(served)
        hb[2] = float(watch.recompiles.value)
        # busy-path publishes stay throttled (the snapshot sorts the
        # hop reservoirs — hot-loop cost); the idle tick forces, so a
        # quiescent parent reads CURRENT counters within one tick
        wt.publish(force=force)

    def flush_trace(now: float) -> float:
        wt.flush_trace(trace_path)
        return now

    beat()

    def serve_slot(idx: int, seq: int) -> None:
        nonlocal served
        img_v, meta_in, kps, scores, sig, meta_out, err = views[idx]
        t_pickup = time.perf_counter()
        h, w = int(meta_in[0]), int(meta_in[1])
        deadline = float(meta_in[2])
        image = img_v[:h, :w]
        # flight record BEFORE any kill point: a SIGKILL mid-serve must
        # still leave the pickup milestone for the postmortem to name
        wt.record(REC_PICKUP, idx, seq, a=deadline)
        # response write under the slot seqlock: odd while mutating,
        # back to even (seq + 2) when consistent — a router that reads
        # a mismatched seq discards the slot as stale
        header[idx, 0] = seq + 1
        err[:] = 0
        meta_out[:] = 0.0
        meta_out[2] = t_pickup
        t0 = time.perf_counter()
        meta_out[3] = t0
        try:
            if deadline > 0.0 and t0 > deadline:
                meta_out[0] = STATUS_EXPIRED
            else:
                chaos_kill_point("worker_serve")
                people, signals = serve(image)
                meta_out[4] = time.perf_counter()
                wt.record(REC_EXEC_DONE, idx, seq)
                chaos_kill_point("worker_respond")
                encode_people(people, signals, kps, scores, sig,
                              meta_out)
                meta_out[0] = STATUS_OK
        except BaseException:  # noqa: BLE001 — delivered per request
            meta_out[0] = STATUS_ERROR
            msg = traceback.format_exc(limit=3).encode()[-ERR_BYTES:]
            err[:len(msg)] = np.frombuffer(msg, np.uint8)
        if meta_out[4] == 0.0:
            meta_out[4] = time.perf_counter()
        t_done = time.perf_counter()
        meta_out[5] = t_done
        header[idx, 0] = seq + 2
        status = float(meta_out[0])
        wt.record(REC_DONE, idx, seq, a=status)
        wt.count_status(status == STATUS_OK,
                        expired=status == STATUS_EXPIRED)
        if status == STATUS_OK:
            # the hops this process pays, measured where they happen
            # (the router sees the same stamps from the wire — its
            # on_hops feed stays the SLO input; see obs.fleet)
            wt.observe_hops(float(meta_out[4]) - float(meta_out[3]),
                            t_done - float(meta_out[4]))
        if tracer.enabled:
            tracer.add_span_abs("serve", t_pickup, t_done - t_pickup,
                                track=track,
                                args={"slot": idx, "seq": seq,
                                      "status": int(status)})
            tracer.add_span_abs("device", float(meta_out[3]),
                                float(meta_out[4]) - float(meta_out[3]),
                                track=track)
            tracer.add_span_abs("decode", float(meta_out[4]),
                                t_done - float(meta_out[4]),
                                track=track)
            # flow step: threads the router's submit→deliver arc
            # through this worker's serve slice — keyed (cat, id) so
            # every (worker, slot, seq) is its own arc
            tracer.flow_step("req", flow_id(worker_idx, idx, seq),
                             track=track, cat="proc",
                             ts=(t_pickup - tracer.t0)
                             + (t_done - t_pickup) / 2.0)
        served += 1
        done_tx.send(("done", worker_idx, idx, seq))

    while True:
        try:
            if not task_rx.poll(2.0):
                if burst:
                    wt.on_burst(burst)
                    burst = 0
                wt.sample_memory()
                beat(force=True)
                now = time.perf_counter()
                if trace_path and now - last_flush > TRACE_FLUSH_S:
                    last_flush = flush_trace(now)
                if parent_pid and os.getppid() != parent_pid:
                    return  # orphaned: the router is gone
                continue
            task = task_rx.recv()
        except (EOFError, OSError, ValueError):
            return          # router closed the channel / died
        if task is None:
            if burst:
                wt.on_burst(burst)
            wt.publish(force=True)
            if trace_path:
                flush_trace(time.perf_counter())
            if sink is not None:
                sink.emit("worker_stop", worker=worker_idx,
                          served=served)
                sink.close()
            return
        kind = task[0]
        if kind == "req":
            burst += 1
            serve_slot(task[1], task[2])
            if not task_rx.poll(0):
                # burst over: no token waiting — the occupancy signal
                # (mean requests drained back-to-back per wakeup)
                wt.on_burst(burst)
                burst = 0
                now = time.perf_counter()
                if trace_path and now - last_flush > TRACE_FLUSH_S:
                    last_flush = flush_trace(now)
            beat()
        elif kind == "warmup":
            try:
                info = _warmup(pred, task[1], task[2], max_batch)
                watch.mark_warm("worker warmup precompile")
                wt.record(REC_WARMUP, a=1.0)
                done_tx.send(("warmup_done", worker_idx, info))
            except BaseException:  # noqa: BLE001 — warmup failure is
                # an answer, not a crash: the router decides
                wt.record(REC_WARMUP, a=0.0)
                done_tx.send(("warmup_err", worker_idx,
                              traceback.format_exc()))
            wt.publish(force=True)
            beat()
