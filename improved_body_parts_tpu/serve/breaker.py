"""Failure-rate circuit breaker: stop routing to a replica that keeps
failing, probe it back to life after a cooldown.

The pool's failover machinery (``serve.pool.EnginePool``) re-submits a
failed request to another replica — correct per request, but a replica
whose program is poisoned (raises on every execute) would keep eating a
first attempt from every unlucky request routed to it.  The breaker is
the aggregate view: a sliding window of recent outcomes trips OPEN past
a failure-rate threshold, the replica stops receiving traffic at all,
and after ``cooldown_s`` a bounded number of HALF-OPEN probe requests
test whether it healed — probes all succeed and the breaker closes,
any probe fails and the cooldown restarts.

States (the classic three):

- ``closed``    — healthy, all traffic flows, outcomes recorded;
- ``open``      — tripped, :meth:`allow` is False until the cooldown;
- ``half_open`` — cooldown passed, up to ``half_open_probes`` requests
  are admitted to test the waters.

Thread-safe; every transition is taken under one lock.  The clock is
injectable so tests drive the cooldown deterministically instead of
sleeping.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

#: state -> numeric code for gauges (obs exposition)
STATE_CODES = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


class CircuitBreaker:
    """Sliding-window failure-rate breaker with half-open probing.

    ::

        breaker = CircuitBreaker(failure_threshold=0.5, min_requests=8)
        if breaker.allow():
            try:
                ...  # the guarded call
                breaker.record_success()
            except Exception:
                breaker.record_failure()
                raise

    ``min_requests`` is the volume floor: a window with fewer outcomes
    never trips (one failed request out of one is 100% failure rate but
    zero evidence).
    """

    def __init__(self, *, failure_threshold: float = 0.5,
                 min_requests: int = 8, window: int = 32,
                 cooldown_s: float = 5.0, half_open_probes: int = 2,
                 clock: Optional[Callable[[], float]] = None):
        if not 0.0 < failure_threshold <= 1.0:
            raise ValueError(f"failure_threshold={failure_threshold} "
                             "must be in (0, 1]")
        if min_requests < 1 or window < min_requests:
            raise ValueError(f"need window >= min_requests >= 1, got "
                             f"window={window} min_requests={min_requests}")
        if half_open_probes < 1:
            raise ValueError(f"half_open_probes={half_open_probes} "
                             "must be >= 1")
        self.failure_threshold = failure_threshold
        self.min_requests = min_requests
        self.cooldown_s = cooldown_s
        self.half_open_probes = half_open_probes
        self._clock = clock or time.monotonic
        self._lock = threading.Lock()
        self._window: "deque[bool]" = deque(maxlen=window)  # True = fail
        self._state = "closed"
        self._opened_at = 0.0
        self._probes_out = 0       # half-open: probes admitted
        self._probe_successes = 0  # half-open: probes that came back ok
        self.trips = 0             # lifetime open transitions (telemetry)

    # ---------------------------------------------------------- readouts
    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    @property
    def state_code(self) -> float:
        """Numeric state for gauges: 0 closed, 1 half-open, 2 open."""
        return STATE_CODES[self.state]

    def failure_rate(self) -> float:
        with self._lock:
            if not self._window:
                return 0.0
            return sum(self._window) / len(self._window)

    # ------------------------------------------------------- transitions
    def _maybe_half_open_locked(self) -> None:
        if self._state == "open" and \
                self._clock() - self._opened_at >= self.cooldown_s:
            self._state = "half_open"
            self._probes_out = 0
            self._probe_successes = 0

    def _trip_locked(self) -> None:
        self._state = "open"
        self._opened_at = self._clock()
        self._window.clear()
        self.trips += 1

    def allow(self) -> bool:
        """May one request be routed here right now?  In half-open this
        CONSUMES a probe slot — call it once per actual submission."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == "closed":
                return True
            if self._state == "half_open" and \
                    self._probes_out < self.half_open_probes:
                self._probes_out += 1
                return True
            return False

    def release_probe(self) -> None:
        """Give back a half-open probe slot that was consumed by
        :meth:`allow` but never turned into a real submission (the
        replica shed the request) — without this the slot would stay
        consumed with no outcome ever recorded and the breaker could
        wedge in half-open."""
        with self._lock:
            if self._state == "half_open" and self._probes_out > 0:
                self._probes_out -= 1

    def probation(self) -> None:
        """Straight to half-open (a restarted replica earns its traffic
        back through bounded probes instead of a full reopen)."""
        with self._lock:
            self._state = "half_open"
            self._probes_out = 0
            self._probe_successes = 0
            self._window.clear()

    def record_success(self) -> None:
        with self._lock:
            if self._state == "half_open":
                self._probe_successes += 1
                if self._probe_successes >= self.half_open_probes:
                    # the replica healed: fresh window, full traffic
                    self._state = "closed"
                    self._window.clear()
                return
            if self._state == "closed":
                self._window.append(False)

    def record_failure(self) -> None:
        with self._lock:
            if self._state == "half_open":
                # a probe failed: straight back to open, new cooldown
                self._trip_locked()
                return
            if self._state != "closed":
                return
            self._window.append(True)
            if len(self._window) >= self.min_requests and \
                    sum(self._window) / len(self._window) \
                    >= self.failure_threshold:
                self._trip_locked()

    def reset(self) -> None:
        """Force-close (a replica restart wipes the evidence)."""
        with self._lock:
            self._state = "closed"
            self._window.clear()
            self._probes_out = 0
            self._probe_successes = 0
