"""Serve-time predictor factories: checkpoints → dtype'd Predictors.

The worker processes (``serve.worker.load_predictor``) build their
predictor from an importable ``"module:callable"`` spec; this module is
the production spec target.  :func:`checkpoint_predictor` routes the
weight-storage policy through ``utils.precision.apply_serve_dtype`` —
the SAME chain ``tools/export_model.py`` serializes and the graftaudit
registry fingerprints — so a worker spawned with
``params_dtype="int8"`` serves the exact program family the int8
artifact's blessed fingerprint covers.

    ProcessRouter(..., spec="improved_body_parts_tpu.serve.artifacts:"
                       "checkpoint_predictor",
                  spec_kwargs={"config": "canonical",
                               "checkpoint": ".../epoch_99",
                               "params_dtype": "int8"})

:func:`cascade_predictors` is the two-tier wiring: int8 (or bf16)
student + full-precision teacher, ready for ``CascadeEngine.build`` —
the cheap tier answers, quantization error is one more escalation
reason the policy's free decode signals already catch.
"""
from __future__ import annotations

from typing import Optional, Tuple


def checkpoint_predictor(config: str = "canonical",
                         checkpoint: Optional[str] = None,
                         params_dtype: str = "fp32",
                         boxsize: int = 0, bucket: int = 128,
                         compact_topk: int = 64,
                         assembly_pmax: int = 32,
                         fused_tta: bool = True,
                         seed: int = 0):
    """Build a serving ``Predictor`` from a config name + optional
    checkpoint, with the storage dtype applied through the one audited
    construction site (``apply_serve_dtype``).

    ``checkpoint=None`` initializes fresh weights from ``seed`` —
    shape/ABI checks and process-isolation tests without an artifact
    on disk.  ``params_dtype``: fp32 / bf16 / auto / int8 (weight-only
    per-output-channel quantization, dequant traced into the serve
    programs).
    """
    import jax
    import jax.numpy as jnp

    from ..config import InferenceModelParams, get_config
    from ..infer import Predictor
    from ..models import build_model
    from ..utils.precision import apply_serve_dtype

    cfg = get_config(config)
    model = build_model(cfg)
    if checkpoint:
        from ..train import restore_checkpoint

        payload = restore_checkpoint(checkpoint)
        variables = {"params": payload["params"],
                     "batch_stats": payload["batch_stats"]}
    else:
        h = cfg.skeleton.height
        variables = model.init(jax.random.PRNGKey(seed),
                               jnp.zeros((1, h, h, 3), jnp.float32),
                               train=False)
    model, variables = apply_serve_dtype(params_dtype, model, variables)
    model_params = (InferenceModelParams(boxsize=boxsize) if boxsize
                    else None)
    return Predictor(model, variables, cfg.skeleton,
                     model_params=model_params, bucket=bucket,
                     compact_topk=compact_topk,
                     assembly_pmax=assembly_pmax, fused_tta=fused_tta)


def cascade_predictors(student_config: str = "tiny_student",
                       teacher_config: str = "canonical",
                       student_checkpoint: Optional[str] = None,
                       teacher_checkpoint: Optional[str] = None,
                       student_dtype: str = "int8",
                       teacher_dtype: str = "fp32",
                       **kwargs) -> Tuple[object, object]:
    """The cascade's (student, teacher) predictor pair: a cheap-storage
    student (int8 by default — FasterPose's cheap-representation knee)
    under a full-precision teacher.  Pass the pair straight to
    ``CascadeEngine.build``; extra kwargs go to both factories."""
    student = checkpoint_predictor(student_config, student_checkpoint,
                                   params_dtype=student_dtype, **kwargs)
    teacher = checkpoint_predictor(teacher_config, teacher_checkpoint,
                                   params_dtype=teacher_dtype, **kwargs)
    return student, teacher
