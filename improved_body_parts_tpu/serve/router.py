"""Process-isolated serving: worker processes behind the pool's router.

:class:`ProcessWorkerEngine` presents ONE worker process through the
exact duck-typed engine contract `serve.pool` / `serve.policy` /
`serve.cascade` / `stream` already consume (``submit(image,
deadline_s=) -> Future``, ``health()``, ``warmup()``, ``stop()``,
``start()``, ``draining``, ``metrics``) — so
:class:`~improved_body_parts_tpu.serve.pool.EnginePool`'s fence /
failover / breaker logic carries over UNCHANGED above the process
boundary.  :class:`ProcessRouter` is the deployment shape: N worker
processes, one ``EnginePool`` over their proxies, one merged metrics /
``/slo`` surface.

Transport is the PR 2 shared-memory wire (``serve.worker``): images in
and fixed-shape person tables out through preallocated slot rows under
per-slot seqlocks; only ``(kind, slot, seq)`` tokens cross a pair of
raw one-way ``multiprocessing.Pipe`` connections (NOT ``mp.Queue`` —
a Queue interposes a feeder thread on every hop, and on a busy host
each request pays two extra scheduler wake round-trips; a bare pipe
sends the token synchronously in the caller).

Worker lifecycle is the PR 6 supervisor discipline, per process:

- a SIGKILLed / crashed worker fails its in-flight futures with
  :class:`~improved_body_parts_tpu.data.shm_ring.WorkerDied` — the pool
  records the failure, fences the replica and RESUBMITS the work to a
  healthy one (zero lost futures across a kill -9);
- ``start()`` (the pool's restart path) respawns with exponential
  backoff on consecutive no-progress failures and a crash budget that
  stops a deterministic crash loop from spinning forever — a worker
  that exhausts it stays down (``health()["running"] = False``) and the
  pool keeps it fenced;
- respawn REPLACES the pipes and the shared-memory region (a process
  killed mid-write can leave a half-written token in the channel,
  poisoning it for every later reader — the ``data.shm_ring`` rebuild
  rule).
"""
import json
import os
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import multiprocessing as mp

import numpy as np

from ..data.shm_ring import WorkerDied, _quiet_close, _slot_views
from ..obs.events import get_sink
from ..obs.fleet import (
    FleetRegistry,
    build_postmortem,
    flow_id,
    read_block,
    read_flight_records,
)
from ..obs.reqtrace import NULL_NODE, get_reqtrace
from ..obs.trace import get_tracer
from .batcher import DeadlineExceeded, ServerOverloaded
from .metrics import HOPS, ServeMetrics
from .pool import EnginePool
from .worker import (
    STATUS_ERROR,
    STATUS_EXPIRED,
    STATUS_OK,
    decode_people,
    hb_view,
    rec_view,
    region_size,
    telem_view,
    wire_format,
    worker_main,
)


class _ProcReq:
    """One in-flight request pinned to a slot row."""

    __slots__ = ("future", "ctx", "rid", "deadline", "t_submit",
                 "finished", "seq")

    def __init__(self, deadline_s: Optional[float]):
        self.future: Future = Future()
        self.ctx = NULL_NODE
        self.rid = ""
        self.t_submit = time.perf_counter()
        self.deadline = (None if deadline_s is None
                         else self.t_submit + deadline_s)
        self.finished = False
        self.seq = 0


class ProcessWorkerEngine:
    """One worker process behind the engine contract.

    ``spec`` is the worker predictor factory (``"module:callable"``)
    and ``spec_kwargs`` its JSON-safe kwargs — the CHILD builds the
    predictor, so the parent never pickles model state.  ``slots``
    bounds admission exactly like the batcher's ``max_queue``
    (``ServerOverloaded`` past it); ``max_image_hw`` / ``num_parts`` /
    ``max_people`` fix the wire layout.
    """

    def __init__(self, spec: str, spec_kwargs: Optional[dict] = None, *,
                 slots: int = 8,
                 max_image_hw: Tuple[int, int] = (512, 512),
                 num_parts: int = 18, max_people: int = 64,
                 max_batch: int = 4,
                 worker_idx: int = 0,
                 sink_path: Optional[str] = None,
                 heartbeat_timeout_s: float = 30.0,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 2.0,
                 crash_budget: int = 5,
                 warmup_timeout_s: float = 300.0,
                 metrics: Optional[ServeMetrics] = None,
                 registry=None,
                 telemetry: bool = True,
                 trace_path: Optional[str] = None):
        if slots < 1:
            raise ValueError(f"slots={slots} must be >= 1")
        self.spec = spec
        # allow_nan=False (JGL004): a non-finite kwarg would cross the
        # process boundary as a bare NaN token the child can't parse
        self.spec_kwargs_json = json.dumps(spec_kwargs or {},
                                           allow_nan=False)
        self.slots = slots
        self.names, self.shapes, self.dtypes = wire_format(
            max_image_hw, num_parts, max_people)
        self.max_image_hw = tuple(max_image_hw)
        self.max_batch = max_batch
        self.worker_idx = worker_idx
        self.sink_path = sink_path
        self.telemetry = bool(telemetry)
        self.trace_path = trace_path
        self.heartbeat_timeout_s = heartbeat_timeout_s
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.crash_budget = crash_budget
        self.warmup_timeout_s = warmup_timeout_s
        self.metrics = metrics or ServeMetrics()
        if registry is not None:
            self.metrics.register_into(registry)
        self._ctx = mp.get_context("spawn")
        self._lock = threading.Lock()
        self._finish_lock = threading.Lock()
        self._pending: Dict[int, _ProcReq] = {}
        self._free: List[int] = []
        self._slots_sem = threading.BoundedSemaphore(slots)
        self._running = False
        self._draining = False
        self._gen = 0
        self._seq = 0
        self._proc = None
        self._shm = None
        self._header = None
        self._views = None
        self._hb = None
        self._telem = None      # worker telemetry block (read-only)
        self._rec = None        # flight-recorder ring (read-only)
        #: the last exhumed ``worker_postmortem`` record (None until a
        #: worker death is detected with the ring attached)
        self.last_postmortem: Optional[dict] = None
        self._backing_off = False
        # scrape-path liveness cache: ``worker_info`` is read per
        # history/metrics tick and ``proc.is_alive()`` is a waitpid
        # syscall (~ms under load on a loaded host); the reporting view
        # tolerates sub-second staleness — death DETECTION stays with
        # the probe loop, which reads ``proc.is_alive()`` directly.
        # Keyed on the proc object so a respawn invalidates it.
        self._alive_cache = (None, 0.0, False)
        self._task_tx = None    # parent write end of the task pipe
        self._done_rx = None    # parent read end of the done pipe
        # multiple client threads write the task channel; pipe sends
        # are NOT atomic across writers, so serialize them
        self._send_lock = threading.Lock()
        self._fetcher: Optional[threading.Thread] = None
        self._stop_lock = threading.Lock()
        # supervisor discipline: consecutive starts without a single
        # completed request; any success resets it
        self.consecutive_failures = 0
        self.restarts = 0
        self.gave_up = False
        self._warmup_box: Dict[str, object] = {}
        self._warmup_evt = threading.Event()

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ProcessWorkerEngine":
        """(Re)spawn the worker: fresh shared-memory region, fresh
        pipes, fresh fetcher — the pool's ``restart()`` lands here.
        Applies the backoff/crash-budget discipline on consecutive
        no-progress respawns; past the budget the engine stays down
        (the pool keeps it fenced) instead of crash-looping."""
        with self._lock:
            if self._running:
                return self
            if self.consecutive_failures >= self.crash_budget:
                if not self.gave_up:
                    self.gave_up = True
                    get_sink().emit("worker_gave_up",
                                    worker=self.worker_idx,
                                    failures=self.consecutive_failures)
                return self
            self._gen += 1
            gen = self._gen
        if self.consecutive_failures > 0:
            self._backing_off = True
            try:
                time.sleep(min(self.backoff_base_s
                               * 2 ** (self.consecutive_failures - 1),
                               self.backoff_max_s))
            finally:
                self._backing_off = False
        self._teardown_transport()
        from multiprocessing import shared_memory

        shm = shared_memory.SharedMemory(
            create=True,
            size=region_size(self.slots, self.shapes, self.dtypes))
        shm.buf[:] = b"\x00" * len(shm.buf)
        # raw one-way pipes: worker reads tasks from task_r, parent
        # reads done-tokens from done_r; no feeder threads anywhere
        task_r, task_w = self._ctx.Pipe(duplex=False)
        done_r, done_w = self._ctx.Pipe(duplex=False)
        # the parent run's identity rides into the worker shard header
        # so the report tools can match shards to THIS run (and skip a
        # stray shard from another one loudly)
        run_id = (getattr(get_sink(), "run_meta", None)
                  or {}).get("run_id")
        proc = self._ctx.Process(
            target=worker_main,
            args=(self.worker_idx, shm.name, self.slots, self.shapes,
                  self.dtypes, self.spec, self.spec_kwargs_json,
                  task_r, done_w, os.getpid(), self.sink_path,
                  self.max_batch, self.telemetry, self.trace_path,
                  run_id),
            name=f"serve-worker-{self.worker_idx}", daemon=True)
        proc.start()
        # drop the parent's copies of the child-side ends so a dead
        # worker surfaces as EOF on done_r instead of a silent stall
        task_r.close()
        done_w.close()
        header, views = _slot_views(shm.buf, self.slots, self.shapes,
                                    self.dtypes, writeable=True)
        with self._lock:
            self._shm, self._header, self._views = shm, header, views
            self._hb = hb_view(shm.buf, self.slots, self.shapes,
                               self.dtypes, writeable=False)
            self._telem = telem_view(shm.buf, self.slots, self.shapes,
                                     self.dtypes, writeable=False)
            self._rec = rec_view(shm.buf, self.slots, self.shapes,
                                 self.dtypes, writeable=False)
            self._task_tx, self._done_rx = task_w, done_r
            self._proc = proc
            self._free = list(range(self.slots))
            self._pending = {}
            self._slots_sem = threading.BoundedSemaphore(self.slots)
            self._running = True
            self._draining = False
            self.restarts += 1
        fetcher = threading.Thread(target=self._fetch_loop,
                                   args=(gen, proc, done_r),
                                   name=f"proc-fetch-{self.worker_idx}",
                                   daemon=True)
        fetcher.start()
        self._fetcher = fetcher
        get_sink().emit("worker_spawned", worker=self.worker_idx,
                        pid=proc.pid, respawn=self.restarts - 1)
        return self

    def _teardown_transport(self) -> None:
        """Drop the previous generation's transport.  Pipes are
        REPLACED, never reused: a worker killed mid-write can leave a
        torn token that corrupts the stream for every later recv."""
        with self._lock:
            proc, self._proc = self._proc, None
            shm, self._shm = self._shm, None
            task_tx, self._task_tx = self._task_tx, None
            done_rx, self._done_rx = self._done_rx, None
            self._header = self._views = self._hb = None
            self._telem = self._rec = None
        if proc is not None and proc.is_alive():
            proc.terminate()
            proc.join(5.0)
        for conn in (task_tx, done_rx):
            if conn is not None:
                try:
                    conn.close()
                except Exception:  # noqa: BLE001 — already torn by a
                    pass           # SIGKILL; close is best-effort
        if shm is not None:
            try:
                shm.unlink()
            except FileNotFoundError:
                pass
            _quiet_close(shm)

    def stop(self, drain_timeout_s: Optional[float] = None) -> None:
        """Bounded graceful stop: admission closes, in-flight slots get
        their bounded drain, stragglers fail explicitly, the worker
        gets the poison pill then SIGTERM.  Idempotent; concurrent
        callers serialize (the batcher's stop discipline)."""
        with self._stop_lock:
            self._stop_locked(drain_timeout_s)

    def _stop_locked(self, drain_timeout_s: Optional[float]) -> None:
        with self._lock:
            if not self._running and self._proc is None:
                return
            self._running = False
            self._draining = True
            proc, task_tx = self._proc, self._task_tx
        deadline = (None if drain_timeout_s is None
                    else time.perf_counter() + drain_timeout_s)
        while self._pending_count():
            if deadline is not None and time.perf_counter() >= deadline:
                break
            if proc is not None and not proc.is_alive():
                break
            time.sleep(0.005)
        for req in self._take_pending():
            self._finish(req, error=RuntimeError(
                "process worker stopped before completion"))
        if task_tx is not None:
            try:
                with self._send_lock:
                    task_tx.send(None)  # poison pill: clean worker exit
            except Exception:  # noqa: BLE001 — pipe torn by a crash
                pass
        if proc is not None:
            proc.join(2.0 if deadline is None
                      else max(0.1, deadline - time.perf_counter()))
        self._teardown_transport()
        fetcher, self._fetcher = self._fetcher, None
        if fetcher is not None and fetcher is not threading.current_thread():
            fetcher.join(5.0)
        with self._lock:
            self._draining = False

    def __enter__(self) -> "ProcessWorkerEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- submit
    def submit(self, image, *,
               deadline_s: Optional[float] = None) -> Future:
        """Write one request into a free slot row and hand the worker
        its token; returns a future resolving to the decoded people (or
        ``(people, signals)`` — the signal vector rides every response,
        so the cascade's escalation input costs nothing extra).

        Same refusal contract as ``DynamicBatcher.submit``:
        :class:`ServerOverloaded` when all slots are in flight or the
        engine drains, :class:`DeadlineExceeded` for a dead-on-arrival
        deadline, ``RuntimeError`` when not running."""
        if self._draining:
            self.metrics.on_reject()
            raise ServerOverloaded(
                "process worker is draining (shutdown in progress); "
                "retry against a live instance")
        if not self._running:
            raise RuntimeError("ProcessWorkerEngine is not running "
                               "(use `with engine:` or call start())")
        if deadline_s is not None and deadline_s <= 0:
            self.metrics.on_expire_rejected()
            raise DeadlineExceeded(
                f"deadline_s={deadline_s} already expired at submit")
        image = np.ascontiguousarray(image, dtype=np.uint8)
        h, w = image.shape[:2]
        mh, mw = self.max_image_hw
        if image.ndim != 3 or image.shape[2] != 3 or h > mh or w > mw:
            raise ValueError(
                f"image shape {image.shape} exceeds the worker wire "
                f"bucket {(mh, mw, 3)} (set max_image_hw)")
        if not self._slots_sem.acquire(blocking=False):
            self.metrics.on_reject()
            raise ServerOverloaded(
                f"{self.slots} requests in flight (slots); retry "
                "with backoff")
        req = _ProcReq(deadline_s)
        rt = get_reqtrace()
        if rt.enabled:
            # root for a bare client; child of the routing layer's node
            # (pool route / policy attempt / cascade lane) when this
            # submit runs inside its child_scope
            req.ctx = rt.begin("proc", worker=self.worker_idx)
        with self._lock:
            if not self._running or not self._free:
                # raced a stop/crash between the flag check and here
                self._slots_sem.release()
                req.ctx.finish("error:ServerOverloaded")
                self.metrics.on_reject()
                raise ServerOverloaded("process worker stopped")
            idx = self._free.pop()
            self._seq += 2
            req.seq = self._seq
            self._pending[idx] = req
            header, views, task_tx = (self._header, self._views,
                                      self._task_tx)
        tracer = get_tracer()
        tr0 = tracer.now() if tracer.enabled else 0.0
        img_v, meta_in = views[idx][0], views[idx][1]
        header[idx, 0] = req.seq - 1        # odd: router writing
        img_v[:h, :w] = image
        meta_in[0], meta_in[1] = float(h), float(w)
        meta_in[2] = 0.0 if req.deadline is None else req.deadline
        meta_in[3] = req.t_submit
        header[idx, 0] = req.seq            # even: consistent
        self.metrics.on_submit()
        try:
            with self._send_lock:
                task_tx.send(("req", idx, req.seq))
        except Exception as e:  # noqa: BLE001 — pipe torn by a crash
            self._finish(req, error=WorkerDied(
                f"serve worker {self.worker_idx} pipe unusable: {e}"),
                idx=idx)
        if tracer.enabled:
            # the router half of the cross-process flow arc: one
            # proc_submit slice (slot write + token send) starting the
            # (cat="proc", flow_id) arc the worker's serve slice steps
            # and the deliver slice finishes
            tr1 = tracer.now()
            rtrack = f"router-w{self.worker_idx}"
            tracer.add_span_rel("proc_submit", tr0,
                                max(tr1 - tr0, 1e-7), track=rtrack,
                                args={"slot": idx, "seq": req.seq,
                                      "worker": self.worker_idx})
            tracer.flow_start("req",
                              flow_id(self.worker_idx, idx, req.seq),
                              track=rtrack, cat="proc",
                              ts=(tr0 + tr1) / 2.0)
        return req.future

    # ------------------------------------------------------------- warmup
    def warmup(self, image_sizes: Sequence[Tuple[int, int]],
               batch_sizes: Optional[Sequence[int]] = None) -> dict:
        """Ask the worker to precompile its bucket programs (and arm
        its own in-process CompileWatch); blocks for the ack."""
        with self._lock:
            if not self._running:
                raise RuntimeError("ProcessWorkerEngine is not running")
            task_tx = self._task_tx
        self._warmup_evt.clear()
        self._warmup_box.clear()
        with self._send_lock:
            task_tx.send(("warmup", [tuple(s) for s in image_sizes],
                          None if batch_sizes is None
                          else list(batch_sizes)))
        if not self._warmup_evt.wait(self.warmup_timeout_s):
            raise RuntimeError(
                f"serve worker {self.worker_idx} warmup did not ack "
                f"within {self.warmup_timeout_s}s")
        if "error" in self._warmup_box:
            raise RuntimeError("serve worker warmup failed:\n"
                               + str(self._warmup_box["error"]))
        return dict(self._warmup_box.get("info", {}))

    # ------------------------------------------------------------- health
    @property
    def draining(self) -> bool:
        return self._draining

    def health(self) -> dict:
        """The pool-probe health contract.  ``dispatcher_alive`` maps
        to the worker PROCESS (additionally gated on heartbeat
        freshness: a live-but-wedged worker reads as dead once its
        heartbeat goes stale), ``fetchers_alive`` to the response
        fetcher thread."""
        with self._lock:
            proc, hb = self._proc, self._hb
            fetcher = self._fetcher
            running, draining = self._running, self._draining
            depth = len(self._pending)
        alive = proc is not None and proc.is_alive()
        if alive and hb is not None and self.heartbeat_timeout_s:
            last = float(hb[0])
            if last > 0.0 and (time.perf_counter() - last
                               > self.heartbeat_timeout_s):
                alive = False
        return {"running": running, "draining": draining,
                "dispatcher_alive": alive,
                "fetchers_alive": int(fetcher is not None
                                      and fetcher.is_alive()),
                "fetchers_expected": 1,
                "queue_depth": self.metrics.depth,
                "batches_in_flight": depth,
                "stall_age_s": self.metrics.stall_age_s()}

    def worker_stats(self) -> dict:
        """Heartbeat-block readout: pid, served count and the worker's
        OWN post-warmup recompile count (compiles happen in the child;
        the parent's CompileWatch cannot see them)."""
        with self._lock:
            hb, proc = self._hb, self._proc
        if hb is None:
            return {"pid": None, "served": 0,
                    "recompiles_post_warmup": 0, "restarts": self.restarts}
        return {"pid": proc.pid if proc is not None else None,
                "served": int(hb[1]),
                "recompiles_post_warmup": int(hb[2]),
                "restarts": self.restarts}

    # ------------------------------------------------------ fleet readout
    def telem_read(self):
        """Seqlock-consistent copy of the worker's telemetry block (or
        ``None``: no transport / torn) — ``obs.fleet.decode_telem``'s
        input, the ``FleetRegistry`` merge source."""
        with self._lock:
            telem = self._telem
        if telem is None:
            return None
        return read_block(telem)

    def worker_info(self) -> dict:
        """The router-side half of the fleet merge: liveness, lifecycle
        counters, crash budget, in-flight ledger and the router-view
        submit/complete counts the conservation check compares against
        the worker's served counter."""
        with self._lock:
            proc, hb = self._proc, self._hb
            running = self._running
            in_flight = len(self._pending)
        c_proc, c_t, c_alive = self._alive_cache
        now = time.perf_counter()
        if proc is c_proc and now - c_t < 0.5:
            alive = c_alive
        else:
            alive = proc is not None and proc.is_alive()
            self._alive_cache = (proc, now, alive)
        hb_stamp = float(hb[0]) if hb is not None else 0.0
        hb_age = (max(0.0, time.perf_counter() - hb_stamp)
                  if hb_stamp > 0.0 else None)
        m = self.metrics
        return {
            "worker": self.worker_idx,
            "pid": proc.pid if proc is not None else None,
            "alive": alive,
            "running": running,
            "backing_off": self._backing_off,
            "gave_up": self.gave_up,
            "consecutive_failures": self.consecutive_failures,
            "crash_budget": self.crash_budget,
            "restarts": self.restarts,
            "in_flight": in_flight,
            "submitted": m.submitted,
            "completed": m.completed,
            "failed": m.failed,
            "hb_age_s": round(hb_age, 3) if hb_age is not None else None,
            "hb_served": int(hb[1]) if hb is not None else 0,
            "hb_recompiles": int(hb[2]) if hb is not None else 0,
        }

    def flight_records(self) -> dict:
        """Exhume the flight-recorder ring (tolerant of a torn write —
        see ``obs.fleet.read_flight_records``)."""
        with self._lock:
            rec = self._rec
        if rec is None:
            return {"records": [], "count": 0, "torn": False}
        return read_flight_records(rec)

    # ------------------------------------------------------------ fetcher
    def _fetch_loop(self, gen: int, proc, done_rx) -> None:
        """Drain the worker's done pipe; detect death.  Generation-
        bound: a fetcher from a previous spawn must never touch the
        rebuilt transport."""
        while True:
            with self._lock:
                if gen != self._gen:
                    return
                running = self._running
            if not running and not self._pending_count():
                return
            try:
                if not done_rx.poll(0.2):
                    if not proc.is_alive():
                        self._on_worker_death(gen)
                        return
                    continue
                token = done_rx.recv()
            except EOFError:
                # write end closed: the worker died (SIGKILL/crash)
                self._on_worker_death(gen)
                return
            except (OSError, ValueError):
                # pipe closed under us by a teardown
                return
            kind = token[0]
            if kind == "done":
                self._on_done(gen, token[2], token[3])
            elif kind == "warmup_done":
                self._warmup_box["info"] = token[2]
                self._warmup_evt.set()
            elif kind in ("warmup_err", "init_err"):
                self._warmup_box["error"] = token[2]
                self._warmup_evt.set()
                if kind == "init_err":
                    get_sink().emit("worker_init_error",
                                    worker=self.worker_idx,
                                    error=str(token[2])[-400:])
                    self._on_worker_death(gen)
                    return

    def _on_done(self, gen: int, idx: int, seq: int) -> None:
        with self._lock:
            if gen != self._gen:
                return
            req = self._pending.get(idx)
            if req is None or req.seq != seq:
                return              # stale token from a torn rebuild
            views, header = self._views, self._header
        if int(header[idx, 0]) != seq + 2:
            # torn response (worker died mid-write): leave the request
            # pending; death detection fails it into pool failover
            return
        _, _, kps, scores, sig, meta_out, err = views[idx]
        status = float(meta_out[0])
        if status == STATUS_OK:
            people, signals = decode_people(kps, scores, sig)
            result = (people, signals) if signals is not None else people
            stamps = (float(meta_out[2]), float(meta_out[3]),
                      float(meta_out[4]), float(meta_out[5]))
            self._finish(req, result=result, idx=idx, stamps=stamps)
        elif status == STATUS_EXPIRED:
            self._finish(req, error=DeadlineExceeded(
                "deadline expired before the worker served it"),
                idx=idx)
        else:
            msg = (bytes(err[err != 0].tobytes()).decode(
                       "utf-8", "replace")
                   if status == STATUS_ERROR
                   else f"unknown wire status {status}")
            self._finish(req, error=RuntimeError(
                f"serve worker {self.worker_idx} error:\n{msg}"),
                idx=idx)

    def _on_worker_death(self, gen: int) -> None:
        """The worker process died (SIGKILL, OOM, segfault): fail every
        in-flight future with ``WorkerDied`` — the pool's failover
        resubmits them — and leave ``running=False`` so the probe
        fences this replica until ``restart()`` respawns it."""
        with self._lock:
            if gen != self._gen:
                return
            if not self._running:
                return
            self._running = False
            self.consecutive_failures += 1
            exitcode = (self._proc.exitcode
                        if self._proc is not None else None)
            pid = self._proc.pid if self._proc is not None else None
            rec = self._rec
            in_flight = [(idx, req.seq)
                         for idx, req in self._pending.items()]
        # exhume the flight recorder BEFORE failing the futures: the
        # ring names the in-flight slot/seq and the last hop the dead
        # worker completed — a SIGKILL leaves no other trace.  The
        # region outlives the process (parent still maps it), and the
        # reader tolerates a permanently-odd parity from a kill
        # mid-write (torn=True, best-effort copy).
        try:
            flight = (read_flight_records(rec) if rec is not None
                      else {"records": [], "count": 0, "torn": False})
            pm = build_postmortem(self.worker_idx, pid, exitcode,
                                  flight, in_flight)
            self.last_postmortem = pm
            get_sink().emit("worker_postmortem", **pm)
        except Exception:  # noqa: BLE001 — forensics must never block
            pass           # the failover path
        get_sink().emit("worker_died", worker=self.worker_idx,
                        exitcode=exitcode,
                        in_flight=self._pending_count())
        err = WorkerDied(
            f"serve worker {self.worker_idx} died "
            f"(exitcode={exitcode}) with work in flight")
        for req in self._take_pending():
            self._finish(req, error=err)

    # ------------------------------------------------------------- finish
    def _pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def _take_pending(self) -> List[_ProcReq]:
        with self._lock:
            reqs = list(self._pending.values())
            self._pending.clear()
            self._free = list(range(self.slots))
        return reqs

    def _finish(self, req: _ProcReq, result=None, error=None,
                idx: Optional[int] = None,
                stamps: Optional[tuple] = None) -> None:
        """Resolve one request exactly once: metrics, future, slot.
        The batcher's once-flag discipline — a drain failing a request
        that a late done-token then completes must no-op."""
        with self._finish_lock:
            if req.finished:
                return
            req.finished = True
        if idx is not None:
            with self._lock:
                if self._pending.get(idx) is req:
                    del self._pending[idx]
                    self._free.append(idx)
        t_fin = time.perf_counter()
        if error is None and stamps is not None:
            # consecutive boundary stamps partition submit→finish into
            # the five serve hops exactly (the conservation contract);
            # worker stamps share CLOCK_MONOTONIC with ours, clamp any
            # residual skew to keep the waterfall non-negative
            t_pickup, t_exec0, t_exec1, t_decode = stamps
            bounds = [req.t_submit, t_pickup, t_exec0, t_exec1,
                      t_decode, t_fin]
            for i in range(1, len(bounds)):
                bounds[i] = max(bounds[i], bounds[i - 1])
            durs = tuple(bounds[i + 1] - bounds[i]
                         for i in range(len(HOPS)))
            if req.ctx.sampled:
                req.ctx.finish("ok", hops=list(zip(HOPS, durs)),
                               replica=self.worker_idx)
            self.metrics.on_hops(self.worker_idx, durs)
            self.metrics.on_decode(fused=True)
            tracer = get_tracer()
            if tracer.enabled and idx is not None:
                # the deliver slice finishes the cross-process flow arc
                # the submit started and the worker's serve slice
                # stepped; worker stamps share CLOCK_MONOTONIC with the
                # tracer's t0 so add_span_abs lands on the same axis
                rtrack = f"router-w{self.worker_idx}"
                tracer.add_span_abs("proc_deliver", bounds[4],
                                    max(t_fin - bounds[4], 1e-7),
                                    track=rtrack,
                                    args={"slot": idx, "seq": req.seq})
                tracer.flow_finish(
                    "req", flow_id(self.worker_idx, idx, req.seq),
                    track=rtrack, cat="proc",
                    ts=(bounds[4] - tracer.t0)
                    + (t_fin - bounds[4]) / 2.0)
        elif req.ctx.sampled:
            req.ctx.finish(
                "ok" if error is None
                else f"error:{type(error).__name__}",
                replica=self.worker_idx)
        try:
            if error is not None:
                self.metrics.on_fail(
                    expired=isinstance(error, DeadlineExceeded))
                req.future.set_exception(error)
            else:
                self.metrics.on_complete(t_fin - req.t_submit)
                self.consecutive_failures = 0
                self.gave_up = False
                req.future.set_result(result)
        except Exception:  # noqa: BLE001 — future cancelled by caller;
            pass           # the outcome is still accounted
        finally:
            try:
                self._slots_sem.release()
            except ValueError:
                pass        # slot pool was rebuilt under a respawn


class ProcessRouter:
    """N process workers behind ONE ``EnginePool``: the deployment
    shape for true multi-core serving.  Every pool capability —
    least-loaded routing, circuit breaking, fencing, transparent
    failover, auto-restart — applies to worker PROCESSES because each
    worker hides behind the unchanged engine contract.

    The router itself re-exports the engine contract too, so
    ``PolicyClient``, ``CascadeEngine`` lanes and ``StreamSession``
    sit on a ``ProcessRouter`` exactly as they would on a single
    batcher or a thread pool.
    """

    def __init__(self, spec: str, num_workers: int = 2,
                 spec_kwargs: Optional[dict] = None, *,
                 sink_path: Optional[str] = None,
                 restart_after_s: Optional[float] = 1.0,
                 wedge_timeout_s: float = 30.0,
                 drain_timeout_s: float = 10.0,
                 probe_interval_s: float = 0.2,
                 breaker_kw: Optional[dict] = None,
                 registry=None, slo=None,
                 qos_class: str = "interactive",
                 pool_kw: Optional[dict] = None,
                 telemetry: bool = True,
                 trace_path: Optional[str] = None,
                 staleness_s: float = 5.0,
                 **engine_kw):
        if num_workers < 1:
            raise ValueError(f"num_workers={num_workers} must be >= 1")
        if sink_path is None:
            sink_path = getattr(get_sink(), "path", None)
        self.workers = [
            ProcessWorkerEngine(
                spec, spec_kwargs, worker_idx=i,
                sink_path=sink_path, telemetry=telemetry,
                # per-worker trace shards next to the parent export —
                # the ".pN" suffix convention tools/trace_report.py and
                # tools/telemetry_report.py auto-discover
                trace_path=(f"{trace_path}.p{i + 1}"
                            if trace_path else None),
                **engine_kw)
            for i in range(num_workers)]
        #: the parent-side merge point: worker telemetry blocks +
        #: router-side lifecycle state under ``worker=`` labels, the
        #: ``/fleet`` document and the cross-process conservation check
        self.fleet = FleetRegistry(staleness_s=staleness_s)
        for w in self.workers:
            self.fleet.add_engine(w)
        kw = dict(pool_kw or {})
        kw.setdefault("restart_after_s", restart_after_s)
        kw.setdefault("wedge_timeout_s", wedge_timeout_s)
        kw.setdefault("drain_timeout_s", drain_timeout_s)
        kw.setdefault("probe_interval_s", probe_interval_s)
        kw.setdefault("breaker_kw", breaker_kw)
        self.pool = EnginePool(self.workers, registry=registry,
                               slo=slo, qos_class=qos_class, **kw)

    # ---------------------------------------------------- engine contract
    @property
    def metrics(self) -> ServeMetrics:
        return self.pool.metrics

    @property
    def draining(self) -> bool:
        return self.pool.draining

    def start(self) -> "ProcessRouter":
        self.pool.start()
        return self

    def stop(self, drain_timeout_s: Optional[float] = None) -> None:
        self.pool.stop(drain_timeout_s=drain_timeout_s)

    def submit(self, image, *,
               deadline_s: Optional[float] = None) -> Future:
        return self.pool.submit(image, deadline_s=deadline_s)

    def warmup(self, image_sizes: Sequence[Tuple[int, int]],
               batch_sizes: Optional[Sequence[int]] = None) -> dict:
        return self.pool.warmup(image_sizes, batch_sizes=batch_sizes)

    def health(self) -> dict:
        """Fleet health: the pool replica-state rollup plus per-worker
        process liveness — one surface for ``/metrics`` and ``/slo``."""
        states = self.pool.replica_states()
        return {"running": self.pool._running,
                "draining": self.pool.draining,
                "workers": [
                    {**s, **w.health(), **w.worker_stats()}
                    for s, w in zip(states, self.workers)]}

    def __enter__(self) -> "ProcessRouter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ----------------------------------------------------------- rollups
    def counters(self) -> dict:
        out = dict(self.pool.counters())
        out["worker_respawns"] = sum(max(0, w.restarts - 1)
                                     for w in self.workers)
        out["workers_gave_up"] = sum(int(w.gave_up)
                                     for w in self.workers)
        return out

    def worker_stats(self) -> List[dict]:
        return [w.worker_stats() for w in self.workers]

    def fleet_state(self) -> dict:
        """The ``/fleet`` route body (wire as ``MetricsServer``'s
        ``fleet=`` callable): per-worker liveness / respawn / crash-
        budget state, decoded telemetry with staleness age, and the
        cross-process conservation block."""
        return self.fleet.fleet_state()

    def health_extra(self) -> dict:
        """The ``/healthz`` fleet block (wire via
        ``HealthSentinel.set_extra("fleet", router.health_extra)``):
        carries its own non-ok ``status`` once any worker exhausts its
        crash budget, which escalates the probe to 503."""
        return self.fleet.health_extra()

    def last_postmortems(self) -> List[Optional[dict]]:
        """Per-worker last exhumed ``worker_postmortem`` (None where no
        death was detected since start)."""
        return [w.last_postmortem for w in self.workers]

    def register_into(self, registry) -> "ProcessRouter":
        """One exposition path for the whole fleet: pool + per-replica
        engine metrics through the pool's weakref collector, plus the
        router's process-level rollups."""
        import weakref

        self.pool.register_into(registry)
        self.fleet.attach(registry)
        ref = weakref.ref(self)

        def _collect():
            rt = ref()
            if rt is None:
                return []
            samples = []
            samples.append(("router_worker_respawns_total", {},
                            "counter",
                            float(rt.counters()["worker_respawns"])))
            # gauges, not counters: gave_up can reset on recovery and
            # the recompile count restarts with a respawned worker —
            # and counter naming (JGL006 / the metric-name lint) would
            # demand a _total suffix these families don't carry
            samples.append(("router_workers_gave_up", {}, "gauge",
                            float(rt.counters()["workers_gave_up"])))
            for i, w in enumerate(rt.workers):
                st = w.worker_stats()
                samples.append(("router_worker_served_total",
                                {"worker": str(i)}, "counter",
                                float(st["served"])))
                samples.append(("router_worker_recompiles_post_warmup",
                                {"worker": str(i)}, "gauge",
                                float(st["recompiles_post_warmup"])))
            return samples

        registry.register_collector(_collect)
        return self

    def snapshot(self) -> dict:
        snap = self.pool.snapshot()
        snap["workers"] = self.worker_stats()
        snap["counters"] = self.counters()
        return snap
