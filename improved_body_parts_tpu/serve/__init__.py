"""Dynamic-batching inference serving (ROADMAP: the path from concurrent
user requests to the chip).

- ``batcher``  — :class:`DynamicBatcher`: shape-bucketed coalescing,
  ``max_batch``/``max_wait_ms`` flush, bounded admission with explicit
  load-shedding (:class:`ServerOverloaded`), per-request futures.
- ``metrics``  — :class:`ServeMetrics`: queue depth, batch occupancy
  histogram, p50/p95/p99 latency, imgs/sec.
- ``warmup``   — startup precompile of every (bucket shape × pow2 batch
  size) program through the persistent compilation cache.

Load generator / benchmark: ``tools/serve_bench.py`` → SERVE_BENCH.json.
"""
from .batcher import DynamicBatcher, ServerOverloaded
from .metrics import ServeMetrics
from .warmup import pow2_batch_sizes, precompile

__all__ = ["DynamicBatcher", "ServerOverloaded", "ServeMetrics",
           "pow2_batch_sizes", "precompile"]
