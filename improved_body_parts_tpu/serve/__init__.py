"""Dynamic-batching inference serving (ROADMAP: the path from concurrent
user requests to the chip).

- ``batcher``  — :class:`DynamicBatcher`: shape-bucketed coalescing,
  ``max_batch``/``max_wait_ms`` flush, bounded admission with explicit
  load-shedding (:class:`ServerOverloaded`), per-request futures and
  per-request deadlines (:class:`DeadlineExceeded`).
- ``metrics``  — :class:`ServeMetrics`: queue depth, batch occupancy
  histogram, p50/p95/p99 latency, imgs/sec, deadline/stall accounting.
- ``warmup``   — startup precompile of every (bucket shape × pow2 batch
  size) program through the persistent compilation cache.
- ``cascade``  — :class:`CascadeEngine`: two-tier serving — a distilled
  student lane answers first, the fused decode payload's free
  escalation signals (:class:`EscalationPolicy`) route hard frames to
  the teacher bucket as a second submit on the same machinery.
- ``pool``     — :class:`EnginePool`: N shared-nothing batcher replicas
  behind a health-checked router — least-loaded routing, circuit
  breaking, fencing and transparent failover of in-flight work.
- ``breaker``  — :class:`CircuitBreaker`: sliding-window failure-rate
  breaker with half-open probing.
- ``router`` / ``worker`` — :class:`ProcessRouter` +
  :class:`ProcessWorkerEngine`: the pool's replicas promoted to real
  worker PROCESSES over the PR 2 shared-memory wire (images in,
  fixed-shape person tables out, no pickling) — true multi-core QPS,
  SIGKILL-survivable, same engine contract end to end.
- ``policy``   — :class:`PolicyClient` + :func:`submit_with_retry`:
  client-side deadlines, jittered retry on ``ServerOverloaded``, hedged
  dispatch for tail latency.
- ``capacity`` — :class:`CapacityModel`: measured per-replica
  saturation (QPS vs latency knee, occupancy headroom) fitted from the
  telemetry history (``obs.history``) into
  ``replicas_needed(target_qps, objective)``.

Load generator / benchmark: ``tools/serve_bench.py`` → SERVE_BENCH.json.
Fault-injection harness: ``tools/chaos_serve.py`` → SERVE_CHAOS.json.
"""
from .artifacts import cascade_predictors, checkpoint_predictor
from .batcher import DeadlineExceeded, DynamicBatcher, ServerOverloaded
from .breaker import CircuitBreaker
from .capacity import CapacityModel
from .cascade import CascadeEngine, CascadeMetrics, EscalationPolicy
from .metrics import ServeMetrics
from .policy import PolicyClient, PolicyStats, jittered_backoff, submit_with_retry
from .pool import EnginePool
from .router import ProcessRouter, ProcessWorkerEngine
from .warmup import pow2_batch_sizes, precompile

__all__ = ["CapacityModel",
           "CascadeEngine", "CascadeMetrics", "CircuitBreaker",
           "DeadlineExceeded", "DynamicBatcher", "EnginePool",
           "EscalationPolicy", "PolicyClient", "PolicyStats",
           "ProcessRouter", "ProcessWorkerEngine",
           "ServeMetrics", "ServerOverloaded", "cascade_predictors",
           "checkpoint_predictor", "jittered_backoff",
           "pow2_batch_sizes", "precompile", "submit_with_retry"]
