"""Two-tier cascade serving: a cheap student lane answers traffic first
and escalates hard frames to the teacher bucket.

The "millions of users" economics lever (ROADMAP open item 2;
"FasterPose", arXiv:2107.03215): most frames are easy, yet a
single-model deployment pays the full stacked-IMHN forward for every
one.  Here a narrow distilled student (``train.distill``,
``canonical_student``) serves ALL traffic, and the fused decode
program's payload — person count, capacity-overflow flags, assembly
scores, all in the same single fetch since PR 9 — decides, for free,
which frames were too hard for the fast tier:

- **student lane**: a :class:`~.batcher.DynamicBatcher` over the student
  predictor with ``device_decode=True, emit_signals=True`` — every
  future resolves to ``(skeletons, EscalationSignals)``;
- **escalation**: when the signals trip the :class:`EscalationPolicy`
  (person count above the threshold, any overflow flag, or the weakest
  person's mean assembly score under the floor), the frame is a SECOND
  submit on the teacher engine — the existing machinery end to end, no
  new dispatch path;
- **degradation**: a teacher that sheds (``ServerOverloaded``) or fails
  delivers the student's answer instead of failing the request — the
  fast tier's result exists and a deliberate quality degrade beats an
  error (counted in ``degraded_student_answer``); only
  ``DeadlineExceeded`` propagates, because the caller already gave up;
- **warmup**: both tiers precompile through the ONE
  ``serve.warmup.precompile`` predictor-set path, so post-warmup
  traffic compiles nothing on either tier.

Per-tier traffic stays separable on a shared registry via the
``ServeMetrics(model="student"/"teacher")`` label dimension;
:class:`CascadeMetrics` adds the routing split
(``answered_student`` / ``escalated_teacher`` / per-reason escalation
counters).
"""
from __future__ import annotations

import threading
import time
import weakref
from dataclasses import dataclass
from concurrent.futures import Future
from typing import Dict, Optional, Sequence, Tuple

from ..obs.reqtrace import NULL_NODE, get_reqtrace
from .batcher import DeadlineExceeded, DynamicBatcher, ServerOverloaded
from .metrics import ServeMetrics

#: escalation reasons, in CHECK ORDER: an overflow invalidates the
#: device assembly entirely (its person count / scores are partial), so
#: it outranks the crowding and score signals
ESCALATION_REASONS = ("overflow", "people", "score")


@dataclass(frozen=True)
class EscalationPolicy:
    """When does a frame leave the fast tier?

    Boundary semantics (pinned by tests): ``n_people == max_people``
    stays on the student — only MORE people escalate;
    ``min_mean_score == score_floor`` stays — only strictly weaker
    people escalate.  ``score_floor = 0`` disables the score signal,
    ``escalate_on_overflow = False`` the overflow one (an overflow then
    still host-fallback-decodes on the student, it just never
    escalates).
    """
    #: escalate when the device assembly found MORE than this many
    #: people (crowds are where the narrow student loses the most AP)
    max_people: int = 4
    #: escalate when the weakest kept person's mean per-part assembly
    #: score is UNDER this floor (0 disables) — low scores mean the
    #: student's heatmaps were ambiguous
    score_floor: float = 0.0
    #: any capacity-overflow flag escalates: the student's assembly was
    #: not authoritative for this frame at all
    escalate_on_overflow: bool = True

    def reason(self, sig) -> Optional[str]:
        """The escalation reason for one frame's signals, or ``None``
        to answer from the student."""
        if self.escalate_on_overflow and (sig.peak_overflow
                                          or sig.cand_overflow
                                          or sig.person_overflow):
            return "overflow"
        if sig.n_people > self.max_people:
            return "people"
        if self.score_floor > 0 and sig.min_mean_score < self.score_floor:
            return "score"
        return None


class CascadeMetrics:
    """Routing accounting for one :class:`CascadeEngine`.

    Conservation (the hammer test's invariant):
    ``submitted == answered_student + escalated_teacher
    + degraded_student_answer + failed + depth``.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self.submitted = 0
        self.answered_student = 0
        self.escalated_teacher = 0
        #: escalation attempted, but the teacher shed/failed and the
        #: student's answer was delivered instead
        self.degraded_student_answer = 0
        self.failed = 0
        self.depth = 0
        self.escalations: Dict[str, int] = {r: 0
                                            for r in ESCALATION_REASONS}

    def on_submit(self) -> None:
        with self._lock:
            self.submitted += 1
            self.depth += 1

    def on_escalate(self, reason: str) -> None:
        with self._lock:
            self.escalations[reason] = self.escalations.get(reason, 0) + 1

    def on_answer(self, lane: str) -> None:
        with self._lock:
            if lane == "student":
                self.answered_student += 1
            elif lane == "teacher":
                self.escalated_teacher += 1
            else:
                self.degraded_student_answer += 1
            self.depth -= 1

    def on_fail(self) -> None:
        with self._lock:
            self.failed += 1
            self.depth -= 1

    def escalation_rate(self) -> float:
        """Escalations attempted per completed request (0.0 before any
        completion)."""
        with self._lock:
            done = (self.answered_student + self.escalated_teacher
                    + self.degraded_student_answer)
            esc = self.escalated_teacher + self.degraded_student_answer
        return esc / done if done else 0.0

    def register_into(self, registry, prefix: str = "cascade"
                      ) -> "CascadeMetrics":
        """Scrape-time collector on a shared ``obs.Registry`` — same
        weakref discipline as ``ServeMetrics.register_into``."""
        ref = weakref.ref(self)

        def _collect():
            m = ref()
            return m.collect(prefix) if m is not None else []

        registry.register_collector(_collect)
        return self

    def collect(self, prefix: str = "cascade"):
        with self._lock:
            counts = (("submitted", self.submitted),
                      ("answered_student", self.answered_student),
                      ("escalated_teacher", self.escalated_teacher),
                      ("degraded_student_answer",
                       self.degraded_student_answer),
                      ("failed", self.failed))
            escalations = dict(self.escalations)
            depth = self.depth
        samples = [(f"{prefix}_{name}_total", {}, "counter", float(v))
                   for name, v in counts]
        for reason, n in sorted(escalations.items()):
            samples.append((f"{prefix}_escalations_total",
                            {"reason": reason}, "counter", float(n)))
        samples.append((f"{prefix}_depth", {}, "gauge", float(depth)))
        samples.append((f"{prefix}_escalation_rate", {}, "gauge",
                        self.escalation_rate()))
        return samples

    def conservation(self) -> dict:
        """The per-tier conservation block bench artifacts record
        (``tools/cascade_bench.py``, mirrored by the stream fast path's
        ``FastPathMetrics.conservation``): every counter of the
        invariant plus ``exact`` — True iff it holds at this instant."""
        with self._lock:
            out = {
                "submitted": self.submitted,
                "answered_student": self.answered_student,
                "escalated_teacher": self.escalated_teacher,
                "degraded_student_answer": self.degraded_student_answer,
                "failed": self.failed,
                "depth": self.depth,
            }
        out["exact"] = (out["submitted"]
                        == out["answered_student"]
                        + out["escalated_teacher"]
                        + out["degraded_student_answer"]
                        + out["failed"] + out["depth"])
        return out

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "submitted": self.submitted,
                "answered_student": self.answered_student,
                "escalated_teacher": self.escalated_teacher,
                "degraded_student_answer": self.degraded_student_answer,
                "failed": self.failed,
                "depth": self.depth,
                "escalations": dict(self.escalations),
            }
        out["escalation_rate"] = round(self.escalation_rate(), 4)
        return out


class _CascadeRequest:
    """Per-request routing state: the caller-facing future plus the
    absolute deadline the teacher leg inherits."""
    __slots__ = ("image", "future", "deadline", "ctx", "t0",
                 "student_node", "teacher_node", "t_s_submit",
                 "t_s_done", "t_t_submit", "t_t_done")

    def __init__(self, image, deadline_s: Optional[float]):
        self.image = image
        self.future: Future = Future()
        self.t0 = time.perf_counter()
        self.deadline = (None if deadline_s is None
                         else self.t0 + deadline_s)
        self.ctx = NULL_NODE          # reqtrace node (obs.reqtrace)
        self.student_node = None
        self.teacher_node = None
        # hop boundary stamps: route / student_lane / escalate /
        # deliver bookends around the tiers' own spans
        self.t_s_submit: Optional[float] = None
        self.t_s_done: Optional[float] = None
        self.t_t_submit: Optional[float] = None
        self.t_t_done: Optional[float] = None


class CascadeEngine:
    """Student-first serving with on-device escalation signals.

    ::

        with CascadeEngine.build(student_pred, teacher_pred,
                                 policy=EscalationPolicy(max_people=4)
                                 ) as cascade:
            cascade.warmup([(512, 512)])      # BOTH tiers precompile
            skeletons = cascade.submit(image).result()

    The student engine must run the fused device-decode lane with
    ``emit_signals=True`` (that payload IS the escalation input); the
    teacher may be any engine with the ``submit``/``start``/``stop``
    contract — a plain :class:`~.batcher.DynamicBatcher` or an
    ``EnginePool`` replica set.  Admission backpressure is the
    student's: a shed at the fast tier is the caller's retry signal
    (``ServerOverloaded``), exactly as for a single-engine deployment.
    """

    def __init__(self, student: DynamicBatcher, teacher,
                 policy: Optional[EscalationPolicy] = None,
                 metrics: Optional[CascadeMetrics] = None,
                 registry=None):
        if not getattr(student, "emit_signals", False):
            raise ValueError(
                "the cascade's student engine must be built with "
                "emit_signals=True (the escalation decision consumes "
                "the fused decode payload's signals)")
        if getattr(teacher, "emit_signals", False):
            raise ValueError(
                "the teacher engine must not emit_signals: its results "
                "are delivered to callers as-is")
        self.student = student
        self.teacher = teacher
        self.policy = policy or EscalationPolicy()
        self.metrics = metrics or CascadeMetrics()
        if registry is not None:
            self.metrics.register_into(registry)
        self._draining = False

    # ---------------------------------------------------------- builders
    @classmethod
    def build(cls, student_predictor, teacher_predictor, *,
              policy: Optional[EscalationPolicy] = None, registry=None,
              max_batch: int = 8, max_wait_ms: float = 25.0,
              max_queue: int = 64, decode_workers: int = 2,
              use_native: bool = True, eager_idle_flush: bool = True,
              student_devices: Optional[Sequence] = None,
              teacher_devices: Optional[Sequence] = None
              ) -> "CascadeEngine":
        """Construct both tiers with the standard wiring: fused
        device-decode lanes, per-tier ``ServeMetrics`` labeled
        ``{model="student"/"teacher"}`` on the shared registry, signal
        emission on the student only."""
        student = DynamicBatcher(
            student_predictor, max_batch=max_batch,
            max_wait_ms=max_wait_ms, max_queue=max_queue,
            decode_workers=decode_workers, use_native=use_native,
            eager_idle_flush=eager_idle_flush, devices=student_devices,
            metrics=ServeMetrics(model="student"), registry=registry,
            device_decode=True, emit_signals=True)
        teacher = DynamicBatcher(
            teacher_predictor, max_batch=max_batch,
            max_wait_ms=max_wait_ms, max_queue=max_queue,
            decode_workers=decode_workers, use_native=use_native,
            eager_idle_flush=eager_idle_flush, devices=teacher_devices,
            metrics=ServeMetrics(model="teacher"), registry=registry,
            device_decode=True)
        return cls(student, teacher, policy=policy, registry=registry)

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "CascadeEngine":
        self._draining = False
        self.student.start()
        self.teacher.start()
        return self

    def stop(self, drain_timeout_s: Optional[float] = None) -> None:
        """Drain both tiers: cascade admission closes, then the student
        drains FIRST (its completions may still escalate) and the
        teacher after it, both against one shared deadline."""
        self._draining = True
        deadline = (None if drain_timeout_s is None
                    else time.perf_counter() + drain_timeout_s)

        def remaining() -> Optional[float]:
            if deadline is None:
                return None
            return max(0.0, deadline - time.perf_counter())

        self.student.stop(remaining())
        self.teacher.stop(remaining())

    def __enter__(self) -> "CascadeEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    @property
    def draining(self) -> bool:
        return self._draining

    # ------------------------------------------------------------- warmup
    def warmup(self, image_sizes: Sequence[Tuple[int, int]],
               batch_sizes: Optional[Sequence[int]] = None) -> dict:
        """Precompile BOTH tiers' bucket programs (each through the
        shared ``serve.warmup.precompile`` predictor-set path) so no
        post-warmup request — answered or escalated — ever hits a
        compile stall.  ``newly_compiled == 0`` in both summaries means
        the cascade was already fully warm."""
        return {"student": self.student.warmup(image_sizes, batch_sizes),
                "teacher": self.teacher.warmup(image_sizes, batch_sizes)}

    def health(self) -> dict:
        return {"draining": self._draining,
                "student": self.student.health(),
                "teacher": self.teacher.health()}

    # ------------------------------------------------------------- submit
    def submit(self, image_bgr, *,
               deadline_s: Optional[float] = None) -> Future:
        """Enqueue one BGR image on the student lane; returns a future
        resolving to decoded skeletons from whichever tier answered.

        :raises ServerOverloaded: the student lane shed the request (or
            the cascade is draining) — retry with backoff, as for a
            single engine.
        :raises DeadlineExceeded: ``deadline_s`` already expired at
            submit.
        """
        if self._draining:
            raise ServerOverloaded(
                "cascade is draining (shutdown in progress); retry "
                "against a live instance")
        req = _CascadeRequest(image_bgr, deadline_s)
        rt = get_reqtrace()
        if rt.enabled:
            req.ctx = rt.begin("cascade")
        # student admission FIRST: a shed must not count as submitted
        try:
            with req.ctx.child_scope("submit") as scope:
                sfut = self.student.submit(image_bgr,
                                           deadline_s=deadline_s)
        except BaseException as e:  # noqa: BLE001 — re-raised: a shed
            # opened no request; close the cascade node it did open
            req.ctx.finish(f"error:{type(e).__name__}")
            raise
        req.student_node = scope.node
        req.t_s_submit = time.perf_counter()
        self.metrics.on_submit()
        sfut.add_done_callback(lambda f: self._student_done(f, req))
        return req.future

    # ------------------------------------------------------------ routing
    def _student_done(self, sfut: Future, req: _CascadeRequest) -> None:
        """Runs on the student engine's completion threads: route the
        answer or escalate."""
        req.t_s_done = time.perf_counter()
        try:
            skeletons, signals = sfut.result()
        except BaseException as e:  # noqa: BLE001 — delivered on the future
            self._finish(req, error=e, node=req.student_node)
            return
        reason = self.policy.reason(signals)
        if reason is None:
            self._finish(req, result=skeletons, lane="student",
                         node=req.student_node)
            return
        self.metrics.on_escalate(reason)
        remaining = (None if req.deadline is None
                     else req.deadline - time.perf_counter())
        try:
            # the ESCALATE edge, annotated with WHY the fast tier's
            # answer was not authoritative (people/overflow/score)
            with req.ctx.child_scope("escalate", reason) as scope:
                tfut = self.teacher.submit(req.image,
                                           deadline_s=remaining)
        except DeadlineExceeded as e:
            # the caller's global deadline passed — delivering anything
            # now is pointless, and a retry elsewhere equally so
            self._finish(req, error=e, node=req.student_node)
            return
        except Exception:  # noqa: BLE001 — teacher shed/stopped: degrade
            self._finish(req, result=skeletons, lane="degraded",
                         node=req.student_node)
            return
        req.teacher_node = scope.node
        req.t_t_submit = time.perf_counter()
        tfut.add_done_callback(
            lambda f: self._teacher_done(f, req, skeletons))

    def _teacher_done(self, tfut: Future, req: _CascadeRequest,
                      student_skeletons) -> None:
        req.t_t_done = time.perf_counter()
        try:
            result = tfut.result()
        except DeadlineExceeded as e:
            self._finish(req, error=e, node=req.teacher_node)
            return
        except BaseException:  # noqa: BLE001 — teacher died mid-flight:
            # the student's answer exists; a deliberate quality degrade
            # beats failing a request the fast tier already served
            self._finish(req, result=student_skeletons, lane="degraded",
                         node=req.student_node)
            return
        self._finish(req, result=result, lane="teacher",
                     node=req.teacher_node)

    def _finish(self, req: _CascadeRequest, result=None, error=None,
                lane: Optional[str] = None, node=None) -> None:
        if req.ctx.sampled:
            # cascade-node hops around the delivering tier's span.
            # Escalated requests carry the student_lane GAP hop — the
            # fast tier's full window is real request latency even
            # though the teacher subtree delivered — so the chain's sum
            # stays conservative (≥95% of e2e) on escalations too.
            t_fin = time.perf_counter()
            hops = []
            if req.t_s_submit is not None:
                hops.append(("route", req.t_s_submit - req.t0))
            if node is req.teacher_node and node is not None:
                if req.t_s_done is not None and \
                        req.t_s_submit is not None:
                    hops.append(("student_lane",
                                 req.t_s_done - req.t_s_submit))
                if req.t_t_submit is not None and \
                        req.t_s_done is not None:
                    hops.append(("escalate",
                                 req.t_t_submit - req.t_s_done))
                if req.t_t_done is not None:
                    hops.append(("deliver", t_fin - req.t_t_done))
            elif req.t_s_done is not None:
                hops.append(("deliver", t_fin - req.t_s_done))
            req.ctx.finish(
                "ok" if error is None
                else f"error:{type(error).__name__}",
                hops=hops, won_by=node,
                **({"lane": lane} if lane else {}))
        if error is not None:
            self.metrics.on_fail()
        else:
            self.metrics.on_answer(lane)
        try:
            if error is not None:
                req.future.set_exception(error)
            else:
                req.future.set_result(result)
        except Exception:  # noqa: BLE001 — future cancelled by caller;
            # the routing work still completed and is accounted
            pass
