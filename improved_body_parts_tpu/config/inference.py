"""Inference / post-processing parameters.

Replaces the reference's ConfigObj INI file with its hard-coded absolute path
(reference: utils/config, utils/config_reader.py:6-37) with a plain dataclass.
Field semantics and defaults match utils/config:14-41.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Tuple


@dataclass(frozen=True)
class InferenceParams:
    """Decode-time knobs (reference: utils/config [param] section)."""
    scale_search: Tuple[float, ...] = (1.0,)
    rotation_search: Tuple[float, ...] = (0.0,)
    thre1: float = 0.1           # keypoint peak threshold
    thre2: float = 0.1           # limb response threshold
    connect_ration: float = 0.8  # fraction of sampled points that must clear thre2
    mid_num: int = 20            # points sampled along a candidate limb
    min_num: int = 4
    len_rate: float = 16.0       # max allowed limb-length growth ratio
    connection_tole: float = 0.7  # tolerance when merging disjoint persons
    offset_radius: int = 2       # sub-pixel refinement window radius
    remove_recon: int = 0        # remove re-connected parts (0/1)
    # assembly pruning (reference: evaluate.py:491-496)
    min_parts: int = 2
    min_mean_score: float = 0.45
    # route the compact extraction's hot inner loops (peak top-K,
    # dense limb gather) through the ops/pallas_peaks.py kernels —
    # off by default: the XLA path is the validated production path,
    # and off-TPU the kernels run in interpreter mode (parity-exact
    # but not faster); tools/pallas_check.py owns the hardware A/B
    use_pallas_decode: bool = False


@dataclass(frozen=True)
class InferenceModelParams:
    """Input-geometry knobs (reference: utils/config [models] section)."""
    boxsize: int = 640
    stride: int = 4
    max_downsample: int = 64     # pad input to a multiple of this
    pad_value: int = 128
    # clamp for very large inputs (reference: evaluate.py:94-96)
    max_height: int = 2600
    max_width: int = 3800


def default_inference_params() -> Tuple[InferenceParams, InferenceModelParams]:
    """Replaces ``config_reader()`` (reference: utils/config_reader.py:6-37)."""
    return InferenceParams(), InferenceModelParams()
