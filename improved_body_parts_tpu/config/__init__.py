from .configs import (
    COCO_PARTS,
    Config,
    ModelConfig,
    SkeletonConfig,
    TrainConfig,
    TransformParams,
    available_configs,
    get_config,
)
from .inference import (
    InferenceModelParams,
    InferenceParams,
    default_inference_params,
)

__all__ = [
    "COCO_PARTS",
    "Config",
    "ModelConfig",
    "SkeletonConfig",
    "TrainConfig",
    "TransformParams",
    "available_configs",
    "get_config",
    "InferenceModelParams",
    "InferenceParams",
    "default_inference_params",
]
