"""Typed configuration system.

Replaces the reference's class-constant configs (reference: config/config.py:8-260,
config/config2.py, config/config_dense.py, config/config_final.py) with frozen
dataclasses and a named registry.  Derived tables (limb indices, flip permutation
orders, channel layout) are *computed* from the part/limb name tables instead of
being hand-maintained arrays; tests pin them against the reference's asserted
golden values (config/config.py:87-92,121-124).

Channel layout (critical invariant, reference config/config.py:96-103):
    [0, paf_layers)                     body-part (limb) heatmaps
    [paf_layers, paf_layers+heat)       keypoint heatmaps
    [bkg_start]                         person-mask background channel
    [bkg_start+1]                       reverse-keypoint background channel
"""
from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Tuple

import numpy as np

# COCO keypoint order (reference: config/config.py:146-148).
COCO_PARTS: Tuple[str, ...] = (
    "nose", "Leye", "Reye", "Lear", "Rear", "Lsho", "Rsho", "Lelb",
    "Relb", "Lwri", "Rwri", "Lhip", "Rhip", "Lkne", "Rkne", "Lank", "Rank",
)

# Internal (CMU-style) part order shared by canonical/3-stack/final variants
# (reference: config/config.py:61-62).
_PARTS_CANONICAL: Tuple[str, ...] = (
    "nose", "neck", "Rsho", "Relb", "Rwri", "Lsho", "Lelb", "Lwri", "Rhip",
    "Rkne", "Rank", "Lhip", "Lkne", "Lank", "Reye", "Leye", "Rear", "Lear",
)
# The dense variant swaps the eye/ear ordering (reference: config_dense.py parts).
_PARTS_DENSE: Tuple[str, ...] = (
    "nose", "neck", "Rsho", "Relb", "Rwri", "Lsho", "Lelb", "Lwri", "Rhip",
    "Rkne", "Rank", "Lhip", "Lkne", "Lank", "Reye", "Rear", "Leye", "Lear",
)

# Limb tables as (from, to) name pairs (reference: config/config.py:74-82).
_LIMBS_CANONICAL: Tuple[Tuple[str, str], ...] = tuple(zip(
    ["neck", "neck", "neck", "neck", "neck", "nose", "nose", "Reye", "Leye",
     "neck", "Rsho", "Relb", "neck", "Lsho", "Lelb",
     "neck", "Rhip", "Rkne", "neck", "Lhip", "Lkne",
     "nose", "nose", "Rsho", "Rhip", "Lsho", "Lhip", "Rear", "Lear", "Rhip"],
    ["nose", "Reye", "Leye", "Rear", "Lear", "Reye", "Leye", "Rear", "Lear",
     "Rsho", "Relb", "Rwri", "Lsho", "Lelb", "Lwri",
     "Rhip", "Rkne", "Rank", "Lhip", "Lkne", "Lank",
     "Rsho", "Lsho", "Rhip", "Lkne", "Lhip", "Rkne", "Rsho", "Lsho", "Lhip"],
))
# 3-stack 384 variant: 24 limbs (reference: config2.py limb tables).
_LIMBS_3STACK: Tuple[Tuple[str, str], ...] = tuple(zip(
    ["neck", "neck", "neck", "neck", "neck", "nose", "nose", "Reye", "Leye",
     "neck", "Rsho", "Relb", "neck", "Lsho", "Lelb",
     "neck", "Rhip", "Rkne", "neck", "Lhip", "Lkne", "Rhip", "Rsho", "Lsho"],
    ["nose", "Reye", "Leye", "Rear", "Lear", "Reye", "Leye", "Rear", "Lear",
     "Rsho", "Relb", "Rwri", "Lsho", "Lelb", "Lwri",
     "Rhip", "Rkne", "Rank", "Lhip", "Lkne", "Lank", "Lhip", "Rear", "Lear"],
))
# Densely connected skeleton: 49 limbs (reference: config_dense.py limb tables;
# header notes the redundant limbs *hurt* AP — kept for parity/ablation).
_LIMBS_DENSE_FROM = [1, 1, 1, 1, 1, 0, 14, 0, 16, 0, 0, 14, 1, 0, 15, 1, 0, 17,
                     2, 1, 5, 1, 3, 3, 2, 6, 5, 1, 2, 5, 1, 5, 2, 8, 4, 7, 8,
                     11, 2, 11, 8, 5, 9, 9, 8, 12, 12, 11, 9]
_LIMBS_DENSE_TO = [0, 14, 15, 16, 17, 14, 15, 16, 17, 15, 17, 16, 2, 2, 2, 5,
                   5, 5, 3, 3, 6, 6, 6, 4, 4, 7, 7, 8, 8, 8, 11, 11, 11, 11,
                   8, 11, 9, 9, 9, 12, 12, 12, 12, 10, 10, 10, 13, 13, 13]
_LIMBS_DENSE: Tuple[Tuple[str, str], ...] = tuple(
    (_PARTS_DENSE[f], _PARTS_DENSE[t])
    for f, t in zip(_LIMBS_DENSE_FROM, _LIMBS_DENSE_TO)
)

_LEFT_PARTS = ("Lsho", "Lelb", "Lwri", "Lhip", "Lkne", "Lank", "Leye", "Lear")
_RIGHT_PARTS = ("Rsho", "Relb", "Rwri", "Rhip", "Rkne", "Rank", "Reye", "Rear")


def _mirror_name(name: str) -> str:
    if name in _LEFT_PARTS:
        return "R" + name[1:]
    if name in _RIGHT_PARTS:
        return "L" + name[1:]
    return name


@dataclass(frozen=True)
class TransformParams:
    """Augmentation hyper-parameters (reference: config/config.py:26-49)."""
    target_dist: float = 0.6
    scale_prob: float = 0.8
    scale_min: float = 0.7
    scale_max: float = 1.3
    max_rotate_degree: float = 40.0
    center_perterb_max: float = 50.0
    flip_prob: float = 0.5
    tint_prob: float = 0.2
    sigma: float = 9.0
    keypoint_gaussian_thre: float = 0.015
    limb_gaussian_thre: float = 0.015
    paf_sigma: float = 7.0
    paf_thre_stride_mult: float = 1.0  # paf_thre = mult * stride (config.py:47)


@dataclass(frozen=True)
class SkeletonConfig:
    """Skeleton definition + channel layout.

    All derived index tables are computed in ``__post_init__`` from the name
    tables; the reference hardcodes them (config/config.py:84-124).
    """
    parts: Tuple[str, ...] = _PARTS_CANONICAL
    limbs: Tuple[Tuple[str, str], ...] = _LIMBS_CANONICAL
    width: int = 512
    height: int = 512
    stride: int = 4
    # curated subset of limbs rendered by the demo (reference:
    # config/config.py:126 ``draw_list``; canonical = [0, 5..20, 29])
    draw_limbs: Tuple[int, ...] = (0,) + tuple(range(5, 21)) + (29,)
    transform_params: TransformParams = field(default_factory=TransformParams)
    # Derived (filled in __post_init__):
    parts_dict: Dict[str, int] = field(default_factory=dict, compare=False)
    limbs_conn: Tuple[Tuple[int, int], ...] = field(default=(), compare=False)
    flip_heat_ord: Tuple[int, ...] = field(default=(), compare=False)
    flip_paf_ord: Tuple[int, ...] = field(default=(), compare=False)
    left_parts: Tuple[int, ...] = field(default=(), compare=False)
    right_parts: Tuple[int, ...] = field(default=(), compare=False)

    def __post_init__(self):
        pd = {p: i for i, p in enumerate(self.parts)}
        limbs_conn = tuple((pd[f], pd[t]) for f, t in self.limbs)
        # Keypoint flip permutation: part -> mirrored part, plus the 2
        # background channels which map to themselves
        # (golden: config/config.py:121).
        mirror = [pd[_mirror_name(p)] for p in self.parts]
        flip_heat = tuple(mirror) + (self.num_parts, self.num_parts + 1)
        # Limb flip permutation: limb -> index of the mirrored limb
        # (golden: config/config.py:122-124).
        mirrored_limbs = [(_mirror_name(f), _mirror_name(t)) for f, t in self.limbs]
        limb_index = {pair: i for i, pair in enumerate(self.limbs)}
        # A limb's scalar map is symmetric in direction, so a mirrored limb may
        # appear reversed in the table (e.g. Rhip->Lhip mirrors to itself).
        flip_paf = []
        for orig, m in zip(self.limbs, mirrored_limbs):
            if m in limb_index:
                flip_paf.append(limb_index[m])
            elif (m[1], m[0]) in limb_index:
                flip_paf.append(limb_index[(m[1], m[0])])
            else:
                raise ValueError(
                    f"limb table is not closed under L/R mirroring: limb "
                    f"{orig} mirrors to {m}, which is absent (flip ensembling "
                    f"needs every limb's mirror in the table)")
        flip_paf = tuple(flip_paf)
        object.__setattr__(self, "parts_dict", pd)
        object.__setattr__(self, "limbs_conn", limbs_conn)
        object.__setattr__(self, "flip_heat_ord", flip_heat)
        object.__setattr__(self, "flip_paf_ord", flip_paf)
        object.__setattr__(self, "left_parts", tuple(pd[p] for p in _LEFT_PARTS))
        object.__setattr__(self, "right_parts", tuple(pd[p] for p in _RIGHT_PARTS))

    # --- channel layout (reference: config/config.py:96-110) ---
    @property
    def num_parts(self) -> int:
        return len(self.parts)

    @property
    def paf_layers(self) -> int:
        return len(self.limbs)

    @property
    def heat_layers(self) -> int:
        return self.num_parts

    @property
    def num_layers(self) -> int:
        return self.paf_layers + self.heat_layers + 2

    @property
    def paf_start(self) -> int:
        return 0

    @property
    def heat_start(self) -> int:
        return self.paf_layers

    @property
    def bkg_start(self) -> int:
        return self.paf_layers + self.heat_layers

    @property
    def grid_shape(self) -> Tuple[int, int]:
        """(H, W) of the stride-4 output grid."""
        return (self.height // self.stride, self.width // self.stride)

    @property
    def parts_shape(self) -> Tuple[int, int, int]:
        h, w = self.grid_shape
        return (h, w, self.num_layers)

    @property
    def paf_thre(self) -> float:
        return self.transform_params.paf_thre_stride_mult * self.stride

    # COCO detection id -> COCO gt id mapping used when writing results
    # (reference: config/config.py:117-118). Computed from name tables.
    @property
    def dt_gt_mapping(self) -> Dict[int, int]:
        coco_index = {p: i for i, p in enumerate(COCO_PARTS)}
        return {i: coco_index.get(p) for i, p in enumerate(self.parts)}


@dataclass(frozen=True)
class ModelConfig:
    """IMHN architecture knobs (reference: config/config.py:14-16)."""
    nstack: int = 4
    inp_dim: int = 256
    increase: int = 128
    hourglass_depth: int = 4
    variant: str = "imhn"  # imhn | imhn_final | imhn_light | imhn_independent | ae
    use_bn: bool = True
    se_reduction: int = 16
    leaky_slope: float = 0.01
    # rematerialize each hourglass stack in the backward pass (memory for
    # FLOPs) — enables big per-chip batches at 512²
    remat: bool = False


@dataclass(frozen=True)
class TrainConfig:
    """Training hyper-parameters (reference: config/config.py:8-23,
    train_distributed.py:123-124, 382-400)."""
    batch_size_per_device: int = 4
    learning_rate_per_device: float = 2.5e-5
    momentum: float = 0.9
    weight_decay: float = 5e-4        # train_distributed.py:124 (train.py uses 1e-4)
    nstack_weight: Tuple[float, ...] = (1.0, 1.0, 1.0, 1.0)
    scale_weight: Tuple[float, ...] = (0.1, 0.2, 0.4, 1.6, 6.4)
    multi_task_weight: float = 0.1
    keypoint_task_weight: float = 3.0
    epochs: int = 100
    warmup_epochs: int = 3            # train_distributed.py:392-396
    lr_decay_factor: float = 0.2
    lr_step_epochs: int = 15          # /5 every 15 epochs ...
    lr_late_epoch: int = 78           # ... every 5 epochs after epoch 78
    lr_late_step_epochs: int = 5
    abnormal_loss_thre: float = 2e5   # drop batch if loss explodes (:259-261)
    max_grad_norm: float = 0.0        # 0 disables (flag kept; ref has it disabled)
    # --- large-batch recipe (train.schedule.large_batch_schedule;
    # "Extremely Large Minibatch SGD", PAPERS.md) ---
    # reference global batch the base LR was tuned at: LR scales
    # linearly by global_batch / lr_batch_ref.  0 = per-device
    # convention (ref = batch_size_per_device, i.e. LR x world_size)
    lr_batch_ref: int = 0
    # gradual-warmup epochs for the base->scaled LR ramp; 0 = reuse
    # warmup_epochs
    large_batch_warmup_epochs: int = 0
    # --- GSPMD partitioned training (parallel.partition) ---
    # run the rule-partitioned train step (state sharded per
    # partition_rules, batch over 'data', activations constrained)
    # instead of the replicated-state program
    partition: bool = False
    # named ruleset (parallel.partition.NAMED_RULESETS): "imhn" shards
    # wide conv kernels' output channels over 'model'
    partition_rules: str = "imhn"
    # 'model' mesh-axis size for make_mesh (data = devices // model)
    mesh_model_axis: int = 1
    print_freq: int = 10
    checkpoint_dir: str = "checkpoints"
    # --- checkpointing cadence + async manager (train.checkpoint) ---
    # save on epochs divisible by N (1 = every epoch); the FINAL epoch
    # of a fit always saves regardless (same always-ship rule as the
    # trailing SWA checkpoint).  Keyed on the ABSOLUTE epoch number —
    # resume-stable and aligned with milestone_every below
    save_freq: int = 1
    # run the val pass on epochs divisible by N (1 = every epoch, final
    # always); absolute-epoch-based like save_freq, so multi-process
    # collectives stay aligned
    eval_freq: int = 1
    # snapshot-then-background-write checkpointing (CheckpointManager):
    # the train loop blocks only on the device->host drain, the Orbax
    # write/commit overlap eval + the next epoch.  False = the fully
    # synchronous legacy path (the sync arm of tools/ckpt_bench.py)
    async_checkpoint: bool = True
    # retention GC over COMMITTED checkpoints: keep the last N epoch
    # dirs (0 = keep everything, GC off) ...
    keep_last_n: int = 0
    # ... plus the best checkpoint by the recorded metric (val_loss when
    # a val pass runs, else train loss) ...
    keep_best: bool = True
    # ... plus every epoch divisible by K (0 = no milestones)
    milestone_every: int = 0
    hdf5_train_data: str = "data/dataset/coco_train_dataset512.h5"
    hdf5_val_data: str = "data/dataset/coco_val_dataset512.h5"
    # normalization convention: True = divide by global batch (distributed
    # semantics, loss_model.py:39); False = caller divides (parallel twin).
    normalize_by_global_batch: bool = True
    bf16_compute: bool = True
    # route the focal loss through the Pallas kernel (ops/pallas_focal.py);
    # off by default — the XLA path is the validated production path
    use_pallas_loss: bool = False
    # --- input pipeline (data.batches / data.shm_ring) ---
    # worker transport: "shm" (persistent shared-memory slot ring, the
    # production default), "pool" (retired spawn-Pool path — its per-sample
    # pickle bytes made workers 4-6x slower than sync at 512²; kept as an
    # escape hatch), "sync" (in-process)
    input_pipeline: str = "shm"
    # image wire format: "uint8" ships warped uint8 HWC across IPC and
    # host->device (4x fewer bytes; normalized to [0,1] inside the jitted
    # step, bit-identical to f32), "f32" is the legacy [0,1] float wire
    input_wire: str = "uint8"
    # ring depth in batch slots; 0 = auto (num_workers + 2)
    input_ring_slots: int = 0
    # --- telemetry (obs/) ---
    # structured JSONL run-event sink: "" disables, "auto" writes
    # <checkpoint_dir>/events.jsonl, anything else is the path itself
    # (tools/telemetry_report.py folds the stream into a summary)
    telemetry_sink: str = ""
    # live /metrics (Prometheus text) + /snapshot (JSON) endpoint:
    # -1 disables, 0 binds an ephemeral port (logged at startup),
    # any other value is the port
    telemetry_port: int = -1
    # emit every Nth per-print_freq step record (1 = all; the data-wait/
    # compute split accumulates in counters regardless of sampling)
    telemetry_sample: int = 1
    # span-trace export (obs/trace.py -> Chrome/Perfetto trace_event
    # JSON): "" disables the export ("auto" still records into the
    # in-memory ring whenever the sink is on), "auto" writes
    # <checkpoint_dir>/trace.json, anything else is the path itself
    # (tools/trace_report.py converts + summarizes)
    telemetry_trace: str = ""
    # run-health sentinel policy on a divergent window (non-finite loss
    # or grad norm): "warn" records and keeps training, "halt" raises
    # obs.DivergenceError out of the loop, "skip_step" drops the update
    # INSIDE the jitted step (extends the abnormal_loss_thre select)
    on_divergence: str = "warn"
    # grad-norm ceiling for the sentinel; 0 = finiteness checks only
    health_grad_norm_limit: float = 0.0
    # --- heatmap distillation (train.distill; "Fast Human Pose
    # Estimation", PAPERS.md) ---
    # blend weight of the GT term:
    #   loss = alpha * focal(student, gt)
    #        + (1 - alpha) * focal(student, stop_grad(teacher))
    # 1.0 degenerates exactly to the plain supervised loss
    distill_alpha: float = 0.5
    # linear ramp of alpha from 1.0 (pure GT) down to distill_alpha over
    # the first N steps — the teacher term fades IN once the student's
    # early layers stop thrashing; 0 = constant alpha from step 0.
    # Computed on device from state.step, so the ramp costs no retraces
    distill_alpha_warmup_steps: int = 0


@dataclass(frozen=True)
class Config:
    """Bundle handed to models/losses/pipelines."""
    name: str = "canonical"
    skeleton: SkeletonConfig = field(default_factory=SkeletonConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def _canonical() -> Config:
    return Config()


def _three_stack_384() -> Config:
    """3-stack 384×384 variant (reference: config/config2.py; ckpt note
    'epoch 102 AP=0.658')."""
    return Config(
        name="three_stack_384",
        skeleton=SkeletonConfig(
            parts=_PARTS_CANONICAL, limbs=_LIMBS_3STACK, width=384, height=384,
            draw_limbs=(0,) + tuple(range(5, 22)),
            transform_params=TransformParams(
                scale_min=0.75, scale_max=1.25, center_perterb_max=40.0,
                tint_prob=0.4, keypoint_gaussian_thre=0.01,
                limb_gaussian_thre=0.01),
        ),
        model=ModelConfig(nstack=3),
        train=TrainConfig(
            batch_size_per_device=8,
            nstack_weight=(1.0, 1.0, 1.0),
            scale_weight=(0.2, 0.1, 0.4, 1.0, 4.0),
            keypoint_task_weight=1.0,
            hdf5_train_data="data/dataset/coco_train_dataset384.h5",
            hdf5_val_data="data/dataset/coco_val_dataset384.h5"),
    )


def _dense_384() -> Config:
    """Densely connected skeleton experiment (reference: config/config_dense.py;
    header notes the extra limbs hurt AP)."""
    return Config(
        name="dense_384",
        skeleton=SkeletonConfig(
            parts=_PARTS_DENSE, limbs=_LIMBS_DENSE, width=384, height=384,
            draw_limbs=(0, 5, 7, 6, 8, 12, 18, 23, 15, 20, 25, 27, 36, 43,
                        30, 39, 46, 33),
            transform_params=TransformParams(
                scale_min=0.75, scale_max=1.25, center_perterb_max=40.0,
                tint_prob=0.1, keypoint_gaussian_thre=0.005,
                limb_gaussian_thre=0.1),
        ),
        model=ModelConfig(nstack=3, inp_dim=384, increase=192),
        train=TrainConfig(
            batch_size_per_device=5,
            learning_rate_per_device=1e-4,
            nstack_weight=(1.0, 1.0, 1.0),
            scale_weight=(0.2, 0.1, 0.4, 1.0, 4.0),
            multi_task_weight=0.2,
            keypoint_task_weight=6.0,
            hdf5_train_data="data/dataset/coco_train_dataset384.h5",
            hdf5_val_data="data/dataset/coco_val_dataset384.h5"),
    )


def _final_384() -> Config:
    """4-stack 384 variant with stronger augmentation for posenet_final
    (reference: config/config_final.py:32-40)."""
    return Config(
        name="final_384",
        skeleton=SkeletonConfig(
            parts=_PARTS_CANONICAL, limbs=_LIMBS_CANONICAL, width=384, height=384,
            transform_params=TransformParams(
                scale_min=0.6, scale_max=1.5, max_rotate_degree=50.0,
                tint_prob=0.35, keypoint_gaussian_thre=0.01,
                limb_gaussian_thre=0.04),
        ),
        model=ModelConfig(variant="imhn_final"),
        train=TrainConfig(
            batch_size_per_device=8,
            learning_rate_per_device=2.5e-4,
            hdf5_train_data="data/dataset/coco_train_dataset384.h5",
            hdf5_val_data="data/dataset/coco_val_dataset384.h5"),
    )


def _tiny() -> Config:
    """Framework-native smoke-test config (no reference counterpart): a
    depth-2, 2-stack, 16-channel IMHN at 128px for CPU tests and CLI
    dry-runs."""
    return Config(
        name="tiny",
        skeleton=SkeletonConfig(width=128, height=128),
        model=ModelConfig(nstack=2, inp_dim=16, increase=8,
                          hourglass_depth=2, se_reduction=4),
        train=TrainConfig(batch_size_per_device=1,
                          nstack_weight=(1.0, 1.0),
                          scale_weight=(0.5, 1.0, 2.0),
                          epochs=2, warmup_epochs=1),
    )


def _synth() -> Config:
    """Drawn-person synthetic benchmark (framework-native, no reference
    counterpart): the tiny IMHN with a hotter LR and a real batch, used by
    tools/synth_ap.py to demonstrate the full learn→decode→AP loop on the
    rendered stick-figure fixture (data/fixture.py ``drawn=True``)."""
    return Config(
        name="synth",
        skeleton=SkeletonConfig(width=128, height=128),
        model=ModelConfig(nstack=2, inp_dim=16, increase=8,
                          hourglass_depth=2, se_reduction=4),
        train=TrainConfig(batch_size_per_device=4,
                          # SGD+momentum sweep on the drawn fixture:
                          # 1e-3 converges fastest, 1e-2 diverges; near
                          # the stability edge — corpora much larger than
                          # ~100 images (3x the steps/epoch) have been
                          # observed to explode mid-run at 1e-3, so drop
                          # to 5e-4 or stretch warmup when scaling up
                          learning_rate_per_device=1e-3,
                          nstack_weight=(1.0, 1.0),
                          scale_weight=(0.5, 1.0, 2.0),
                          epochs=60, warmup_epochs=2),
    )


def _synth_deep() -> Config:
    """Production-architecture synthetic benchmark (framework-native):
    the flagship IMHN *shape* — 4 stacks, recursive depth-4 hourglass,
    BN, bf16 compute, per-stack remat, full 5-scale supervision — at a
    width (inp_dim 64) and resolution (256²) a 1-core CPU host can
    train in hours.  Bridges the toy ``synth`` config (2-stack/16-ch,
    where every learn→AP measurement before round 4 lived) and the true
    canonical config (reference: config/config.py:14-16, 4-stack/256-ch
    @512²), exercising every production training knob the toy config
    skips: cross-stack caches at depth 4, BN statistics through 4
    stacks, bf16 numerics, rematerialized backward, and the canonical
    5-scale loss pyramid with the reference's scale weights."""
    return Config(
        name="synth_deep",
        skeleton=SkeletonConfig(width=256, height=256),
        model=ModelConfig(nstack=4, inp_dim=64, increase=32,
                          hourglass_depth=4, se_reduction=16, remat=True),
        train=TrainConfig(batch_size_per_device=4,
                          # deeper + wider than synth: keep well inside
                          # the SGD stability edge (see _synth note)
                          learning_rate_per_device=5e-4,
                          nstack_weight=(1.0, 1.0, 1.0, 1.0),
                          scale_weight=(0.1, 0.2, 0.4, 1.6, 6.4),
                          epochs=30, warmup_epochs=2,
                          bf16_compute=True),
    )


def _synth_canonical() -> Config:
    """The CANONICAL-WIDTH model on the synthetic benchmark: every model
    hyperparameter exactly matches the reference flagship (reference:
    config/config.py:14-16 — nstack=4, hourglass_inp_dim=256,
    increase=128, bn=True → 128,998,760 params), with only the canvas
    reduced (512² → 192²) so a 1-core CPU host can execute a real
    multi-epoch learn→AP run (~8 s/step measured; 512² would be ~60).
    This stages the last architecture-scale claim — "the production
    model, not just the production shape, learns" — until a chip is
    available for the full-resolution run; tools/synth_ap.py
    --config synth_canonical drives it (CANONICAL_TRAIN.json).

    Width changes optimization (BN statistics, LR scale, bf16
    accumulation, memory under remat), so this is NOT redundant with
    ``synth_deep`` (inp_dim=64, 8.2M params).  LR: the reference's
    canonical 2.5e-5/process is tuned for 4×4-batch COCO epochs;
    on the ~100-record drawn corpus it would take hundreds of epochs to
    move, so the benchmark keeps synth_deep's 5e-4 stability-tested
    setting scaled down 2× for the 16× wider model (2.5e-4), with the
    reference's warmup + /5-every-15-epochs step schedule unchanged.
    """
    return Config(
        name="synth_canonical",
        skeleton=SkeletonConfig(width=192, height=192),
        # EXACTLY the canonical flagship architecture (remat, a
        # training-memory knob, on — as the flagship-shape runs use it)
        model=ModelConfig(remat=True),
        train=TrainConfig(batch_size_per_device=2,
                          learning_rate_per_device=2.5e-4,
                          epochs=18, warmup_epochs=2,
                          bf16_compute=True),
    )


def _synth_canonical_512() -> Config:
    """``synth_canonical`` at FULL resolution: the reference flagship
    exactly as trained (reference: config/config.py:14-16 — nstack=4,
    inp_dim=256, increase=128, 512² input → 128,998,760 params) on the
    synthetic drawn-person benchmark, for the ON-CHIP learn→AP run the
    round-4 verdict staged (CANONICAL_TRAIN.json was the reduced-canvas
    CPU stage).  Batch 8 is the one-chip batch the round-5 train-step
    timing measured at 110 ms/step = 72.6 imgs/s on a v5e; LR follows
    synth_canonical's stability-tested 2.5e-4 (the reference's COCO
    2.5e-5 barely moves on a ~100-image corpus), with the reference's
    warmup + /5-every-15-epochs schedule unchanged."""
    return Config(
        name="synth_canonical_512",
        model=ModelConfig(remat=True),
        train=TrainConfig(batch_size_per_device=8,
                          learning_rate_per_device=2.5e-4,
                          epochs=30, warmup_epochs=2,
                          bf16_compute=True),
    )


def _synth_deep_student() -> Config:
    """Student twin of ``synth_deep`` (the production-SHAPE pair a CPU
    host can actually run): 2 stacks at a quarter of the width, depth-4
    hourglasses and the full 5-scale supervision kept.  The cascade
    bench's default fast tier (tools/cascade_bench.py: its fused decode
    dispatch measures ~2.8x cheaper than synth_deep's at 256px on the
    2-core host), and the distillation smoke target
    (``--distill-from <synth_deep ckpt> --teacher-config synth_deep``).
    """
    return Config(
        name="synth_deep_student",
        skeleton=SkeletonConfig(width=256, height=256),
        model=ModelConfig(nstack=2, inp_dim=16, increase=8,
                          hourglass_depth=4, se_reduction=8),
        train=TrainConfig(batch_size_per_device=4,
                          learning_rate_per_device=5e-4,
                          nstack_weight=(1.0, 1.0),
                          scale_weight=(0.1, 0.2, 0.4, 1.6, 6.4),
                          epochs=30, warmup_epochs=2,
                          bf16_compute=True,
                          distill_alpha=0.5),
    )


def _canonical_student() -> Config:
    """The distilled FAST TIER of the canonical flagship (ROADMAP open
    item 2; "Fast Human Pose Estimation" / "FasterPose", PAPERS.md): a
    2-stack, half-width IMHN trained with heatmap distillation from the
    4-stack/256-ch teacher (``tools/train.py --distill-from``), served
    as the cascade's student lane (``serve.cascade``) with escalation to
    the teacher on hard frames.  Architecture follows the papers' recipe
    — halve the stacks AND the width (~1/8 the FLOPs); the skeleton,
    channel layout and bucket geometry are the teacher's exactly, so the
    two tiers share serve buckets and the escalation decode is
    layout-compatible."""
    return Config(
        name="canonical_student",
        model=ModelConfig(nstack=2, inp_dim=128, increase=64),
        train=TrainConfig(batch_size_per_device=8,
                          nstack_weight=(1.0, 1.0),
                          distill_alpha=0.5),
    )


def _tiny_student() -> Config:
    """Student twin of ``tiny`` for CPU tests, the graftaudit registry
    and the cascade bench: ONE stack at half the width (the narrow 1-2
    stack variant of the distillation recipe, scaled to smoke size).
    Same 18-part skeleton and 128px canvas as ``tiny``, so a
    tiny_student/tiny cascade shares bucket shapes end to end."""
    return Config(
        name="tiny_student",
        skeleton=SkeletonConfig(width=128, height=128),
        model=ModelConfig(nstack=1, inp_dim=8, increase=4,
                          hourglass_depth=2, se_reduction=4),
        train=TrainConfig(batch_size_per_device=1,
                          nstack_weight=(1.0,),
                          scale_weight=(0.5, 1.0, 2.0),
                          epochs=2, warmup_epochs=1,
                          distill_alpha=0.5),
    )


def _ae() -> Config:
    """Associative-Embedding-style classic hourglass (reference:
    models/ae_pose.py, kept for ablation): ONE full-resolution output per
    stack, so the loss runs with a single scale weight.  (The reference never
    shipped a config for it — its 5-scale loss cannot consume ae outputs.)"""
    return Config(
        name="ae",
        model=ModelConfig(variant="ae"),
        train=TrainConfig(scale_weight=(1.0,)),
    )


_REGISTRY = {
    "canonical": _canonical,
    "three_stack_384": _three_stack_384,
    "dense_384": _dense_384,
    "final_384": _final_384,
    "tiny": _tiny,
    "tiny_student": _tiny_student,
    "canonical_student": _canonical_student,
    "synth": _synth,
    "synth_deep": _synth_deep,
    "synth_deep_student": _synth_deep_student,
    "synth_canonical": _synth_canonical,
    "synth_canonical_512": _synth_canonical_512,
    "ae": _ae,
}


def get_config(name: str = "canonical") -> Config:
    """Named registry (reference: config/config.py:239-260 ``GetConfig``)."""
    if name not in _REGISTRY:
        raise KeyError(f"unknown config '{name}'; available: {sorted(_REGISTRY)}")
    return _REGISTRY[name]()


def available_configs() -> List[str]:
    return sorted(_REGISTRY)
