"""Deterministic synthetic corpus fixture for tests and smoke runs.

Writes a tiny HDF5 file with the exact schema of the real corpus builder
(see hdf5_corpus.py / reference data/coco_masks_hdf5.py): groups ``dataset`` /
``images`` / ``masks``, per-main-person JSON records.  People are simple stick
figures with plausible COCO-order joints so the GT synthesis produces
non-trivial heatmaps.
"""
from __future__ import annotations

import json
from typing import Tuple

import numpy as np

from .hdf5_corpus import write_record

# rough upright stick figure in a unit box: (x, y) per COCO part
_UNIT_POSE = {
    "nose": (0.50, 0.10), "Leye": (0.55, 0.07), "Reye": (0.45, 0.07),
    "Lear": (0.60, 0.09), "Rear": (0.40, 0.09),
    "Lsho": (0.65, 0.25), "Rsho": (0.35, 0.25),
    "Lelb": (0.70, 0.42), "Relb": (0.30, 0.42),
    "Lwri": (0.72, 0.58), "Rwri": (0.28, 0.58),
    "Lhip": (0.60, 0.55), "Rhip": (0.40, 0.55),
    "Lkne": (0.60, 0.75), "Rkne": (0.40, 0.75),
    "Lank": (0.60, 0.95), "Rank": (0.40, 0.95),
}


def synthetic_person(rng: np.random.Generator, img_w: int, img_h: int,
                     image_size: int, all_visible: bool = False):
    from ..config import COCO_PARTS

    h = rng.uniform(0.4, 0.8) * img_h
    w = 0.5 * h
    x0 = rng.uniform(0, max(img_w - w, 1))
    y0 = rng.uniform(0, max(img_h - h, 1))
    joints = np.zeros((len(COCO_PARTS), 3))
    for i, part in enumerate(COCO_PARTS):
        ux, uy = _UNIT_POSE[part]
        joints[i, 0] = x0 + ux * w + rng.normal(0, 2)
        joints[i, 1] = y0 + uy * h + rng.normal(0, 2)
        # stored (internal) visibility: 1 visible, 0 occluded, 2 unlabeled
        joints[i, 2] = 1 if all_visible else rng.choice([0, 1], p=[0.2, 0.8])
    bbox = [x0, y0, w, h]
    return {
        "objpos": [x0 + w / 2, y0 + h / 2],
        "bbox": bbox,
        "segment_area": w * h,
        "num_keypoints": 17,
        "joint": joints,
        "scale_provided": h / image_size,
    }


# limb segments for RENDERING drawn people (COCO part names); bright
# part/limb colors make the figures genuinely learnable from pixels,
# unlike the noise-background fixture — but mirror counterparts MUST
# share a color (see _canonical) or the flip ensemble self-destructs
_DRAW_LIMBS = [
    ("nose", "Leye"), ("nose", "Reye"), ("Leye", "Lear"), ("Reye", "Rear"),
    ("Lsho", "Rsho"), ("Lsho", "Lelb"), ("Lelb", "Lwri"),
    ("Rsho", "Relb"), ("Relb", "Rwri"), ("Lsho", "Lhip"), ("Rsho", "Rhip"),
    ("Lhip", "Rhip"), ("Lhip", "Lkne"), ("Lkne", "Lank"),
    ("Rhip", "Rkne"), ("Rkne", "Rank"),
]


def _part_color(i: int):
    # fixed, well-separated 8-bit colors (deterministic, no rng)
    return (int((37 + i * 53) % 200 + 55), int((91 + i * 97) % 200 + 55),
            int((13 + i * 151) % 200 + 55))


def _canonical(name: str) -> str:
    """Strip the L/R prefix so mirror-counterpart parts share a color.

    The flip-ensemble (and real human appearance) assumes left/right
    symmetry: a mirrored left shoulder must LOOK like a right shoulder.
    Chiral per-part colors break that — the flipped inference lane then
    contradicts the unflipped one and the ensemble average destroys the
    peaks (measured: heat max 1.0 raw → 0.21 ensembled).  With shared
    colors the model disambiguates left/right from pose geometry, as on
    real people.
    """
    return name[1:] if len(name) > 1 and name[0] in "LR" else name


def _color_index(name: str) -> int:
    order = ["nose", "eye", "ear", "sho", "elb", "wri", "hip", "kne", "ank"]
    return order.index(_canonical(name))


def draw_person(img: np.ndarray, joints: np.ndarray) -> None:
    """Render one stick figure into ``img`` in place.

    Limbs are thick colored lines, joints filled circles with a per-part
    color.  Joints with stored visibility < 2 (visible AND occluded) are
    drawn — the same ``v < 2`` rule the heatmapper uses to synthesize GT
    (heatmapper.py), so every labeled joint has pixel evidence and the
    fixture stays learnable even without ``all_visible``.
    """
    import cv2

    from ..config import COCO_PARTS

    idx = {p: i for i, p in enumerate(COCO_PARTS)}
    for a, b in _DRAW_LIMBS:
        pa, pb = joints[idx[a]], joints[idx[b]]
        if pa[2] < 2 and pb[2] < 2:
            # limb color from the canonical endpoint pair, so mirror
            # limbs (Lsho-Lelb / Rsho-Relb) are identically colored
            ci = 9 + _color_index(a) + 2 * _color_index(b)
            cv2.line(img, (int(pa[0]), int(pa[1])), (int(pb[0]), int(pb[1])),
                     _part_color(ci), thickness=3)
    for i, name in enumerate(COCO_PARTS):
        x, y, v = joints[i]
        if v < 2:
            cv2.circle(img, (int(x), int(y)), 4,
                       _part_color(_color_index(name)), thickness=-1)


def _synth_image(rng: np.random.Generator, h: int, w: int,
                 people_per_image: int, image_size: int, drawn: bool):
    """One synthetic image + its person records (shared by the corpus and
    val-set builders so train and eval see the same distribution)."""
    if drawn:
        # low-amplitude noise background so the rendered figures are the
        # dominant signal — this is the LEARNABLE variant
        img = rng.integers(0, 64, (h, w, 3), dtype=np.uint8)
        persons = [synthetic_person(rng, w, h, image_size, all_visible=True)
                   for _ in range(people_per_image)]
        for p in persons:
            draw_person(img, p["joint"])
    else:
        img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
        persons = [synthetic_person(rng, w, h, image_size)
                   for _ in range(people_per_image)]
    return img, persons


def build_fixture(path: str, num_images: int = 4, img_size: Tuple[int, int]
                  = (240, 320), people_per_image: int = 2,
                  image_size: int = 512, seed: int = 0,
                  drawn: bool = False) -> int:
    """Write the fixture; returns the number of records.

    ``drawn=True`` renders the stick figures into the images (visible,
    colored limbs/joints on a quiet background) so a model can genuinely
    LEARN detection from pixels and generalize — the default noise images
    carry no pixel signal and only support overfit/protocol tests.
    """
    import h5py

    from .hdf5_corpus import build_masks, iter_records

    rng = np.random.default_rng(seed)
    h, w = img_size
    count = 0
    with h5py.File(path, "w") as f:
        grp = f.create_group("dataset")
        img_grp = f.create_group("images")
        mask_grp = f.create_group("masks")
        for image_index in range(num_images):
            img_id = 1000 + image_index
            img, persons = _synth_image(rng, h, w, people_per_image,
                                        image_size, drawn)
            person_masks = []
            for p in persons:
                m = np.zeros((h, w), np.uint8)
                x0, y0, bw, bh = [int(v) for v in p["bbox"]]
                m[max(y0, 0): y0 + bh, max(x0, 0): x0 + bw] = 1
                person_masks.append(m)
            mask_miss, mask_all = build_masks(
                (h, w), person_masks, [p["num_keypoints"] for p in persons])
            image_rec = {"width": w, "height": h}
            for rec in iter_records(image_rec, img_id, image_index, persons,
                                    "SYNTH", is_validation=False):
                write_record(grp, img_grp, mask_grp, rec, count, img,
                             mask_miss, mask_all)
                count += 1
    return count


def build_val_set(images_dir: str, anno_path: str, num_images: int = 16,
                  img_size: Tuple[int, int] = (240, 320),
                  people_per_image: int = 2, image_size: int = 512,
                  seed: int = 1, drawn: bool = True) -> int:
    """Held-out val set on disk: jpgs + a COCO-format keypoint JSON, the
    exact inputs of ``tools/evaluate.py`` (reference: evaluate.py:585-622
    reads COCO annotations + an image dir).  Returns the person count.

    Stored visibility (1=visible, 0=occluded, 2=unlabeled) is re-coded
    back to COCO (2 / 1 / 0) for the annotations file.
    """
    import os

    import cv2

    os.makedirs(images_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    h, w = img_size
    images, annotations = [], []
    ann_id = 0
    for image_index in range(num_images):
        img_id = 1 + image_index
        img, persons = _synth_image(rng, h, w, people_per_image,
                                    image_size, drawn)
        name = f"{img_id:012d}.jpg"
        cv2.imwrite(os.path.join(images_dir, name), img)
        images.append({"id": img_id, "file_name": name,
                       "width": w, "height": h})
        for p in persons:
            kp = []
            for x, y, v in p["joint"]:
                coco_v = {1: 2, 0: 1, 2: 0}[int(v)]
                kp.extend([float(x), float(y), coco_v])
            ann_id += 1
            annotations.append({
                "id": ann_id, "image_id": img_id, "category_id": 1,
                "keypoints": kp, "num_keypoints": p["num_keypoints"],
                "area": float(p["segment_area"]),
                "bbox": [float(v) for v in p["bbox"]], "iscrowd": 0})
    with open(anno_path, "w") as f:
        json.dump({"images": images, "annotations": annotations,
                   "categories": [{"id": 1, "name": "person"}]}, f)
    return ann_id
