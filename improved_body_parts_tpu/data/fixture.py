"""Deterministic synthetic corpus fixture for tests and smoke runs.

Writes a tiny HDF5 file with the exact schema of the real corpus builder
(see hdf5_corpus.py / reference data/coco_masks_hdf5.py): groups ``dataset`` /
``images`` / ``masks``, per-main-person JSON records.  People are simple stick
figures with plausible COCO-order joints so the GT synthesis produces
non-trivial heatmaps.
"""
from __future__ import annotations

from typing import Tuple

import numpy as np

from ..obs.events import strict_dump
from .hdf5_corpus import NUM_COCO_PARTS, write_record

# rough upright stick figure in a unit box: (x, y) per COCO part
_UNIT_POSE = {
    "nose": (0.50, 0.10), "Leye": (0.55, 0.07), "Reye": (0.45, 0.07),
    "Lear": (0.60, 0.09), "Rear": (0.40, 0.09),
    "Lsho": (0.65, 0.25), "Rsho": (0.35, 0.25),
    "Lelb": (0.70, 0.42), "Relb": (0.30, 0.42),
    "Lwri": (0.72, 0.58), "Rwri": (0.28, 0.58),
    "Lhip": (0.60, 0.55), "Rhip": (0.40, 0.55),
    "Lkne": (0.60, 0.75), "Rkne": (0.40, 0.75),
    "Lank": (0.60, 0.95), "Rank": (0.40, 0.95),
}


def synthetic_person(rng: np.random.Generator, img_w: int, img_h: int,
                     image_size: int, all_visible: bool = False,
                     hard: bool = False):
    """One stick-figure person record.

    ``hard=True`` is the harder benchmark tier (round-5): a wider scale
    range (0.25–0.85 vs 0.4–0.8 of image height) and a per-person
    IN-PLANE ROTATION of the whole figure (uniform ±60°) about its
    centre — beyond the training augmentation's ±40° range
    (configs.TransformParams.max_rotate_degree), so upright-only
    inference degrades and the reference's rotation TTA grid
    (reference: evaluate.py:89-90) has poses where it genuinely pays.
    The bbox/objpos/scale are recomputed from the rotated joints, the
    way COCO boxes follow the person, not the canvas.
    """
    from ..config import COCO_PARTS

    lo, hi = (0.25, 0.85) if hard else (0.4, 0.8)
    h = rng.uniform(lo, hi) * img_h
    w = 0.5 * h
    x0 = rng.uniform(0, max(img_w - w, 1))
    y0 = rng.uniform(0, max(img_h - h, 1))
    joints = np.zeros((len(COCO_PARTS), 3))
    for i, part in enumerate(COCO_PARTS):
        ux, uy = _UNIT_POSE[part]
        joints[i, 0] = x0 + ux * w + rng.normal(0, 2)
        joints[i, 1] = y0 + uy * h + rng.normal(0, 2)
        # stored (internal) visibility: 1 visible, 0 occluded, 2 unlabeled
        joints[i, 2] = 1 if all_visible else rng.choice([0, 1], p=[0.2, 0.8])
    bbox = [x0, y0, w, h]
    if hard:
        theta = np.radians(rng.uniform(-60.0, 60.0))
        c, s = np.cos(theta), np.sin(theta)
        cx, cy = x0 + w / 2, y0 + h / 2
        dx, dy = joints[:, 0] - cx, joints[:, 1] - cy
        joints[:, 0] = cx + c * dx - s * dy
        joints[:, 1] = cy + s * dx + c * dy
        # keep the figure on-canvas after rotation; when it cannot fit
        # (rotated extent wider than the canvas), center it instead —
        # min(lo, hi) ordering matters, np.clip(0, lo, hi) silently
        # returns hi when lo > hi
        for axis, bound in ((0, img_w - 1), (1, img_h - 1)):
            lo = -joints[:, axis].min()          # shift needed at the low edge
            hi = bound - joints[:, axis].max()   # headroom at the high edge
            joints[:, axis] += (lo + hi) / 2 if lo > hi else np.clip(0, lo, hi)
        margin = 0.05 * h
        jx0, jy0 = joints[:, 0].min() - margin, joints[:, 1].min() - margin
        bw = joints[:, 0].max() + margin - jx0
        bh = joints[:, 1].max() + margin - jy0
        bbox = [jx0, jy0, bw, bh]
        x0, y0, w, h = jx0, jy0, bw, bh
    return {
        "objpos": [x0 + w / 2, y0 + h / 2],
        "bbox": bbox,
        "segment_area": w * h,
        "num_keypoints": 17,
        "joint": joints,
        "scale_provided": h / image_size,
    }


# limb segments for RENDERING drawn people (COCO part names); bright
# part/limb colors make the figures genuinely learnable from pixels,
# unlike the noise-background fixture — but mirror counterparts MUST
# share a color (see _canonical) or the flip ensemble self-destructs
_DRAW_LIMBS = [
    ("nose", "Leye"), ("nose", "Reye"), ("Leye", "Lear"), ("Reye", "Rear"),
    ("Lsho", "Rsho"), ("Lsho", "Lelb"), ("Lelb", "Lwri"),
    ("Rsho", "Relb"), ("Relb", "Rwri"), ("Lsho", "Lhip"), ("Rsho", "Rhip"),
    ("Lhip", "Rhip"), ("Lhip", "Lkne"), ("Lkne", "Lank"),
    ("Rhip", "Rkne"), ("Rkne", "Rank"),
]


def _part_color(i: int):
    # fixed, well-separated 8-bit colors (deterministic, no rng)
    return (int((37 + i * 53) % 200 + 55), int((91 + i * 97) % 200 + 55),
            int((13 + i * 151) % 200 + 55))


def _canonical(name: str) -> str:
    """Strip the L/R prefix so mirror-counterpart parts share a color.

    The flip-ensemble (and real human appearance) assumes left/right
    symmetry: a mirrored left shoulder must LOOK like a right shoulder.
    Chiral per-part colors break that — the flipped inference lane then
    contradicts the unflipped one and the ensemble average destroys the
    peaks (measured: heat max 1.0 raw → 0.21 ensembled).  With shared
    colors the model disambiguates left/right from pose geometry, as on
    real people.
    """
    return name[1:] if len(name) > 1 and name[0] in "LR" else name


def _color_index(name: str) -> int:
    order = ["nose", "eye", "ear", "sho", "elb", "wri", "hip", "kne", "ank"]
    return order.index(_canonical(name))


def draw_person(img: np.ndarray, joints: np.ndarray) -> None:
    """Render one stick figure into ``img`` in place.

    Limbs are thick colored lines, joints filled circles with a per-part
    color.  Joints with stored visibility < 2 (visible AND occluded) are
    drawn — the same ``v < 2`` rule the heatmapper uses to synthesize GT
    (heatmapper.py), so every labeled joint has pixel evidence and the
    fixture stays learnable even without ``all_visible``.
    """
    import cv2

    from ..config import COCO_PARTS

    idx = {p: i for i, p in enumerate(COCO_PARTS)}
    for a, b in _DRAW_LIMBS:
        pa, pb = joints[idx[a]], joints[idx[b]]
        if pa[2] < 2 and pb[2] < 2:
            # limb color from the canonical endpoint pair, so mirror
            # limbs (Lsho-Lelb / Rsho-Relb) are identically colored
            ci = 9 + _color_index(a) + 2 * _color_index(b)
            cv2.line(img, (int(pa[0]), int(pa[1])), (int(pb[0]), int(pb[1])),
                     _part_color(ci), thickness=3)
    for i, name in enumerate(COCO_PARTS):
        x, y, v = joints[i]
        if v < 2:
            cv2.circle(img, (int(x), int(y)), 4,
                       _part_color(_color_index(name)), thickness=-1)


def _render_crowd_box(rng: np.random.Generator, img: np.ndarray,
                      image_size: int) -> np.ndarray:
    """Draw a crowd: several overlapping partial stick figures inside a
    box; returns the {0,1} crowd mask (the synthetic stand-in for COCO's
    RLE crowd regions, reference coco_masks_hdf5.py:66-99)."""
    h, w = img.shape[:2]
    bw = int(rng.uniform(0.25, 0.4) * w)
    bh = int(rng.uniform(0.3, 0.5) * h)
    x0 = int(rng.uniform(0, w - bw))
    y0 = int(rng.uniform(0, h - bh))
    for _ in range(3):
        p = synthetic_person(rng, bw, bh, image_size, all_visible=True)
        joints = p["joint"].copy()
        joints[:, 0] += x0
        joints[:, 1] += y0
        draw_person(img, joints)
    mask = np.zeros((h, w), np.uint8)
    mask[y0:y0 + bh, x0:x0 + bw] = 1
    return mask


def _synth_image(rng: np.random.Generator, h: int, w: int,
                 people_per_image: int, image_size: int, drawn: bool,
                 crowd: bool = False, hard: bool = False):
    """One synthetic image + its person records (shared by the corpus and
    val-set builders so train and eval see the same distribution).

    ``crowd=True`` (drawn protocol only) additionally renders, with pixel
    evidence but NO ground truth: an unannotated person (appended to
    ``persons`` with ``num_keypoints=0`` — the corpus rules then exclude
    it from records and zero its region in mask_miss) and/or a crowd box
    of overlapping figures (returned as a crowd mask).  This reproduces
    the structure that makes the reference's miss-mask machinery matter
    (reference: coco_masks_hdf5.py:38-116, loss_model.py:52-56): real
    people in pixels the loss must NOT penalize the model for detecting.

    Returns (img, persons, crowd_masks).
    """
    if drawn:
        # low-amplitude noise background so the rendered figures are the
        # dominant signal — this is the LEARNABLE variant
        img = rng.integers(0, 64, (h, w, 3), dtype=np.uint8)
        persons = [synthetic_person(rng, w, h, image_size, all_visible=True,
                                    hard=hard)
                   for _ in range(people_per_image)]
        for p in persons:
            draw_person(img, p["joint"])
    else:
        img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
        persons = [synthetic_person(rng, w, h, image_size, hard=hard)
                   for _ in range(people_per_image)]
    crowd_masks = []
    if crowd:
        add_unannotated = bool(rng.uniform() < 0.5)
        add_crowd = bool(rng.uniform() < 0.5) or not add_unannotated
        if add_unannotated:
            extra = synthetic_person(rng, w, h, image_size, all_visible=True)
            if drawn:
                draw_person(img, extra["joint"])
            extra["num_keypoints"] = 0  # pixel evidence, no annotation
            persons.append(extra)
        if add_crowd:
            crowd_masks.append(_render_crowd_box(rng, img, image_size))
    return img, persons, crowd_masks


def build_fixture(path: str, num_images: int = 4, img_size: Tuple[int, int]
                  = (240, 320), people_per_image: int = 2,
                  image_size: int = 512, seed: int = 0,
                  drawn: bool = False, crowd: bool = False,
                  mask_extras: bool = True, hard: bool = False) -> int:
    """Write the fixture; returns the number of records.

    ``drawn=True`` renders the stick figures into the images (visible,
    colored limbs/joints on a quiet background) so a model can genuinely
    LEARN detection from pixels and generalize — the default noise images
    carry no pixel signal and only support overfit/protocol tests.

    ``crowd=True`` adds unannotated people and crowd regions with pixel
    evidence but no GT (see ``_synth_image``), producing corpora with
    non-trivial mask_miss — the end-to-end exercise of the reference's
    miss-mask semantics.  ``mask_extras=False`` is the ablation: the SAME
    extras are rendered but mask_miss stays all-ones, so training wrongly
    penalizes the model for detecting them (quantifies what the masking
    machinery buys; tools/synth_ap.py --crowd runs both arms).
    """
    import h5py

    from .hdf5_corpus import build_masks, iter_records

    rng = np.random.default_rng(seed)
    h, w = img_size
    count = 0
    with h5py.File(path, "w") as f:
        grp = f.create_group("dataset")
        img_grp = f.create_group("images")
        mask_grp = f.create_group("masks")
        for image_index in range(num_images):
            img_id = 1000 + image_index
            img, persons, crowd_masks = _synth_image(
                rng, h, w, people_per_image, image_size, drawn, crowd=crowd,
                hard=hard)
            person_masks = []
            for p in persons:
                m = np.zeros((h, w), np.uint8)
                x0, y0, bw, bh = [int(v) for v in p["bbox"]]
                m[max(y0, 0): y0 + bh, max(x0, 0): x0 + bw] = 1
                person_masks.append(m)
            mask_miss, mask_all = build_masks(
                (h, w), person_masks, [p["num_keypoints"] for p in persons],
                crowd_masks=crowd_masks)
            if not mask_extras:
                mask_miss = np.full((h, w), 255, np.uint8)
            image_rec = {"width": w, "height": h}
            for rec in iter_records(image_rec, img_id, image_index, persons,
                                    "SYNTH", is_validation=False):
                write_record(grp, img_grp, mask_grp, rec, count, img,
                             mask_miss, mask_all)
                count += 1
    return count


def _write_coco_set(images_dir: str, anno_path: str, num_images: int,
                    img_size: Tuple[int, int], people_per_image: int,
                    image_size: int, seed: int, drawn: bool, crowd: bool,
                    train_side: bool, hard: bool = False) -> int:
    """Shared emitter behind :func:`build_val_set` /
    :func:`build_coco_train_set` — one per-image loop so the visibility
    recode, crowd-bbox extraction and JSON shape cannot drift between the
    two surfaces.  The only policy differences:

    - ``train_side=True`` writes segmentations (cycling polygon →
      uncompressed RLE → compressed RLE so one corpus build exercises
      every ``coco_masks`` decode path) and keeps unannotated people as
      ``iscrowd=0, num_keypoints=0`` — real COCO's shape for people
      lacking keypoint labels, which the corpus rules route into
      mask_miss;
    - ``train_side=False`` (eval side) writes no segmentations and marks
      unannotated people ``iscrowd=1`` so COCOeval / the OKS proxy
      IGNORES detections landing there (real COCO crowds' treatment);
    - crowd-region ``area``: mask pixel count on the train side (real
      COCO derives crowd area from the RLE) vs bbox area on the eval
      side (the OKS proxy's ignore radius works from the bbox).

    Returns the number of annotated (scored) persons.
    """
    import os

    import cv2

    from .coco_masks import rle_encode, rle_to_string

    os.makedirs(images_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    h, w = img_size
    images, annotations = [], []
    encodings = ("polygon", "rle", "crle")
    ann_id = 0
    n_scored = 0
    for image_index in range(num_images):
        img_id = 1 + image_index
        img, persons, crowd_masks = _synth_image(
            rng, h, w, people_per_image, image_size, drawn, crowd=crowd,
            hard=hard)
        name = f"{img_id:012d}.jpg"
        cv2.imwrite(os.path.join(images_dir, name), img)
        images.append({"id": img_id, "file_name": name,
                       "width": w, "height": h})
        for p in persons:
            unannotated = p["num_keypoints"] == 0
            kp = []
            for x, y, v in p["joint"]:
                coco_v = 0 if unannotated else {1: 2, 0: 1, 2: 0}[int(v)]
                kp.extend([0.0, 0.0, 0] if unannotated
                          else [float(x), float(y), coco_v])
            ann_id += 1
            n_scored += 0 if unannotated else 1
            ann = {
                "id": ann_id, "image_id": img_id, "category_id": 1,
                "keypoints": kp, "num_keypoints": p["num_keypoints"],
                "area": float(p["segment_area"]),
                "bbox": [float(v) for v in p["bbox"]],
                "iscrowd": (1 if unannotated and not train_side else 0)}
            if train_side:
                ann["segmentation"] = _rect_segmentation(
                    p["bbox"], h, w, encodings[ann_id % len(encodings)])
            annotations.append(ann)
        for cm in crowd_masks:
            ys, xs = np.nonzero(cm)
            x0, y0 = float(xs.min()), float(ys.min())
            bw, bh = float(xs.max() - x0 + 1), float(ys.max() - y0 + 1)
            ann_id += 1
            ann = {
                "id": ann_id, "image_id": img_id, "category_id": 1,
                "keypoints": [0.0, 0.0, 0] * NUM_COCO_PARTS,
                "num_keypoints": 0,
                "area": float(cm.sum()) if train_side else bw * bh,
                "bbox": [x0, y0, bw, bh], "iscrowd": 1}
            if train_side:
                ann["segmentation"] = {
                    "size": [h, w], "counts": rle_to_string(rle_encode(cm))}
            annotations.append(ann)
    with open(anno_path, "w") as f:
        strict_dump({"images": images, "annotations": annotations,
                     "categories": [{"id": 1, "name": "person"}]}, f)
    return n_scored


def build_val_set(images_dir: str, anno_path: str, num_images: int = 16,
                  img_size: Tuple[int, int] = (240, 320),
                  people_per_image: int = 2, image_size: int = 512,
                  seed: int = 1, drawn: bool = True,
                  crowd: bool = False, hard: bool = False) -> int:
    """Held-out val set on disk: jpgs + a COCO-format keypoint JSON, the
    exact inputs of ``tools/evaluate.py`` (reference: evaluate.py:585-622
    reads COCO annotations + an image dir).  Returns the count of
    NON-ignored person annotations.

    Stored visibility (1=visible, 0=occluded, 2=unlabeled) is re-coded
    back to COCO (2 / 1 / 0) for the annotations file.

    ``crowd=True`` renders the same unannotated-people / crowd-box extras
    as the training corpus and annotates their regions ``iscrowd=1`` with
    zero keypoints — COCOeval (and the OKS proxy's ``k1 == 0`` bbox
    fallback) then IGNORES detections landing there instead of counting
    false positives, exactly real COCO's treatment of crowds.
    """
    return _write_coco_set(images_dir, anno_path, num_images, img_size,
                           people_per_image, image_size, seed, drawn, crowd,
                           train_side=False, hard=hard)


def _rect_mask(bbox, h: int, w: int) -> np.ndarray:
    x0, y0, bw, bh = [int(round(v)) for v in bbox]
    m = np.zeros((h, w), np.uint8)
    m[max(y0, 0): y0 + bh, max(x0, 0): x0 + bw] = 1
    return m


def _rect_segmentation(bbox, h: int, w: int, encoding: str):
    """A rectangle in one of the three COCO segmentation encodings.

    RLE variants encode the exact same pixel set as the fixture's HDF5
    person masks; the polygon variant covers the rect with ``cv2.fillPoly``
    inclusive-boundary semantics (see coco_masks.polygons_to_mask).
    """
    from .coco_masks import rle_encode, rle_to_string

    if encoding == "polygon":
        x0, y0, bw, bh = bbox
        return [[x0, y0, x0 + bw, y0, x0 + bw, y0 + bh, x0, y0 + bh]]
    counts = rle_encode(_rect_mask(bbox, h, w))
    if encoding == "rle":
        return {"size": [h, w], "counts": counts}
    assert encoding == "crle", encoding
    return {"size": [h, w], "counts": rle_to_string(counts)}


def build_coco_train_set(images_dir: str, anno_path: str,
                         num_images: int = 8,
                         img_size: Tuple[int, int] = (240, 320),
                         people_per_image: int = 2, image_size: int = 512,
                         seed: int = 0, drawn: bool = True,
                         crowd: bool = False, hard: bool = False) -> int:
    """Synthetic TRAIN-side COCO dataset on disk: jpgs + a
    person_keypoints JSON **with segmentations** — the exact inputs of
    ``tools/make_corpus.py`` (reference: data/coco_masks_hdf5.py:304-351
    reads COCO annotations + an image dir), enabling the full COCO-format
    journey (JSON+images → HDF5 → train → evaluate) without any COCO
    download or pycocotools.  See :func:`_write_coco_set` for the
    train-side annotation policy.  Returns the number of annotated
    (scored) persons.
    """
    return _write_coco_set(images_dir, anno_path, num_images, img_size,
                           people_per_image, image_size, seed, drawn, crowd,
                           train_side=True, hard=hard)
