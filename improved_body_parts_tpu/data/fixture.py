"""Deterministic synthetic corpus fixture for tests and smoke runs.

Writes a tiny HDF5 file with the exact schema of the real corpus builder
(see hdf5_corpus.py / reference data/coco_masks_hdf5.py): groups ``dataset`` /
``images`` / ``masks``, per-main-person JSON records.  People are simple stick
figures with plausible COCO-order joints so the GT synthesis produces
non-trivial heatmaps.
"""
from __future__ import annotations

import json
from typing import Tuple

import numpy as np

from .hdf5_corpus import write_record

# rough upright stick figure in a unit box: (x, y) per COCO part
_UNIT_POSE = {
    "nose": (0.50, 0.10), "Leye": (0.55, 0.07), "Reye": (0.45, 0.07),
    "Lear": (0.60, 0.09), "Rear": (0.40, 0.09),
    "Lsho": (0.65, 0.25), "Rsho": (0.35, 0.25),
    "Lelb": (0.70, 0.42), "Relb": (0.30, 0.42),
    "Lwri": (0.72, 0.58), "Rwri": (0.28, 0.58),
    "Lhip": (0.60, 0.55), "Rhip": (0.40, 0.55),
    "Lkne": (0.60, 0.75), "Rkne": (0.40, 0.75),
    "Lank": (0.60, 0.95), "Rank": (0.40, 0.95),
}


def synthetic_person(rng: np.random.Generator, img_w: int, img_h: int,
                     image_size: int):
    from ..config import COCO_PARTS

    h = rng.uniform(0.4, 0.8) * img_h
    w = 0.5 * h
    x0 = rng.uniform(0, max(img_w - w, 1))
    y0 = rng.uniform(0, max(img_h - h, 1))
    joints = np.zeros((len(COCO_PARTS), 3))
    for i, part in enumerate(COCO_PARTS):
        ux, uy = _UNIT_POSE[part]
        joints[i, 0] = x0 + ux * w + rng.normal(0, 2)
        joints[i, 1] = y0 + uy * h + rng.normal(0, 2)
        joints[i, 2] = rng.choice([0, 1], p=[0.2, 0.8])  # hidden/visible
    bbox = [x0, y0, w, h]
    return {
        "objpos": [x0 + w / 2, y0 + h / 2],
        "bbox": bbox,
        "segment_area": w * h,
        "num_keypoints": 17,
        "joint": joints,
        "scale_provided": h / image_size,
    }


def build_fixture(path: str, num_images: int = 4, img_size: Tuple[int, int]
                  = (240, 320), people_per_image: int = 2,
                  image_size: int = 512, seed: int = 0) -> int:
    """Write the fixture; returns the number of records."""
    import h5py

    from .hdf5_corpus import build_masks, iter_records

    rng = np.random.default_rng(seed)
    h, w = img_size
    count = 0
    with h5py.File(path, "w") as f:
        grp = f.create_group("dataset")
        img_grp = f.create_group("images")
        mask_grp = f.create_group("masks")
        for image_index in range(num_images):
            img_id = 1000 + image_index
            img = rng.integers(0, 255, (h, w, 3), dtype=np.uint8)
            persons = [synthetic_person(rng, w, h, image_size)
                       for _ in range(people_per_image)]
            person_masks = []
            for p in persons:
                m = np.zeros((h, w), np.uint8)
                x0, y0, bw, bh = [int(v) for v in p["bbox"]]
                m[max(y0, 0): y0 + bh, max(x0, 0): x0 + bw] = 1
                person_masks.append(m)
            mask_miss, mask_all = build_masks(
                (h, w), person_masks, [p["num_keypoints"] for p in persons])
            image_rec = {"width": w, "height": h}
            for rec in iter_records(image_rec, img_id, image_index, persons,
                                    "SYNTH", is_validation=False):
                write_record(grp, img_grp, mask_grp, rec, count, img,
                             mask_miss, mask_all)
                count += 1
    return count
