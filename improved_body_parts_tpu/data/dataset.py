"""Online training dataset: HDF5 corpus → (image, mask_miss, labels) samples.

Replaces the reference's torch Dataset + iterator
(reference: data/mydataset.py, py_cocodata_server/py_data_iterator.py) with a
seedable, host-shardable pipeline:

- per-sample randomness comes from a ``(seed, epoch, index)``-derived
  ``numpy.random.Generator`` — deterministic and fork-safe (fixes the
  DataLoader numpy-seed hazard noted at data/mydataset.py:33);
- epoch shuffling is an epoch-seeded permutation and multi-host sharding is a
  strided slice of it — replacing ``DistributedSampler.set_epoch``
  (train_distributed.py:205-213, 231-232);
- HDF5 handles are opened lazily per process (py_data_iterator.py:41-44).

Outputs are channel-LAST: image (H, W, 3) — float32 in [0,1] on the legacy
``wire="f32"``, warped uint8 pixels on ``wire="uint8"`` (normalized inside
the jitted train step) — mask_miss (h, w, 1), labels (h, w, num_layers) on
the stride-4 grid.  Multi-worker loading goes through the shared-memory
slot ring (``data.shm_ring``); the spawn-Pool transport is retired but
kept as ``batches(pipeline="pool")``.
"""
from __future__ import annotations

import json
from typing import Iterator, Optional, Tuple

import numpy as np

from ..config import COCO_PARTS, Config, SkeletonConfig
from .heatmapper import Heatmapper
from .transformer import AugmentParams, Transformer


def convert_joints(coco_joints: np.ndarray, skeleton: SkeletonConfig
                   ) -> np.ndarray:
    """COCO 17-part → internal 18-part order with neck = mean of shoulders
    (reference: config/config.py:155-224 ``COCOSourceConfig.convert``).

    Visibility: 3 = never marked in this dataset (the synthetic neck gets 2
    when either shoulder is unknown, else min of the shoulder flags).
    """
    coco_index = {p: i for i, p in enumerate(COCO_PARTS)}
    n_people = coco_joints.shape[0]
    out = np.zeros((n_people, skeleton.num_parts, 3), dtype=np.float64)
    out[:, :, 2] = 3.0
    for part, gid in skeleton.parts_dict.items():
        cid = coco_index.get(part)
        if cid is not None:
            out[:, gid, :] = coco_joints[:, cid, :]
    if "neck" in skeleton.parts_dict:
        neck = skeleton.parts_dict["neck"]
        rs, ls = coco_index["Rsho"], coco_index["Lsho"]
        known = (coco_joints[:, rs, 2] < 2) & (coco_joints[:, ls, 2] < 2)
        out[~known, neck, 2] = 2.0
        out[known, neck, 0:2] = (coco_joints[known, rs, 0:2]
                                 + coco_joints[known, ls, 0:2]) / 2
        out[known, neck, 2] = np.minimum(coco_joints[known, rs, 2],
                                         coco_joints[known, ls, 2])
    return out


class CocoPoseDataset:
    """Random-access view over the HDF5 corpus."""

    def __init__(self, h5_path: str, config: Config, augment: bool = True,
                 seed: int = 0):
        self.h5_path = h5_path
        self.config = config
        self.skeleton = config.skeleton
        self.augment = augment
        self.seed = seed
        self.transformer = Transformer(self.skeleton)
        self.heatmapper = Heatmapper(self.skeleton)
        self._file = None
        import h5py
        with h5py.File(h5_path, "r") as f:
            self.keys = sorted(f["dataset"].keys())

    def __len__(self) -> int:
        return len(self.keys)

    def _groups(self):
        if self._file is None:
            import h5py
            self._file = h5py.File(self.h5_path, "r")
        f = self._file
        return f["dataset"], f["images"], f.get("masks")

    def read_raw(self, index: int):
        """(img, mask_miss, mask_all, joints, objpos, scale_provided)
        (py_data_iterator.py:109-144 'new format' reader)."""
        dataset, images, masks = self._groups()
        entry = dataset[self.keys[index]]
        meta = json.loads(entry[()])
        img = images[meta["image"]][()]
        if masks is not None and meta["image"] in masks:
            mask_concat = masks[meta["image"]][()]
            mask_miss, mask_all = mask_concat[..., 0], mask_concat[..., 1]
        else:  # MPII-style corpus without masks (py_data_iterator.py:140-142)
            mask_miss = np.full(img.shape[:2], 255, np.uint8)
            mask_all = np.zeros(img.shape[:2], np.uint8)
        joints = convert_joints(np.asarray(meta["joints"]), self.skeleton)
        return (img, mask_miss, mask_all, joints,
                tuple(meta["objpos"][0]), float(meta["scale_provided"][0]))

    def _augmented(self, index: int, epoch: int, wire: str = "f32",
                   image_out: Optional[np.ndarray] = None):
        img, mask_miss, mask_all, joints, objpos, scale = self.read_raw(index)
        rng = np.random.default_rng((self.seed, epoch, index))
        aug = None if self.augment else AugmentParams.identity()
        return self.transformer.transform(
            img, mask_miss, mask_all, joints, objpos, scale, aug=aug, rng=rng,
            wire=wire, image_out=image_out)

    def sample(self, index: int, epoch: int = 0, wire: str = "f32",
               image_out: Optional[np.ndarray] = None
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Generate one training sample deterministically from
        (seed, epoch, index).

        ``wire="uint8"`` returns the image as warped uint8 HWC pixels (the
        shared-memory pipeline's wire format; the jitted train step
        normalizes on device, bit-identical to the host f32 wire);
        ``image_out`` optionally renders the uint8 image in place.
        """
        img, mask_miss, mask_all, joints = self._augmented(
            index, epoch, wire=wire, image_out=image_out)
        labels = self.heatmapper.create_heatmaps(joints, mask_all)
        return img, mask_miss[..., None], labels

    def sample_raw(self, index: int, epoch: int = 0, max_people: int = 16,
                   wire: str = "f32",
                   image_out: Optional[np.ndarray] = None
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Device-GT variant of :meth:`sample`: same deterministic
        augmentation, but returns (image, mask_miss, padded joints,
        mask_all) — labels are synthesized on device inside the train step
        (ops.make_gt_synthesizer).  Padding rows carry visibility 2
        ("absent"); people beyond ``max_people`` are dropped (rare on COCO;
        raise ``max_people`` if the corpus is denser).  ``wire`` /
        ``image_out`` as in :meth:`sample`."""
        img, mask_miss, mask_all, joints = self._augmented(
            index, epoch, wire=wire, image_out=image_out)
        padded = np.zeros((max_people, joints.shape[1], 3), np.float32)
        padded[:, :, 2] = 2.0
        n = min(len(joints), max_people)
        padded[:n] = joints[:n]
        return (img, mask_miss[..., None], padded,
                mask_all.astype(np.float32)[..., None])

    def close(self):
        if self._file is not None:
            self._file.close()
            self._file = None


def epoch_permutation(n: int, epoch: int, seed: int = 0) -> np.ndarray:
    return np.random.default_rng((seed, epoch)).permutation(n)


def host_shard(indices: np.ndarray, process_index: int, process_count: int,
               batch_size: int) -> np.ndarray:
    """This host's strided slice, truncated so every host yields the same
    number of full batches (drop_last semantics,
    train_distributed.py:205-213).

    The batch count is computed from the GLOBAL minimum shard length — a host
    with one extra sample must not run an extra step, or its collective would
    wait forever on the other hosts.
    """
    shard = indices[process_index::process_count]
    min_shard_len = len(indices) // process_count
    n_batches = min_shard_len // batch_size
    return shard[: n_batches * batch_size]


def host_batch_shard(indices: np.ndarray, process_index: int,
                     process_count: int, batch_size: int) -> np.ndarray:
    """This host's CONTIGUOUS slab of every global batch — the
    partitioned-training input shard.

    Global batch ``k`` is ``indices[k*G : (k+1)*G]`` (``G = batch_size *
    process_count`` — exactly the batch a single-host run of the same
    permutation would form), and host ``p`` renders rows ``[p*batch_size,
    (p+1)*batch_size)`` of it.  Because host ``p``'s addressable devices
    hold shard ``p`` of the 'data' axis, ``parallel.shard_batch``'s
    ``jax.make_array_from_process_local_data`` assembly reconstructs the
    single-host global batch BIT-IDENTICALLY, row order included — so
    scaling the host count changes which process renders a row, never
    which rows a step trains on.

    (The strided :func:`host_shard` yields the same per-epoch sample
    *multiset* but groups rows into different batches than a single-host
    run; it remains the replicated regime's historical shard.  Both
    truncate to full global batches — drop_last semantics.)
    """
    global_batch = batch_size * process_count
    n_batches = len(indices) // global_batch
    rows = [indices[k * global_batch + process_index * batch_size:
                    k * global_batch + (process_index + 1) * batch_size]
            for k in range(n_batches)]
    if not rows:
        return indices[:0]
    return np.concatenate(rows)


def resolve_host_shard(indices: np.ndarray, process_index: int,
                       process_count: int, batch_size: int,
                       shard: str = "strided") -> np.ndarray:
    """Dispatch on the shard mode: ``"strided"`` (historical) or
    ``"batch"`` (contiguous per-global-batch slabs — the partitioned
    path).  ONE dispatch shared by the sync/pool paths and the shm
    ring, so the two transports can never disagree on which rows a
    host renders."""
    if shard not in ("strided", "batch"):
        raise ValueError(f"unknown host shard mode {shard!r}; "
                         "use 'strided' or 'batch'")
    fn = host_batch_shard if shard == "batch" else host_shard
    return fn(indices, process_index, process_count, batch_size)


def batches(dataset: CocoPoseDataset, batch_size: int, epoch: int,
            process_index: int = 0, process_count: int = 1,
            num_workers: int = 0, prefetch: int = 2, raw_gt: int = 0,
            pipeline: Optional[str] = None, wire: str = "f32",
            ring_slots: int = 0, shard: str = "strided"
            ) -> Iterator[Tuple[np.ndarray, ...]]:
    """Yield batched (images, mask_miss, labels) for one epoch.

    ``pipeline`` selects the worker transport (default: ``"shm"`` when
    ``num_workers > 0``, else ``"sync"``):

    - ``"sync"``  in-process sample generation (``num_workers`` ignored);
    - ``"shm"``   persistent spawn workers rendering into a
      ``multiprocessing.shared_memory`` slot ring (``data.shm_ring``) —
      only slot tokens cross process boundaries.  Yields READ-ONLY views
      valid until the generator advances; ``parallel.device_prefetch``
      places each batch before advancing.  This transient form spawns a
      ring per call; loops that run many epochs should hold a
      ``ShmRingInput`` and call its ``batches(epoch)`` instead;
    - ``"pool"``  the retired spawn-Pool path (one ``starmap_async``
      window, every sample pickled through the Pool pipe — measured 4-6x
      slower than sync at 512²; kept as an escape hatch / A-B reference).

    Spawn-based pipelines require an importable ``__main__`` — from a REPL
    or stdin script use ``num_workers=0``.

    ``prefetch`` batches are in flight in the pool ahead of the consumer
    (pool path only; the shm ring's depth is its slot count,
    ``ring_slots``, default ``num_workers + 2``).  Samples are
    deterministic in (seed, epoch, index), so no transport can change
    results: all three produce bit-identical streams on the same wire.

    ``raw_gt > 0``: yield (images, mask_miss, joints, mask_all) batches for
    on-device GT synthesis instead of host labels; the value is the
    ``max_people`` padding (``CocoPoseDataset.sample_raw``).

    ``wire="uint8"`` ships images as uint8 HWC — 4x fewer bytes across IPC
    and host->device — normalized to [0, 1] inside the jitted train step
    (bit-identical to the f32 wire; ``train.step``).

    ``shard`` selects the multi-host row assignment: ``"strided"`` (the
    historical ``host_shard``) or ``"batch"`` (``host_batch_shard`` —
    contiguous per-global-batch slabs, whose ``shard_batch`` assembly
    reconstructs the single-host global batch bit-identically; the
    partitioned-training path).
    """
    if pipeline is None:
        pipeline = "shm" if num_workers > 0 else "sync"
    if pipeline not in ("sync", "shm", "pool"):
        raise ValueError(f"unknown input pipeline {pipeline!r}; "
                         "use 'sync', 'shm' or 'pool'")
    if pipeline != "sync" and num_workers <= 0:
        pipeline = "sync"

    if pipeline == "shm":
        from .shm_ring import ShmRingInput

        ring = ShmRingInput(dataset, batch_size, num_workers, raw_gt=raw_gt,
                            wire=wire, slots=ring_slots)
        try:
            # copy out of the ring: this facade keeps batches()'s historical
            # contract (yielded arrays stay valid indefinitely, list() is
            # safe).  The zero-copy contract — views valid until advance —
            # is ShmRingInput.batches(), which the hot paths use directly.
            for batch in ring.batches(epoch, process_index, process_count,
                                      shard=shard):
                yield tuple(np.copy(x) for x in batch)
                batch = None  # drop the view before close() unmaps
        finally:
            ring.close()
        return

    perm = epoch_permutation(len(dataset), epoch, dataset.seed)
    shard = resolve_host_shard(perm, process_index, process_count,
                               batch_size, shard=shard)

    def gen(i):
        if raw_gt > 0:
            return dataset.sample_raw(int(i), epoch, max_people=raw_gt,
                                      wire=wire)
        return dataset.sample(int(i), epoch, wire=wire)

    def collate(samples):
        return tuple(np.stack(col) for col in zip(*samples))

    if pipeline == "sync":
        for start in range(0, len(shard), batch_size):
            idxs = shard[start: start + batch_size]
            yield collate([gen(i) for i in idxs])
        return

    import multiprocessing as mp
    from collections import deque

    # spawn, not fork: the parent is JAX-multithreaded and fork from a
    # multithreaded process is a deadlock hazard (py3.12 warns); workers
    # rebuild their state from pickled initargs anyway
    ctx = mp.get_context("spawn")
    with ctx.Pool(num_workers, initializer=_worker_init,
                  initargs=(dataset.h5_path, dataset.config, dataset.augment,
                            dataset.seed)) as pool:
        starts = iter(range(0, len(shard), batch_size))
        window: deque = deque()
        # one mode switch: worker fn and its extra args are selected together
        worker_fn, extra = ((_worker_sample_raw, (raw_gt,)) if raw_gt > 0
                            else (_worker_sample, ()))

        def submit() -> None:
            start = next(starts, None)
            if start is not None:
                idxs = [(int(i), epoch, wire, *extra)
                        for i in shard[start: start + batch_size]]
                window.append(pool.starmap_async(worker_fn, idxs))

        for _ in range(max(1, prefetch)):
            submit()
        while window:
            samples = window.popleft().get()
            submit()  # keep the window full before handing control back
            yield collate(samples)


_WORKER_DATASET: Optional[CocoPoseDataset] = None


def _worker_init(h5_path, config, augment, seed):
    global _WORKER_DATASET
    _WORKER_DATASET = CocoPoseDataset(h5_path, config, augment=augment,
                                      seed=seed)


def _worker_sample(index, epoch, wire):
    return _WORKER_DATASET.sample(index, epoch, wire=wire)


def _worker_sample_raw(index, epoch, wire, max_people):
    return _WORKER_DATASET.sample_raw(index, epoch, max_people=max_people,
                                      wire=wire)
