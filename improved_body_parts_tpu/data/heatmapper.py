"""Ground-truth heatmap synthesis on the stride-4 grid.

Host-side NumPy; semantics follow the reference heatmapper
(reference: py_cocodata_server/py_data_heatmapper.py) with the same
quantization-avoiding trick: Gaussians are evaluated at the *original-resolution
stride-center coordinates* ``arange(n)*stride + stride/2 - 0.5``
(py_data_heatmapper.py:40-48), never by downsampling a full-res map.

Differences from the reference (documented deviations):
- Output is channel-LAST (H, W, C) — the natural layout for NHWC TPU convs;
  the reference transposes to CHW for torch (py_data_heatmapper.py:97).
- Keypoint Gaussians are splatted with a single vectorized scatter-max over all
  (person, joint) instances instead of a Python loop per joint.

Channel layout (reference config/config.py:96-103): limbs [0, paf_layers),
keypoints [heat_start, bkg_start), eroded person mask at bkg_start, max of
keypoint channels at bkg_start+1.
"""
from __future__ import annotations

from math import ceil, log, sqrt
from typing import Tuple

import cv2
import numpy as np

from ..config import SkeletonConfig


class Heatmapper:
    def __init__(self, config: SkeletonConfig):
        self.config = config
        tp = config.transform_params
        self.sigma = tp.sigma
        self.paf_sigma = tp.paf_sigma
        self.double_sigma2 = 2.0 * self.sigma * self.sigma
        self.keypoint_thre = tp.keypoint_gaussian_thre
        self.limb_thre = tp.limb_gaussian_thre
        # Window half-extent so the tails below keypoint_thre are dropped
        # (reference: py_data_heatmapper.py:30).
        self.gaussian_size = ceil(
            sqrt(-self.double_sigma2 * log(self.keypoint_thre)) / config.stride) * 2
        self.paf_thre = config.paf_thre

        stride = config.stride
        h, w = config.grid_shape
        # Stride-center sample coordinates in original-resolution units.
        self.grid_x = (np.arange(w) * stride + stride / 2 - 0.5).astype(np.float32)
        self.grid_y = (np.arange(h) * stride + stride / 2 - 0.5).astype(np.float32)

    # ------------------------------------------------------------------ #
    def create_heatmaps(self, joints: np.ndarray, mask_all: np.ndarray
                        ) -> np.ndarray:
        """Build the full (H, W, num_layers) GT tensor.

        :param joints: (num_people, num_parts, 3) in original-resolution coords
            with visibility in col 2 (0 hidden / 1 visible / 2 absent — both
            0 and 1 count as annotated, reference: py_data_heatmapper.py:160).
        :param mask_all: (H, W) float in [0,1], person-area mask on the grid.
        """
        cfg = self.config
        heatmaps = np.zeros(cfg.parts_shape, dtype=np.float32)
        self.put_joints(heatmaps, joints)
        self.put_limbs(heatmaps, joints)

        # Person-mask background channel: eroded mask_all
        # (reference: py_data_heatmapper.py:73-76).
        kernel = np.ones((3, 3), np.uint8)
        heatmaps[:, :, cfg.bkg_start] = cv2.erode(mask_all, kernel)

        # Reverse-keypoint channel: max over all keypoint channels
        # (reference: py_data_heatmapper.py:78-80).
        sl = slice(cfg.heat_start, cfg.heat_start + cfg.heat_layers)
        heatmaps[:, :, cfg.bkg_start + 1] = np.amax(heatmaps[:, :, sl], axis=2)

        return np.clip(heatmaps, 0.0, 1.0)

    # ------------------------------------------------------------------ #
    def put_joints(self, heatmaps: np.ndarray, joints: np.ndarray) -> None:
        """Splat all keypoint Gaussians with one scatter-max per axis pass.

        Equivalent to the reference's per-joint windowed outer products
        (py_data_heatmapper.py:99-155): same windows, same stride-center
        evaluation, overlapping people combined by max, not mean.
        """
        assert heatmaps.flags["C_CONTIGUOUS"], (
            "put_joints scatters into heatmaps.reshape(-1), which must be a "
            "view; pass a C-contiguous array")
        cfg = self.config
        h, w = cfg.grid_shape
        g = self.gaussian_size // 2
        win = 2 * g + 1  # window is [c-g, c+g] inclusive

        vis = joints[:, :, 2] < 2  # annotated
        people_idx, part_idx = np.nonzero(vis)
        if people_idx.size == 0:
            return
        xs = joints[people_idx, part_idx, 0].astype(np.float32)
        ys = joints[people_idx, part_idx, 1].astype(np.float32)
        n = xs.shape[0]

        cx = np.round(xs / cfg.stride).astype(np.int64)
        cy = np.round(ys / cfg.stride).astype(np.int64)
        offs = np.arange(-g, g + 1, dtype=np.int64)
        ix = cx[:, None] + offs[None, :]           # (n, win)
        iy = cy[:, None] + offs[None, :]
        valid_x = (ix >= 0) & (ix < w)
        valid_y = (iy >= 0) & (iy < h)

        gx = self.grid_x[np.clip(ix, 0, w - 1)]
        gy = self.grid_y[np.clip(iy, 0, h - 1)]
        exp_x = np.exp(-((gx - xs[:, None]) ** 2) / self.double_sigma2)
        exp_y = np.exp(-((gy - ys[:, None]) ** 2) / self.double_sigma2)

        vals = exp_y[:, :, None] * exp_x[:, None, :]          # (n, win, win)
        valid = valid_y[:, :, None] & valid_x[:, None, :]

        chan = cfg.heat_start + part_idx                      # (n,)
        flat = ((iy[:, :, None] * w + ix[:, None, :]) * cfg.num_layers
                + chan[:, None, None])
        target = heatmaps.reshape(-1)
        np.maximum.at(target, flat[valid], vals[valid].astype(np.float32))

    # ------------------------------------------------------------------ #
    def put_limbs(self, heatmaps: np.ndarray, joints: np.ndarray) -> None:
        """Scalar body-part (limb) maps, count-averaged across instances
        (reference: py_data_heatmapper.py:163-240)."""
        cfg = self.config
        for i, (fr, to) in enumerate(cfg.limbs_conn):
            visible = (joints[:, fr, 2] < 2) & (joints[:, to, 2] < 2)
            layer = cfg.paf_start + i
            self._put_limb_channel(heatmaps, layer, joints[visible, fr, 0:2],
                                   joints[visible, to, 0:2])

    def _put_limb_channel(self, heatmaps: np.ndarray, layer: int,
                          joint_from: np.ndarray, joint_to: np.ndarray) -> None:
        cfg = self.config
        h, w = cfg.grid_shape
        count = np.zeros((h, w), dtype=np.float32)
        acc = heatmaps[:, :, layer]

        for (x1, y1), (x2, y2) in zip(joint_from, joint_to):
            dx, dy = x2 - x1, y2 - y1
            if dx * dx + dy * dy == 0:  # zero-length limb kills the NN; skip
                continue

            min_sx, max_sx = sorted((x1, x2))
            min_sy, max_sy = sorted((y1, y2))
            # include endpoints: pad bbox by paf_thre in original coords
            min_sx = int(round((min_sx - self.paf_thre) / cfg.stride))
            min_sy = int(round((min_sy - self.paf_thre) / cfg.stride))
            max_sx = int(round((max_sx + self.paf_thre) / cfg.stride))
            max_sy = int(round((max_sy + self.paf_thre) / cfg.stride))
            if max_sx < 0 or max_sy < 0:
                continue
            min_sx, min_sy = max(min_sx, 0), max(min_sy, 0)

            sx = slice(min_sx, max_sx + 1)
            sy = slice(min_sy, max_sy + 1)
            X = self.grid_x[sx][None, :]
            Y = self.grid_y[sy][:, None]
            resp = limb_response(X, Y, self.paf_sigma, x1, y1, x2, y2,
                                 self.limb_thre)
            acc[sy, sx] += resp
            count[sy, sx] += 1.0

        nz = count > 0  # average overlapping limb instances by count
        acc[nz] /= count[nz]


class OffsetMapper:
    """Sub-pixel offset ground truth (reference: py_data_heatmapper.py:242-299
    ``put_offset`` — dormant in the reference's final path, kept for the
    offset-regression experiments of posenet_final/config_final).

    All keypoints share one (x, y) offset channel pair; offsets are normalized
    by (offset_size * stride), averaged where windows overlap, and the mask
    marks touched cells.
    """

    def __init__(self, config: SkeletonConfig):
        self.config = config
        hm = Heatmapper(config)
        self.offset_size = hm.gaussian_size // 2 + 1
        self.grid_x = hm.grid_x
        self.grid_y = hm.grid_y

    def create_offsets(self, joints: np.ndarray
                       ) -> Tuple[np.ndarray, np.ndarray]:
        """(H, W, 2) offset vectors + (H, W, 2) mask, channel-last."""
        cfg = self.config
        h, w = cfg.grid_shape
        offsets = np.zeros((h, w, 2), dtype=np.float32)
        counts = np.zeros((h, w, 2), dtype=np.float32)
        g = self.offset_size // 2
        norm = self.offset_size * cfg.stride

        vis = joints[:, :, 2] < 2
        pi, ki = np.nonzero(vis)
        for x, y in zip(joints[pi, ki, 0], joints[pi, ki, 1]):
            cx = int(round(x / cfg.stride))
            cy = int(round(y / cfg.stride))
            x0, x1 = max(cx - g, 0), min(cx + g + 1, w)
            y0, y1 = max(cy - g, 0), min(cy + g + 1, h)
            if x1 <= 0 or y1 <= 0 or x0 >= w or y0 >= h:
                continue
            ox = (self.grid_x[x0:x1] - x) / norm
            oy = (self.grid_y[y0:y1] - y) / norm
            offsets[y0:y1, x0:x1, 0] += ox[None, :]
            offsets[y0:y1, x0:x1, 1] += oy[:, None]
            counts[y0:y1, x0:x1, :] += 1.0

        nz = counts > 0
        offsets[nz] /= counts[nz]
        mask = nz.astype(np.float32)
        return offsets, mask


def limb_response(X, Y, sigma, x1, y1, x2, y2, thresh=0.01):
    """Gaussian of point-to-segment-line distance (the scalar 'PAF')
    (reference: py_data_heatmapper.py:309-340 ``distances``).

    Responses at or below ``thresh`` are set to 0.01, matching the reference's
    floor (py_data_heatmapper.py:336) — the floor marks 'this window was
    touched' for the count-averaging step.
    """
    xD, yD = x2 - x1, y2 - y1
    norm = sqrt(xD * xD + yD * yD)
    dist = np.abs((xD * (y1 - Y) - (x1 - X) * yD) / (norm + 1e-6))
    resp = np.exp(-(dist ** 2) / (2.0 * sigma * sigma)).astype(np.float32)
    resp[resp <= thresh] = 0.01
    return resp
