from .dataset import (
    CocoPoseDataset,
    batches,
    convert_joints,
    epoch_permutation,
    host_batch_shard,
    host_shard,
    resolve_host_shard,
)
from .fixture import (build_coco_train_set, build_fixture,
                      build_val_set, draw_person)
from .heatmapper import Heatmapper, OffsetMapper
from .shm_ring import ShmRingInput, batch_wire_format
from .transformer import AugmentParams, Transformer

__all__ = [
    "CocoPoseDataset", "ShmRingInput", "batch_wire_format", "batches",
    "convert_joints", "epoch_permutation",
    "host_batch_shard", "host_shard", "resolve_host_shard",
    "build_fixture", "build_coco_train_set", "build_val_set", "draw_person", "Heatmapper", "OffsetMapper", "AugmentParams",
    "Transformer",
]
