"""Zero-copy shared-memory input pipeline: persistent workers + a slot ring.

Replaces the spawn-Pool worker path (retired; kept behind
``batches(pipeline="pool")``) whose per-sample cost was dominated by
IPC bytes, not CPU: every sample crossed the Pool pipe as ~6 MB of pickled
fp32 arrays, so workers=1 ran 4-6x SLOWER than synchronous
(INPUT_PIPELINE.json, PR 1 era).  Here the only things that ever cross a
queue are slot tokens and index lists:

- one ``multiprocessing.shared_memory`` block holds ``slots`` preallocated
  batch slots (images / mask_miss / labels-or-joints arrays at fixed batch
  shape) plus a small int64 seqlock header per slot;
- persistent spawn workers (one ``CocoPoseDataset`` each, same
  ``(seed, epoch, index)`` RNG scheme as the synchronous path) receive
  ``(generation, seq, epoch, batch_idx, slot, indices)`` tasks and render
  each sample IN PLACE into the slot's rows — ``cv2.warpAffine`` writes
  the uint8 image directly into shared memory (``image_out``),
  labels/joints are one row assignment.  No pickling, no copy on collate;
- the consumer reassembles completions in strict task (``seq``) order
  (the determinism contract: the sample stream is bit-identical to the
  synchronous path for any worker count), yields read-only views into the
  slot, and hands the slot token back when the caller advances the
  generator — by which point ``parallel.prefetch`` has already placed the
  batch on device (``shard_batch`` copies; verified non-aliasing).
  ``batches(epoch)`` runs one epoch; ``stream()`` pipelines tasks across
  epoch boundaries (no drain bubble between epochs).

Slot-granularity seqlock: each slot's header carries
``[seq, epoch, batch_idx]``; the worker bumps ``seq`` to odd before
writing and to even after, and the consumer verifies ``seq`` is even and
``(epoch, batch_idx)`` match before yielding — a cheap tripwire that turns
any ownership-protocol violation (a worker writing a slot the consumer
still holds) into a hard error instead of silently corrupted samples.

Wire format: images cross IPC — and, untouched, the host->device hop — as
uint8 HWC (4x smaller than fp32); normalization to [0, 1] happens inside
the jitted train step (``train.step``), bit-identical to the host's
``astype(float32) / 255``.
"""
from __future__ import annotations

import os
import queue
import time
import traceback
import weakref
from typing import Iterator, List, Optional, Tuple

import numpy as np

_HEADER_INTS = 3  # per-slot seqlock header: [seq, epoch, batch_idx]
_ALIGN = 64


class WorkerDied(RuntimeError):
    """An input worker process died while the consumer waited.

    Fatal by default (the historical contract: fail loudly, never hang);
    under ``ShmRingInput(supervise=True)`` the consumer catches it and
    rebuilds the ring instead — see :meth:`ShmRingInput._rebuild`."""


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


def batch_wire_format(config, batch_size: int, raw_gt: int = 0,
                      wire: str = "uint8"
                      ) -> Tuple[Tuple[str, ...], Tuple[Tuple[int, ...], ...],
                                 Tuple[str, ...]]:
    """(names, shapes, dtypes) of one batch slot.

    ``wire="uint8"`` ships images as uint8 HWC (the default wire; 4x fewer
    bytes both across IPC and host->device), ``"f32"`` as float32 in [0, 1]
    (the legacy format).  Masks and labels/joints are float32 either way —
    in device-GT mode (``raw_gt > 0``) the slot carries only padded joints
    + masks, as the synchronous path does.
    """
    if wire not in ("uint8", "f32"):
        raise ValueError(f"unknown wire format {wire!r}; use 'uint8' or 'f32'")
    sk = config.skeleton
    gh, gw = sk.grid_shape
    names = ["images", "mask_miss"]
    shapes = [(batch_size, sk.height, sk.width, 3), (batch_size, gh, gw, 1)]
    dtypes = ["uint8" if wire == "uint8" else "float32", "float32"]
    if raw_gt > 0:
        names += ["joints", "mask_all"]
        shapes += [(batch_size, raw_gt, sk.num_parts, 3),
                   (batch_size, gh, gw, 1)]
        dtypes += ["float32", "float32"]
    else:
        names += ["labels"]
        shapes += [(batch_size, gh, gw, sk.num_layers)]
        dtypes += ["float32"]
    return tuple(names), tuple(shapes), tuple(dtypes)


def _slot_layout(shapes, dtypes) -> Tuple[List[int], int]:
    """Field byte offsets within one slot + the aligned slot size."""
    offsets, off = [], 0
    for shape, dtype in zip(shapes, dtypes):
        offsets.append(off)
        off += _align(int(np.prod(shape)) * np.dtype(dtype).itemsize)
    return offsets, off


def _attach_shm(name: str):
    """Attach to an existing block without registering it with the (shared)
    resource_tracker daemon — the consumer owns the block's lifetime, and a
    worker-side registration would make the tracker double-unlink it at
    exit (py3.10 has no ``track=False`` yet)."""
    from multiprocessing import resource_tracker, shared_memory

    orig = resource_tracker.register
    resource_tracker.register = lambda *a, **kw: None
    try:
        return shared_memory.SharedMemory(name=name)
    finally:
        resource_tracker.register = orig


def _quiet_close(shm) -> None:
    """Close a SharedMemory mapping, tolerating live buffer exports.

    A view yielded to a consumer (or still referenced by a worker frame)
    makes ``mmap.close()`` raise BufferError; the mapping is reclaimed by
    the OS at process exit regardless, so detach the handles to keep
    ``SharedMemory.__del__`` from retrying and spamming stderr."""
    try:
        shm.close()
    except BufferError:
        shm._mmap = None  # noqa: SLF001 — freed when the last view dies
        shm._buf = None   # noqa: SLF001


def _slot_views(buf, slots: int, shapes, dtypes, writeable: bool):
    """header array + per-slot field views into ``buf``."""
    offsets, slot_bytes = _slot_layout(shapes, dtypes)
    header_bytes = _align(slots * _HEADER_INTS * 8)
    header = np.frombuffer(buf, np.int64, slots * _HEADER_INTS
                           ).reshape(slots, _HEADER_INTS)
    header.flags.writeable = writeable
    views = []
    for s in range(slots):
        base = header_bytes + s * slot_bytes
        fields = []
        for shape, dtype, off in zip(shapes, dtypes, offsets):
            v = np.frombuffer(buf, np.dtype(dtype), int(np.prod(shape)),
                              offset=base + off).reshape(shape)
            v.flags.writeable = writeable
            fields.append(v)
        views.append(tuple(fields))
    return header, views


def _ring_worker(worker_id: int, shm_name: str, slots: int, shapes, dtypes,
                 h5_path: str, config, augment: bool, seed: int, raw_gt: int,
                 wire: str, task_q, done_q, parent_pid: int = 0) -> None:
    """Persistent worker entry (spawn target — module importable, no JAX).

    Renders each task's samples directly into the slot's shared-memory
    rows under the slot seqlock; only ``("ok"|"err", generation, seq,
    (slot, worker_id, render_seconds, render_start_monotonic)-or-
    (slot, traceback))`` tokens travel back — the render time AND its
    absolute ``time.monotonic()`` start stamp ride along so the consumer
    can export per-worker render histograms and place each render as a
    span on the run's trace timeline (CLOCK_MONOTONIC is system-wide, so
    a worker-process stamp lands correctly among consumer-side spans)
    without a second IPC channel.
    """
    try:
        try:
            import cv2
            cv2.setNumThreads(0)  # one core per worker; no nested pools
        except Exception:  # noqa: BLE001 — determinism aid only
            pass
        try:
            # deprioritize slightly: when workers oversubscribe the host's
            # cores, the consumer's placement/handback is the critical
            # path — starving it stalls the whole ring
            os.nice(2)
        except OSError:
            pass
        shm = _attach_shm(shm_name)
    except BaseException:  # noqa: BLE001 — surfaced by start()
        done_q.put(("init_err", worker_id, -1, traceback.format_exc()))
        return
    try:
        # all numpy views over the mapping live in _worker_loop's frame,
        # so they are released before the close below
        _worker_loop(worker_id, shm, slots, shapes, dtypes, h5_path, config,
                     augment, seed, raw_gt, wire, task_q, done_q,
                     parent_pid)
    finally:
        _quiet_close(shm)


def _worker_loop(worker_id: int, shm, slots: int, shapes, dtypes,
                 h5_path: str, config, augment: bool, seed: int, raw_gt: int,
                 wire: str, task_q, done_q, parent_pid: int = 0) -> None:
    try:
        from .dataset import CocoPoseDataset

        header, views = _slot_views(shm.buf, slots, shapes, dtypes,
                                    writeable=True)
        ds = CocoPoseDataset(h5_path, config, augment=augment, seed=seed)
        done_q.put(("ready", worker_id, -1, -1))
    except BaseException:  # noqa: BLE001 — surfaced by start()
        done_q.put(("init_err", worker_id, -1, traceback.format_exc()))
        return
    try:
        while True:
            try:
                task = task_q.get(timeout=2.0)
            except queue.Empty:
                # orphan watchdog: a SIGKILLed consumer (preemption,
                # OOM-killer, the chaos harness) runs no cleanup and
                # never sends the poison pill — daemon=True only helps
                # on orderly interpreter exit.  A reparented worker
                # would otherwise block on this queue forever, which is
                # exactly the "leaked ring workers" the chaos harness
                # asserts against.
                if parent_pid and os.getppid() != parent_pid:
                    return
                continue
            if task is None:
                return
            gen, seq, epoch, batch_idx, slot, idxs = task
            try:
                # monotonic, not perf_counter: the stamp crosses the
                # process boundary and must share the consumer's clock
                t_render = time.monotonic()
                header[slot, 0] += 1  # odd: write in progress
                fields = views[slot]
                for row, index in enumerate(idxs):
                    # bind the row view ONCE: indexing creates a fresh view
                    # object per evaluation, so an inline
                    # `img is not fields[0][row]` would always be true and
                    # re-copy the already-in-place image onto itself
                    img_row = fields[0][row]
                    if raw_gt > 0:
                        img, mm, joints, mask_all = ds.sample_raw(
                            index, epoch, max_people=raw_gt, wire=wire,
                            image_out=img_row)
                        if img is not img_row:
                            img_row[...] = img
                        fields[1][row] = mm
                        fields[2][row] = joints
                        fields[3][row] = mask_all
                    else:
                        img, mm, labels = ds.sample(
                            index, epoch, wire=wire, image_out=img_row)
                        if img is not img_row:
                            img_row[...] = img
                        fields[1][row] = mm
                        fields[2][row] = labels
                header[slot, 1] = epoch
                header[slot, 2] = batch_idx
                header[slot, 0] += 1  # even: slot consistent
                done_q.put(("ok", gen, seq,
                            (slot, worker_id,
                             time.monotonic() - t_render, t_render)))
            except Exception:  # noqa: BLE001 — consumer re-raises
                if header[slot, 0] % 2:
                    # restore seqlock parity: the slot is reclaimed after
                    # an error, and a stuck-odd seq would make its next
                    # (correct) use trip _check_header spuriously
                    header[slot, 0] += 1
                done_q.put(("err", gen, seq,
                            (slot, traceback.format_exc())))
    finally:
        ds.close()


class ShmRingInput:
    """Persistent shared-memory ring pipeline over one dataset.

    Construct once (workers spawn, corpus opens, ~seconds) and reuse across
    epochs — ``batches(epoch)`` is a per-epoch generator with the exact
    ``data.batches`` yield contract.  Yielded arrays are READ-ONLY views
    into the ring: they are valid until the generator is advanced (or
    closed); place them on device or copy before the next ``next()``.
    ``parallel.device_prefetch`` honours this contract (it places each
    batch via ``shard_batch`` before advancing the source iterator).
    """

    def __init__(self, dataset, batch_size: int, num_workers: int,
                 raw_gt: int = 0, wire: str = "uint8", slots: int = 0,
                 start_timeout: float = 120.0, supervise: bool = False,
                 max_rebuilds: int = 3):
        if num_workers < 1:
            raise ValueError("ShmRingInput needs num_workers >= 1; use the "
                             "synchronous path for in-process loading")
        import multiprocessing as mp
        from multiprocessing import shared_memory

        self.dataset = dataset
        self.batch_size = batch_size
        self.num_workers = num_workers
        self.raw_gt = raw_gt
        self.wire = wire
        # supervise=True: a dead worker triggers a ring REBUILD (all
        # workers respawned, lost tasks re-rendered, the stream resumes
        # bit-identically) instead of the fatal WorkerDied — the elastic
        # training mode (tools/train.py --supervised).  max_rebuilds
        # bounds CONSECUTIVE rebuilds with no yielded batch in between,
        # so a deterministically-crashing worker cannot respawn forever.
        self.supervise = bool(supervise)
        self.max_rebuilds = int(max_rebuilds)
        self._consecutive_rebuilds = 0
        self.rebuilds_total = 0
        self.slots = slots if slots > 0 else num_workers + 2
        self.names, self.shapes, self.dtypes = batch_wire_format(
            dataset.config, batch_size, raw_gt=raw_gt, wire=wire)
        _, slot_bytes = _slot_layout(self.shapes, self.dtypes)
        total = _align(self.slots * _HEADER_INTS * 8) + self.slots * slot_bytes

        # spawn, not fork: the parent is JAX-multithreaded and fork from a
        # multithreaded process is a deadlock hazard (same rationale as the
        # retired Pool path); the ring module imports no JAX so worker
        # start-up is cheap and happens ONCE, not per epoch
        ctx = mp.get_context("spawn")
        self._ctx = ctx
        self._start_timeout = float(start_timeout)
        self._shm = shared_memory.SharedMemory(create=True, size=total)
        # pre-fault the whole block now: otherwise every slot's first use
        # pays its page faults inside the training (or benchmark) window
        np.frombuffer(self._shm.buf, np.uint8).fill(0)
        self._header, self._views = _slot_views(
            self._shm.buf, self.slots, self.shapes, self.dtypes,
            writeable=False)
        self._task_q = ctx.Queue()
        self._done_q = ctx.Queue()
        self._procs = [self._make_worker(i) for i in range(num_workers)]
        self._free: List[int] = list(range(self.slots))
        self._gen = 0
        self._closed = False
        # mutable holder so the finalizer tracks the CURRENT task queue
        # across supervised rebuilds (which replace both queues)
        self._qholder = [self._task_q]
        self._tele = None          # obs.Registry, via attach_telemetry
        self._tele_prefix = "input_ring"
        self._render_hists = {}    # worker_id -> Histogram
        self._rebuilds_counter = None
        self._finalizer = weakref.finalize(self, ShmRingInput._cleanup,
                                           self._procs, self._qholder,
                                           self._shm)
        try:
            for p in self._procs:
                p.start()
            self._wait_ready(start_timeout)
        except BaseException:
            self.close()
            raise

    # -- lifecycle ---------------------------------------------------------

    def _make_worker(self, worker_id: int):
        """One (unstarted) worker process — shared by the initial spawn
        and the supervised rebuild's respawn."""
        ds = self.dataset
        return self._ctx.Process(
            target=_ring_worker, daemon=True,
            name=f"shm-ring-worker-{worker_id}",
            args=(worker_id, self._shm.name, self.slots, self.shapes,
                  self.dtypes, ds.h5_path, ds.config, ds.augment, ds.seed,
                  self.raw_gt, self.wire, self._task_q, self._done_q,
                  os.getpid()))

    def _wait_ready(self, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        ready = 0
        while ready < self.num_workers:
            msg = self._next_done(deadline=deadline,
                                  what="worker start-up")
            if msg[0] == "ready":
                ready += 1
            elif msg[0] == "init_err":
                raise RuntimeError(
                    f"input worker {msg[1]} failed to start:\n{msg[3]}")
            # no epoch tasks can be outstanding yet

    @staticmethod
    def _cleanup(procs, qholder, shm) -> None:
        task_q = qholder[0]
        for _ in procs:
            try:
                task_q.put_nowait(None)
            except Exception:  # noqa: BLE001
                pass
        for p in procs:
            p.join(timeout=5.0)
            if p.is_alive():
                p.terminate()
                p.join(timeout=5.0)
        try:  # unlink FIRST: close() raises while yielded views are alive,
            shm.unlink()  # and the name must not outlive the pipeline
        except Exception:  # noqa: BLE001 — already unlinked
            pass
        _quiet_close(shm)

    def attach_telemetry(self, registry,
                         prefix: str = "input_ring") -> "ShmRingInput":
        """Export the ring's internals through an ``obs.Registry``:

        - ``<prefix>_slots_total`` / ``<prefix>_free_slots`` — ring
          capacity and live free-slot count (a persistently-zero free
          count means the consumer is the bottleneck, a persistently-full
          one means the workers are);
        - ``<prefix>_consumer_stall_seconds_total`` / ``_stalls_total``
          — time the consumer blocked waiting for a completion with no
          batch ready to yield (the ring-side twin of the train loop's
          data-wait counter);
        - ``<prefix>_render_seconds{worker=N}`` — per-worker render-time
          histograms (a straggler worker shows up as one shifted
          distribution, not a mystery in the aggregate);
        - ``<prefix>_batches_total`` — batches yielded.
        """
        self._tele = registry
        self._tele_prefix = prefix
        registry.gauge(prefix + "_slots_total", "ring capacity "
                       "(batch slots)").set(self.slots)
        # weakref: the registry (often process-global) outlives the
        # ring, and a closure over self would pin the closed ring for
        # process lifetime; a dead ring scrapes as 0
        ref = weakref.ref(self)

        def _free_slots():
            ring = ref()
            return len(ring._free) if ring is not None else 0

        registry.gauge(prefix + "_free_slots",
                       "slots not owned by a worker or in-flight batch",
                       fn=_free_slots)
        self._stall_s = registry.counter(
            prefix + "_consumer_stall_seconds_total",
            "consumer time blocked on the done queue")
        self._stalls = registry.counter(prefix + "_consumer_stalls_total")
        self._batches_total = registry.counter(prefix + "_batches_total")
        self._rebuilds_counter = registry.counter(
            prefix + "_rebuilds_total",
            "supervised ring rebuilds after a worker death")
        return self

    def _observe_render(self, worker_id: int, render_s: float) -> None:
        h = self._render_hists.get(worker_id)
        if h is None:
            h = self._tele.histogram(
                self._tele_prefix + "_render_seconds",
                "per-worker batch render time",
                labels={"worker": str(worker_id)})
            self._render_hists[worker_id] = h
        h.observe(render_s)

    def close(self) -> None:
        """Stop workers and release the shared-memory block (idempotent)."""
        if self._closed:
            return
        self._closed = True
        # drop our own buffer exports so the finalizer's close() can
        # actually unmap (yielded views held by callers are tolerated)
        self._header = self._views = None
        self._finalizer()

    def __enter__(self) -> "ShmRingInput":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- the per-epoch generator ------------------------------------------

    def _next_done(self, deadline: Optional[float] = None,
                   what: str = "the next batch"):
        """One message off the done queue, surfacing dead workers as a
        raised error instead of an indefinite hang."""
        while True:
            try:
                return self._done_q.get(timeout=0.5)
            except queue.Empty:
                dead = [p for p in self._procs if not p.is_alive()]
                if dead:
                    codes = ", ".join(
                        f"{p.name} exitcode={p.exitcode}" for p in dead)
                    raise WorkerDied(
                        f"input worker died while the consumer waited for "
                        f"{what} ({codes}); the sample it was rendering is "
                        "lost — restart the pipeline") from None
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(f"timed out waiting for {what}")

    def _check_header(self, slot: int, epoch: int, batch_idx: int) -> None:
        seq, h_epoch, h_idx = (int(self._header[slot, 0]),
                               int(self._header[slot, 1]),
                               int(self._header[slot, 2]))
        if seq % 2 or (h_epoch, h_idx) != (epoch, batch_idx):
            raise RuntimeError(
                f"ring-slot protocol violation: slot {slot} header "
                f"(seq={seq}, epoch={h_epoch}, batch={h_idx}) does not match "
                f"the completed task (epoch={epoch}, batch={batch_idx})")

    def _rebuild(self, meta, completed, gen: int, why: str) -> None:
        """Supervised recovery from a dead worker: rebuild the whole ring
        in place and re-render the lost tasks.

        Stop-the-world by design — partial recovery (respawn only the
        dead worker) would leave live workers mid-render on slots whose
        ownership the consumer can no longer prove, and the seqlock can
        only detect that corruption, not prevent it.  Sequence:

        1. terminate + join EVERY worker (after this, nothing writes the
           shared block);
        2. drain the done queue — completions that landed before the
           stop are valid rendered batches and are kept;
        3. drain the task queue — tasks nobody picked up would otherwise
           be rendered twice after resubmission;
        4. rebuild the free-slot list from first principles: every slot
           not held by a kept completion is free (the dead worker's slot
           comes back here);
        5. respawn all workers and resubmit the lost tasks under the
           SAME seq numbers — the in-order yield logic never notices the
           failure, so the stream stays bit-identical to the synchronous
           path.

        Consecutive rebuilds with no yielded batch in between are
        bounded by ``max_rebuilds`` — a worker that dies
        deterministically on the same sample must surface as an error,
        not an infinite respawn loop.
        """
        self._consecutive_rebuilds += 1
        self.rebuilds_total += 1
        if self._consecutive_rebuilds > self.max_rebuilds:
            raise RuntimeError(
                f"input ring rebuilt {self._consecutive_rebuilds - 1} "
                "consecutive times without yielding a batch "
                f"(max_rebuilds={self.max_rebuilds}); the worker failure "
                f"looks deterministic — last: {why}")
        t0 = time.perf_counter()
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            p.join(timeout=10.0)
            if p.is_alive():
                p.kill()
                p.join(timeout=10.0)
        # drain completions that raced the stop: current-generation "ok"
        # tokens are finished batches (the data is in the slot and no
        # worker can touch it now); anything else is reclaimed by the
        # free-list rebuild below.  A killed writer can leave a torn
        # pickle in the pipe — tolerated, the batch is simply re-counted
        # as lost.
        while True:
            try:
                kind, g, seq, payload = self._done_q.get(timeout=0.2)
            except queue.Empty:
                break
            except Exception:  # noqa: BLE001 — torn write from the kill
                continue
            if kind == "ok" and g == gen and seq in meta:
                completed[seq] = payload
        # REPLACE both queues instead of reusing them: a worker killed
        # mid-``get``/mid-``put`` dies holding the queue's shared lock,
        # and every later operation on that queue (the respawned
        # workers' get, their ready handshake) deadlocks forever — the
        # documented terminate-vs-Queue hazard.  Replacing also discards
        # any unpicked tasks still buffered in the old feeder thread, so
        # a resubmitted task can never be rendered twice.
        old_task_q, old_done_q = self._task_q, self._done_q
        self._task_q = self._ctx.Queue()
        self._done_q = self._ctx.Queue()
        self._qholder[0] = self._task_q
        for q in (old_task_q, old_done_q):
            try:
                q.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        held = {payload[0] for payload in completed.values()}
        self._free = [s for s in range(self.slots) if s not in held]
        # restore seqlock parity on every reclaimed slot: a worker
        # SIGKILLed MID-WRITE leaves its slot's seq odd, and the
        # respawned worker's parity-based bumps would then publish the
        # re-rendered batch under an odd (apparently in-progress) seq —
        # tripping _check_header on a perfectly good batch.  Every
        # worker is dead here, so the consumer owns the block
        # exclusively and the direct fix is race-free.
        fix = np.frombuffer(self._shm.buf, np.int64,
                            self.slots * _HEADER_INTS
                            ).reshape(self.slots, _HEADER_INTS)
        for s in self._free:
            if fix[s, 0] % 2:
                fix[s, 0] += 1
        del fix  # release the buffer export before any later close()
        lost = sorted(seq for seq in meta if seq not in completed)
        for i in range(self.num_workers):
            self._procs[i] = self._make_worker(i)
            self._procs[i].start()
        self._wait_ready(self._start_timeout)
        for seq in lost:
            epoch, batch_idx, idxs, _ = meta[seq]
            slot = self._free.pop()
            meta[seq] = (epoch, batch_idx, idxs, slot)
            self._task_q.put((gen, seq, epoch, batch_idx, slot, idxs))
        dt = time.perf_counter() - t0
        if self._tele is not None and self._rebuilds_counter is not None:
            self._rebuilds_counter.inc()
        from ..obs.events import get_sink

        get_sink().emit("ring_rebuild", reason=why[:500],
                        lost_tasks=len(lost), kept_completions=len(held),
                        rebuild_s=round(dt, 3),
                        consecutive=self._consecutive_rebuilds)

    def _epoch_tasks(self, epoch: int, process_index: int,
                     process_count: int, shard: str = "strided"):
        """(epoch, batch_idx, indices) task triples for one epoch — the
        same permutation/shard/batching as the synchronous path
        (``shard`` dispatches through the one ``resolve_host_shard``
        the sync path uses, so the transports cannot disagree)."""
        from .dataset import epoch_permutation, resolve_host_shard

        perm = epoch_permutation(len(self.dataset), epoch, self.dataset.seed)
        rows = resolve_host_shard(perm, process_index, process_count,
                                  self.batch_size, shard=shard)
        for batch_idx, s in enumerate(range(0, len(rows), self.batch_size)):
            yield epoch, batch_idx, [int(i) for i in
                                     rows[s: s + self.batch_size]]

    def batches(self, epoch: int, process_index: int = 0,
                process_count: int = 1, shard: str = "strided"
                ) -> Iterator[Tuple[np.ndarray, ...]]:
        """Yield this host's batches for ``epoch`` in deterministic order.

        Identical stream to ``data.batches(..., num_workers=0)`` on the
        same wire format: same epoch permutation, same host shard, same
        per-sample ``(seed, epoch, index)`` RNG, yields in batch order.
        ``shard="batch"`` selects the contiguous per-global-batch slab
        assignment (``data.dataset.host_batch_shard``) whose multi-host
        assembly reconstructs the single-host global batch bit-identically
        — the partitioned-training feed.  Worker failures raise (with the
        worker traceback) — except a *dead* worker under
        ``supervise=True``, which triggers a ring rebuild
        (:meth:`_rebuild`) and the stream continues, still bit-identical.
        An abandoned generator leaves in-flight slots to be reclaimed
        lazily by the next generator.
        """
        return self._run(self._epoch_tasks(epoch, process_index,
                                           process_count, shard))

    def stream(self, start_epoch: int = 0, process_index: int = 0,
               process_count: int = 1, shard: str = "strided"
               ) -> Iterator[Tuple[np.ndarray, ...]]:
        """Endless multi-epoch batch stream, pipelined ACROSS epoch
        boundaries: epoch N+1 tasks enter the ring while N's last batches
        drain, so workers never idle at the boundary.  Same per-epoch
        stream as ``batches(N)`` concatenated in epoch order.  Use where
        the consumer has no epoch-boundary work (throughput benchmarks,
        pure-feed deployments); per-epoch loops (checkpointing, eval)
        want ``batches(epoch)``.
        """
        def endless():
            epoch = start_epoch
            while True:
                yield from self._epoch_tasks(epoch, process_index,
                                             process_count, shard)
                epoch += 1

        return self._run(endless())

    def _run(self, task_iter) -> Iterator[Tuple[np.ndarray, ...]]:
        """Drive the ring over ``task_iter`` of (epoch, batch_idx,
        indices), yielding in task order (slot-count batches in flight)."""
        if self._closed:
            raise RuntimeError("ShmRingInput is closed")
        # consumer-side import (workers import this module too and must
        # stay lean); the process tracer is installed by RunTelemetry
        from ..obs.trace import get_tracer

        trace = get_tracer()
        self._gen += 1
        gen = self._gen
        pending = iter(task_iter)
        # seq -> (epoch, batch_idx, indices, slot): everything needed to
        # RE-render a task whose worker died (the supervised rebuild)
        meta = {}
        completed = {}  # seq -> (slot, worker_id, render_s, t_start_mono)
        next_submit = 0
        next_yield = 0
        exhausted = False

        def submit() -> bool:
            nonlocal next_submit, exhausted
            if exhausted or not self._free:
                return False
            task = next(pending, None)
            if task is None:
                exhausted = True
                return False
            epoch, batch_idx, idxs = task
            slot = self._free.pop()
            meta[next_submit] = (epoch, batch_idx, idxs, slot)
            self._task_q.put((gen, next_submit, epoch, batch_idx, slot, idxs))
            next_submit += 1
            return True

        try:
            while True:
                while submit():
                    pass
                while next_yield in completed:
                    slot, wid, render_s, t_start = completed.pop(next_yield)
                    epoch, batch_idx, _, _ = meta.pop(next_yield)
                    self._check_header(slot, epoch, batch_idx)
                    if trace.enabled:
                        # the worker's absolute monotonic start stamp
                        # places its render among consumer-side spans
                        trace.add_span_abs(
                            "render", t_start, render_s,
                            track=f"ring-worker-{wid}",
                            args={"slot": slot, "epoch": epoch,
                                  "batch": batch_idx})
                    if self._tele is not None:
                        self._observe_render(wid, render_s)
                        self._batches_total.inc()
                    try:
                        yield self._views[slot]
                    finally:
                        # the caller advanced (batch on device / copied) —
                        # or closed the generator, which raises
                        # GeneratorExit AT the yield: hand the slot token
                        # back on BOTH paths, or every abandoned generator
                        # leaks the slot it was yielding and the ring
                        # eventually starves
                        self._free.append(slot)
                    next_yield += 1
                    self._consecutive_rebuilds = 0  # real progress
                    submit()
                if exhausted and next_yield >= next_submit:
                    return
                t_stall = time.perf_counter() if self._tele is not None \
                    else 0.0
                try:
                    kind, g, seq, payload = self._next_done(
                        what=f"batch "
                             f"{meta.get(next_yield, ('?', '?'))[1]} of "
                             f"epoch {meta.get(next_yield, ('?', '?'))[0]}")
                except WorkerDied as e:
                    if not self.supervise:
                        raise
                    self._rebuild(meta, completed, gen, str(e))
                    continue
                if self._tele is not None:
                    # blocked with nothing ready to yield: the workers
                    # (or the slot budget) are behind the consumer
                    self._stall_s.inc(time.perf_counter() - t_stall)
                    self._stalls.inc()
                if g != gen:  # stale completion (or stale failure) from an
                    # abandoned generator: reclaim the slot, don't let an
                    # old epoch's error poison this one
                    self._free.append(payload[0])
                    continue
                if kind == "err":
                    slot, tb = payload
                    self._free.append(slot)
                    epoch, batch_idx = meta.pop(seq, ("?", "?", 0, 0))[:2]
                    raise RuntimeError(
                        f"input worker failed on batch {batch_idx} of epoch "
                        f"{epoch}:\n{tb}")
                completed[seq] = payload
        finally:
            # completions already drained off done_q but not yet yielded
            # have no token left anywhere — with multiple workers batch
            # n+1 routinely finishes before batch n, so abandoning at the
            # yield for n would otherwise leak n+1's slot permanently
            self._free.extend(slot for slot, *_ in completed.values())
            completed.clear()
