"""Dependency-free COCO segmentation-mask decoding.

The reference corpus builder decodes every annotation's segmentation with
pycocotools (reference: data/coco_masks_hdf5.py:6,52-76 ``annToMask``),
which made the full COCO-format user journey (JSON+images → HDF5 → train
→ evaluate) impossible in environments without that Cython package.  This
module implements all three COCO segmentation encodings in NumPy/OpenCV:

- **uncompressed RLE** — ``{"counts": [int, ...], "size": [h, w]}``,
  column-major alternating background/foreground run lengths;
- **compressed RLE** — ``counts`` as an ASCII string: pycocotools'
  5-bits-per-char LEB128 variant with difference coding of every count
  after the third against the count two positions back (the exact
  algorithm of pycocotools ``rleFrString`` — byte-for-byte compatible,
  verified by an encode→decode roundtrip test and golden strings);
- **polygons** — ``[[x0, y0, x1, y1, ...], ...]`` rasterized with
  ``cv2.fillPoly``.

pycocotools is deliberately NOT used even when importable: its polygon
rasterizer (``rleFrPoly``, 5× upsampled boundary walk) differs from
``cv2.fillPoly`` by boundary pixels, so an "optional fast path" would
make corpus content depend on the build environment.  Pure NumPy keeps
corpora bit-identical everywhere; RLE decoding (both kinds) is exact, and
the polygon boundary deviation (≤1 px, documented in PARITY.md) is far
below the 8×-downsampled resolution at which masks enter the loss
(reference: loss_model.py:52-56).
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Union

import cv2
import numpy as np


def rle_decode(counts: Sequence[int], h: int, w: int) -> np.ndarray:
    """Uncompressed-RLE → (h, w) uint8 {0,1} mask.

    Runs are column-major (Fortran order) and start with background, per
    the COCO spec (pycocotools ``rleDecode``).
    """
    counts = np.asarray(counts, dtype=np.int64)
    if counts.sum() != h * w:
        raise ValueError(
            f"RLE runs sum to {int(counts.sum())}, expected h*w={h * w}")
    vals = np.zeros(len(counts), np.uint8)
    vals[1::2] = 1
    return np.repeat(vals, counts).reshape((h, w), order="F")


def rle_from_string(s: Union[str, bytes]) -> List[int]:
    """Compressed-RLE counts string → list of run lengths.

    Implements pycocotools ``rleFrString``: 5 data bits per character
    (ASCII offset 48), bit 0x20 = continuation, sign-extension via bit
    0x10 of the final character, and counts[i] for i ≥ 3 stored as a
    difference against counts[i-2].
    """
    if isinstance(s, bytes):
        s = s.decode("ascii")
    cnts: List[int] = []
    p = 0
    while p < len(s):
        x, k, more = 0, 0, True
        while more:
            c = ord(s[p]) - 48
            x |= (c & 0x1F) << (5 * k)
            more = bool(c & 0x20)
            p += 1
            k += 1
            if not more and (c & 0x10):
                x |= -1 << (5 * k)
        if len(cnts) > 2:
            x += cnts[-2]
        cnts.append(x)
    return cnts


def rle_to_string(counts: Sequence[int]) -> str:
    """Run lengths → compressed counts string (pycocotools ``rleToString``).

    The encoder exists so synthetic COCO-format fixtures can exercise the
    compressed decode path without pycocotools; the roundtrip is pinned by
    tests.
    """
    out: List[str] = []
    counts = list(counts)
    for i, x in enumerate(counts):
        if i > 2:
            x -= counts[i - 2]
        more = True
        while more:
            c = x & 0x1F
            x >>= 5  # Python's >> is arithmetic, matching the C long
            more = (x != -1) if (c & 0x10) else (x != 0)
            if more:
                c |= 0x20
            out.append(chr(c + 48))
    return "".join(out)


def rle_encode(mask: np.ndarray) -> List[int]:
    """(h, w) {0,1} mask → uncompressed run lengths (column-major)."""
    flat = np.asarray(mask, np.uint8).reshape(-1, order="F")
    if flat.size == 0:
        return []
    change = np.flatnonzero(np.diff(flat)) + 1
    bounds = np.concatenate([[0], change, [flat.size]])
    counts = np.diff(bounds).tolist()
    if flat[0] == 1:  # runs must start with background
        counts = [0] + counts
    return counts


def polygons_to_mask(polygons: Sequence[Sequence[float]], h: int, w: int
                     ) -> np.ndarray:
    """COCO polygon list → (h, w) uint8 {0,1} mask via ``cv2.fillPoly``.

    Documented deviation: pycocotools rasterizes polygons through a 5×
    upsampled boundary walk (``rleFrPoly``), which can differ from
    ``cv2.fillPoly`` by single boundary pixels.  See module docstring.
    """
    mask = np.zeros((h, w), np.uint8)
    pts = [np.round(np.asarray(p, np.float64).reshape(-1, 2)).astype(np.int32)
           for p in polygons if len(p) >= 6]
    if pts:
        cv2.fillPoly(mask, pts, 1)
    return mask


def ann_to_mask(ann: Dict, h: int, w: int) -> np.ndarray:
    """One COCO annotation → (h, w) uint8 {0,1} mask.

    Dispatches on the segmentation encoding exactly as pycocotools
    ``annToRLE`` does (reference usage: data/coco_masks_hdf5.py:52-76):
    dict → RLE (string counts = compressed), list → polygons.
    """
    seg = ann.get("segmentation")
    if seg is None:
        raise ValueError(f"annotation {ann.get('id')} has no segmentation")
    if isinstance(seg, dict):
        sh, sw = seg["size"]
        if (sh, sw) != (h, w):
            raise ValueError(
                f"RLE size {(sh, sw)} != image size {(h, w)}")
        counts = seg["counts"]
        if isinstance(counts, (str, bytes)):
            counts = rle_from_string(counts)
        return rle_decode(counts, sh, sw)
    return polygons_to_mask(seg, h, w)
