"""Offline COCO → HDF5 training-corpus builder.

Re-implementation of the reference's corpus generator
(reference: data/coco_masks_hdf5.py) with the same schema:

- group ``images``: one BGR uint8 image per COCO image id (key ``%012d``)
- group ``masks``:  (H, W, 2) uint8 per image — channel 0 ``mask_miss``
  (0 = area with people lacking keypoint annotation → excluded from the loss),
  channel 1 ``mask_all`` (255 = any-person area) (coco_masks_hdf5.py:38-116)
- group ``dataset``: one record per *main person* (key ``%07d``), JSON with
  ``image`` key, ``joints``/``objpos``/``scale_provided`` lists (main person
  first, then all other annotated people), full metadata mirrored in the
  ``meta`` attribute (coco_masks_hdf5.py:260-299)

Main-person selection (coco_masks_hdf5.py:165-207): ≥5 keypoints, segment area
≥ 32², and center at least 0.3×(bbox max side) away from every previously
selected main person.  Deviations from the reference (documented):

- the reference measures that distance against the *last iterated* person's
  bbox (a stale loop variable, coco_masks_hdf5.py:206); we use the candidate's
  own bbox;
- multiple crowd regions per image are merged instead of raising
  (coco_masks_hdf5.py:94 raises).

Visibility recode (coco_masks_hdf5.py:147-158): COCO v=2 (visible) → 1,
v=1 (labeled, occluded) → 0, v=0 (unlabeled) → 2.
"""
from __future__ import annotations

import json
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import cv2
import numpy as np

from ..obs.events import strict_dumps

MIN_KEYPOINTS = 5
MIN_AREA = 32 * 32
MAIN_PERSON_MIN_DIST_RATIO = 0.3
NUM_COCO_PARTS = 17


def recode_visibility(v: int) -> int:
    if v == 2:
        return 1  # marked and visible
    if v == 1:
        return 0  # marked but occluded
    return 2      # not labeled for this person


def person_record(ann: Dict, image_size: int) -> Dict:
    """Extract one person's fields (coco_masks_hdf5.py:128-163)."""
    x, y, w, h = ann["bbox"]
    joints = np.zeros((NUM_COCO_PARTS, 3), dtype=np.float64)
    kp = ann["keypoints"]
    for part in range(NUM_COCO_PARTS):
        joints[part, 0] = kp[part * 3]
        joints[part, 1] = kp[part * 3 + 1]
        joints[part, 2] = recode_visibility(kp[part * 3 + 2])
    return {
        "objpos": [x + w / 2, y + h / 2],
        "bbox": list(ann["bbox"]),
        "segment_area": ann["area"],
        "num_keypoints": ann["num_keypoints"],
        "joint": joints,
        # main-person height normalized by the training image size
        "scale_provided": h / image_size,
    }


def select_main_persons(persons: Sequence[Dict]) -> List[int]:
    """Indices of the main persons (coco_masks_hdf5.py:165-207)."""
    mains: List[int] = []
    prev: List[Tuple[float, float, float]] = []  # (cx, cy, max_side)
    for i, pers in enumerate(persons):
        if pers["num_keypoints"] < MIN_KEYPOINTS or \
                pers["segment_area"] < MIN_AREA:
            continue
        cx, cy = pers["objpos"]
        too_close = any(
            np.hypot(cx - px, cy - py) < side * MAIN_PERSON_MIN_DIST_RATIO
            for px, py, side in prev)
        if too_close:
            continue
        mains.append(i)
        prev.append((cx, cy, max(pers["bbox"][2], pers["bbox"][3])))
    return mains


def build_masks(shape: Tuple[int, int], person_masks: Sequence[np.ndarray],
                num_keypoints: Sequence[int],
                crowd_masks: Sequence[np.ndarray] = ()
                ) -> Tuple[np.ndarray, np.ndarray]:
    """mask_miss / mask_all as uint8 {0, 255} (coco_masks_hdf5.py:38-103).

    :param person_masks: binary {0,1} masks of non-crowd people
    :param num_keypoints: per person, aligned with person_masks
    :param crowd_masks: binary masks of crowd regions (RLE-decoded)
    """
    h, w = shape
    mask_all = np.zeros((h, w), dtype=np.uint8)
    unannotated = np.zeros((h, w), dtype=np.uint8)
    for m, nk in zip(person_masks, num_keypoints):
        mask_all |= m
        if nk <= 0:
            unannotated |= m
    for cm in crowd_masks:
        cm = cm - (mask_all & cm)  # subtract overlap with known people
        unannotated |= cm
        mask_all |= cm
    mask_miss = np.logical_not(unannotated).astype(np.uint8) * 255
    return mask_miss, mask_all * 255


def iter_records(image_rec: Dict, img_id: int, image_index: int,
                 persons: Sequence[Dict], dataset_type: str,
                 is_validation: bool) -> Iterator[Dict]:
    """One record per main person; each record centers the image on that
    person and appends every other annotated person
    (coco_masks_hdf5.py:209-257)."""
    mains = select_main_persons(persons)
    base = {
        "dataset": dataset_type,
        "isValidation": 1 if is_validation else 0,
        "img_width": image_rec["width"],
        "img_height": image_rec["height"],
        "image_id": img_id,
        "annolist_index": image_index,
        "img_path": image_rec.get("file_name", "%012d.jpg" % img_id),
    }
    for mi in mains:
        main = persons[mi]
        rec = dict(base)
        rec["objpos"] = [main["objpos"]]
        rec["joints"] = [main["joint"].tolist()]
        rec["scale_provided"] = [main["scale_provided"]]
        rec["people_index"] = mi
        others = 0
        for oi, other in enumerate(persons):
            if oi == mi or other["num_keypoints"] == 0:
                continue
            rec["joints"].append(other["joint"].tolist())
            rec["scale_provided"].append(other["scale_provided"])
            rec["objpos"].append(other["objpos"])
            others += 1
        rec["numOtherPeople"] = others
        yield rec


def write_record(dataset_grp, images_grp, masks_grp, record: Dict, count: int,
                 img: np.ndarray, mask_miss: np.ndarray,
                 mask_all: np.ndarray) -> None:
    """HDF5 writing (schema of coco_masks_hdf5.py:260-299)."""
    record = dict(record)
    record["count"] = count
    img_key = "%012d" % record["image_id"]
    if img_key not in images_grp:
        images_grp.create_dataset(img_key, data=img)
        masks_grp.create_dataset(
            img_key,
            data=np.stack([mask_miss, mask_all], axis=-1))
    required = {
        "image": img_key,
        "joints": record["joints"],
        "objpos": record["objpos"],
        "scale_provided": record["scale_provided"],
    }
    # strict emission (graftlint JGL004): COCO floats are finite today,
    # but a bare-NaN token in a stored record would surface as a parse
    # error at TRAINING time, arbitrarily far from the corpus build
    ds = dataset_grp.create_dataset("%07d" % count,
                                    data=strict_dumps(required))
    ds.attrs["meta"] = strict_dumps(record)


def load_coco_annotations(anno_path: str) -> Tuple[Dict, Dict]:
    """Stdlib parse of a person_keypoints_*.json: (image_id → image rec,
    image_id → list of person annotations), both in file order.

    Replaces the reference's ``pycocotools.coco.COCO`` index
    (coco_masks_hdf5.py:306-309) — the builder only ever needs images and
    per-image person annotations, which a single JSON pass provides.
    """
    with open(anno_path) as f:
        data = json.load(f)
    person_ids = {c["id"] for c in data.get("categories", [])
                  if c.get("name") == "person"} or {1}
    imgs = {im["id"]: im for im in data["images"]}
    anns: Dict[int, List[Dict]] = {i: [] for i in imgs}
    for ann in data.get("annotations", []):
        if ann.get("category_id", 1) in person_ids:
            anns.setdefault(ann["image_id"], []).append(ann)
    return imgs, anns


def build_coco_corpus(anno_path: str, img_dir: str, out_train: str,
                      out_val: str, image_size: int = 512,
                      val_size: int = 100,
                      limit: Optional[int] = None) -> Tuple[int, int]:
    """Full COCO → HDF5 pipeline (coco_masks_hdf5.py:304-351).

    Dependency-free: annotations are parsed with the stdlib and
    segmentation masks decoded by :mod:`.coco_masks` (polygons,
    uncompressed and compressed RLE), so the whole COCO-format journey
    runs without pycocotools (which the reference hard-requires,
    coco_masks_hdf5.py:6).  Returns (train_count, val_count).
    """
    import h5py

    from .coco_masks import ann_to_mask

    imgs, anns_by_img = load_coco_annotations(anno_path)
    ids = list(imgs.keys())
    if limit is not None:
        ids = ids[:limit]

    tr = h5py.File(out_train, "w")
    va = h5py.File(out_val, "w")
    grps = {f: (f.create_group("dataset"), f.create_group("images"),
                f.create_group("masks")) for f in (tr, va)}
    counts = {tr: 0, va: 0}

    for image_index, img_id in enumerate(ids):
        anns = anns_by_img.get(img_id, [])
        image_rec = imgs[img_id]
        persons = [person_record(a, image_size) for a in anns
                   if a["iscrowd"] == 0]
        is_val = image_index < val_size
        records = list(iter_records(image_rec, img_id, image_index,
                                    persons, "COCO", is_val))
        if not records:
            continue
        fname = image_rec.get("file_name", "%012d.jpg" % img_id)
        img = cv2.imread(os.path.join(img_dir, fname))
        if img is None:
            raise IOError(f"missing image {fname} in {img_dir}")
        h, w = img.shape[:2]
        person_masks = [ann_to_mask(a, h, w) for a in anns
                        if a["iscrowd"] == 0]
        crowd_masks = [ann_to_mask(a, h, w) for a in anns
                       if a["iscrowd"] == 1]
        nks = [a["num_keypoints"] for a in anns if a["iscrowd"] == 0]
        mask_miss, mask_all = build_masks(img.shape[:2], person_masks, nks,
                                          crowd_masks)
        target = va if is_val else tr
        for rec in records:
            write_record(*grps[target], rec, counts[target], img, mask_miss,
                         mask_all)
            counts[target] += 1

    tr_count, va_count = counts[tr], counts[va]
    tr.close()
    va.close()
    return tr_count, va_count
