"""Image/keypoint augmentation as a single composed affine transform.

Host-side (NumPy/OpenCV) part of the input pipeline.  Semantics follow the
reference transformer (reference: py_cocodata_server/py_data_transformer.py):
all geometric augmentations — recenter on the main person, rotate, scale to
``target_dist``, flip, recenter+shift — compose into ONE 2x3 affine matrix which
is applied once with ``cv2.warpAffine`` to the image and both masks, and by
matrix multiplication to the joint coordinates (py_data_transformer.py:43-89,
112-184).

Randomness is explicit: an ``AugmentParams`` is drawn from a
``numpy.random.Generator`` so the pipeline is seedable per-host and per-epoch
(the TPU-native replacement for the reference's process-global ``random``
module, whose DataLoader fork hazard is noted at data/mydataset.py:33).
"""
from __future__ import annotations

from dataclasses import dataclass
from math import cos, pi, sin
from typing import Optional, Tuple

import cv2
import numpy as np

from ..config import SkeletonConfig, TransformParams

# The image normalization constant, shared with the on-device prologue
# (train.step.normalize_images).  Multiplication by the f32 reciprocal —
# not division by 255 — on BOTH sides: XLA rewrites division-by-constant
# into reciprocal multiplication, so dividing on the host would leave the
# two wire formats 1 ULP apart on 126 of the 256 uint8 values.  With the
# shared constant the uint8 and f32 wires are bit-identical end to end
# (exhaustively checked over all 256 values in test_input_pipeline.py).
IMAGE_NORM_SCALE = np.float32(1.0 / 255.0)


@dataclass(frozen=True)
class AugmentParams:
    """One draw of augmentation parameters (reference: AugmentSelection)."""
    flip: bool = False
    tint: bool = False
    degree: float = 0.0
    shift: Tuple[int, int] = (0, 0)
    scale: float = 1.0

    @staticmethod
    def sample(tp: TransformParams, rng: np.random.Generator) -> "AugmentParams":
        """Random draw (reference: py_data_transformer.py:18-30)."""
        flip = rng.uniform() < tp.flip_prob
        tint = rng.uniform() < tp.tint_prob
        degree = rng.uniform(-1.0, 1.0) * tp.max_rotate_degree
        scale = (
            (tp.scale_max - tp.scale_min) * rng.uniform() + tp.scale_min
            if rng.uniform() < tp.scale_prob else 1.0)
        shift = (
            int(rng.uniform(-1.0, 1.0) * tp.center_perterb_max),
            int(rng.uniform(-1.0, 1.0) * tp.center_perterb_max))
        return AugmentParams(flip, tint, degree, shift, scale)

    @staticmethod
    def identity() -> "AugmentParams":
        return AugmentParams()


def build_affine(aug: AugmentParams, center: Tuple[float, float],
                 scale_provided: float, config: SkeletonConfig
                 ) -> Tuple[np.ndarray, float]:
    """Compose center→rotate→scale→flip→recenter(+shift) into one 2x3 matrix.

    ``scale_provided`` is main-person height / image size; the person is
    normalized so its height is ``target_dist`` (0.6) of the output
    (reference: py_data_transformer.py:43-89).
    Returns (2x3 affine matrix, applied scale factor).
    """
    tp = config.transform_params
    scale_self = scale_provided * (config.height / (config.height - 1))
    A = cos(aug.degree / 180.0 * pi)
    B = sin(aug.degree / 180.0 * pi)
    scale_size = tp.target_dist / scale_self * aug.scale

    center_x, center_y = center
    center2zero = np.array([[1.0, 0.0, -center_x],
                            [0.0, 1.0, -center_y],
                            [0.0, 0.0, 1.0]])
    rotate = np.array([[A, B, 0.0],
                       [-B, A, 0.0],
                       [0.0, 0.0, 1.0]])
    scale_m = np.array([[scale_size, 0.0, 0.0],
                        [0.0, scale_size, 0.0],
                        [0.0, 0.0, 1.0]])
    flip_m = np.array([[-1.0 if aug.flip else 1.0, 0.0, 0.0],
                       [0.0, 1.0, 0.0],
                       [0.0, 0.0, 1.0]])
    center2center = np.array(
        [[1.0, 0.0, config.width / 2 - 0.5 + aug.shift[0]],
         [0.0, 1.0, config.height / 2 - 0.5 + aug.shift[1]],
         [0.0, 0.0, 1.0]])
    combined = center2center @ flip_m @ scale_m @ rotate @ center2zero
    return combined[0:2], scale_size


def distort_color(img: np.ndarray, rng: np.random.Generator) -> np.ndarray:
    """HSV jitter on a uint8 BGR image (reference: py_data_transformer.py:98-110)."""
    hsv = cv2.cvtColor(img, cv2.COLOR_BGR2HSV).astype(np.int16)
    hsv[:, :, 0] = np.clip(hsv[:, :, 0] - 10 + rng.integers(0, 21), 0, 179)
    hsv[:, :, 1] = np.clip(hsv[:, :, 1] - 20 + rng.integers(0, 81), 0, 255)
    hsv[:, :, 2] = np.clip(hsv[:, :, 2] - 20 + rng.integers(0, 61), 0, 255)
    return cv2.cvtColor(hsv.astype(np.uint8), cv2.COLOR_HSV2BGR)


class Transformer:
    """Applies one composed affine to image, masks, and joints.

    Outputs float32: image HxWx3 in [0,1]; mask_miss and mask_all resized to the
    stride-4 grid in [0,1] (reference: py_data_transformer.py:112-184).
    """

    def __init__(self, config: SkeletonConfig):
        self.config = config

    def transform(self, img: np.ndarray, mask_miss: np.ndarray,
                  mask_all: np.ndarray, joints: np.ndarray,
                  objpos: Tuple[float, float], scale_provided: float,
                  aug: Optional[AugmentParams] = None,
                  rng: Optional[np.random.Generator] = None,
                  wire: str = "f32",
                  image_out: Optional[np.ndarray] = None):
        """
        :param img: HxWx3 uint8 (BGR, as read by cv2)
        :param mask_miss: HxW uint8, 0 = masked (no annotation)
        :param mask_all: HxW uint8, 255 = person area
        :param joints: (num_people, num_parts, 3) float — x, y, visibility
            (0 hidden / 1 visible / 2 absent, recoded by the corpus builder)
        :param wire: image wire format — ``"f32"`` returns the image as
            float32 in [0, 1] (the legacy contract); ``"uint8"`` returns
            the warped uint8 pixels untouched, for pipelines that ship
            uint8 and normalize on device.  The f32 image is EXACTLY
            ``uint8_image.astype(float32) / 255``, so the two wires are
            bit-identical after normalization.
        :param image_out: optional preallocated (height, width, 3)
            contiguous uint8 array; with ``wire="uint8"`` the warp renders
            directly into it (zero-copy into, e.g., a shared-memory ring
            slot) and it is returned as the image.
        :returns: (image, mask_miss, mask_all, joints) — masks/joints
            float32; image per ``wire``
        """
        cfg = self.config
        if aug is None:
            rng = rng if rng is not None else np.random.default_rng()
            aug = AugmentParams.sample(cfg.transform_params, rng)
        if aug.tint:
            if rng is None:
                raise ValueError(
                    "aug.tint=True requires an rng (color jitter draws random "
                    "offsets); pass rng= to keep the pipeline seedable")
            img = distort_color(img, rng)

        assert scale_provided != 0, "scale_provided is zero"
        M, _ = build_affine(aug, objpos, scale_provided, cfg)

        size = (cfg.width, cfg.height)
        dst = image_out if wire == "uint8" else None
        img = cv2.warpAffine(img, M, size, dst=dst, flags=cv2.INTER_LINEAR,
                             borderMode=cv2.BORDER_CONSTANT,
                             borderValue=(124, 127, 127))
        mask_miss = cv2.warpAffine(mask_miss, M, size, flags=cv2.INTER_LINEAR,
                                   borderMode=cv2.BORDER_CONSTANT,
                                   borderValue=255)
        mask_miss = cv2.resize(mask_miss, cfg.grid_shape[::-1],
                               interpolation=cv2.INTER_AREA)
        mask_all = cv2.warpAffine(mask_all, M, size, flags=cv2.INTER_LINEAR,
                                  borderMode=cv2.BORDER_CONSTANT, borderValue=0)
        mask_all = cv2.resize(mask_all, cfg.grid_shape[::-1],
                              interpolation=cv2.INTER_AREA)

        # Transform joints with the same matrix: homogeneous coords as column
        # vectors (reference: py_data_transformer.py:161-170).
        joints = joints.copy()
        homo = joints.copy()
        homo[:, :, 2] = 1.0
        warped = np.matmul(M, homo.transpose([0, 2, 1])).transpose([0, 2, 1])
        joints[:, :, 0:2] = warped

        if aug.flip:  # L/R keypoint identity swap (py_data_transformer.py:173-177)
            left, right = list(cfg.left_parts), list(cfg.right_parts)
            joints[:, left + right, :] = joints[:, right + left, :]

        image = (img if wire == "uint8"
                 else img.astype(np.float32) * IMAGE_NORM_SCALE)
        return (image,
                mask_miss.astype(np.float32) / 255.0,
                mask_all.astype(np.float32) / 255.0,
                joints.astype(np.float32))
