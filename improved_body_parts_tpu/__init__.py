"""improved_body_parts_tpu — a TPU-native (JAX/XLA/Flax/pjit) bottom-up multi-person
2D pose estimation framework with the capabilities of hellojialee/Improved-Body-Parts
("SimplePose", AAAI-2020).

Design stance (see SURVEY.md §7): this is a from-scratch framework, not a port.
The compute path is JAX/Flax NHWC lowered to XLA for the MXU; distribution is
single-program SPMD over a `jax.sharding.Mesh` (ICI collectives inserted by XLA);
mixed precision is bf16 compute with fp32 params; the post-processing decoder has
a vectorized NumPy path and a native C++ path (ctypes).

Subpackages
-----------
- ``config``    typed configs (reference: config/config.py and variants)
- ``data``      augmentation + GT synthesis + HDF5 corpus + loader
                (reference: py_cocodata_server/, data/)
- ``models``    Flax IMHN layer library and PoseNet variants (reference: models/)
- ``ops``       jitted losses, NMS, resize primitives
- ``parallel``  mesh construction and sharding rules (reference: train_distributed.py,
                parallel_encoding/paralle.py — obsolete under SPMD)
- ``train``     schedules, train state, SPMD training loop, SWA
- ``infer``     multi-scale flip-ensemble prediction, decoding, COCO evaluation
- ``serve``     dynamic-batching request serving (shape-bucket coalescing,
                bounded admission, device-replica round-robin, warmup precompile)
- ``obs``       unified telemetry: metric registry w/ Prometheus + JSON
                exposition, JSONL run events, /metrics endpoint, data-wait
                vs compute attribution, post-warmup recompile detection
- ``utils``     meters, padding, logging helpers
"""

__version__ = "0.1.0"
