from .mesh import (
    barrier,
    batch_sharding,
    batch_spec,
    initialize_distributed,
    make_mesh,
    mesh_topology,
    replicated,
    reshard_replicated,
    shard_batch,
    topology_mismatch,
)
from .partition import (
    NAMED_RULESETS,
    UnmatchedLeafError,
    constrain_batch_sharded,
    get_ruleset,
    imhn_partition_rules,
    match_partition_rules,
    reshard_tree,
    rules_fingerprint,
    shard_tree,
    sharding_summary,
    train_state_shardings,
    tree_shardings,
)
from .prefetch import device_prefetch

__all__ = [
    "barrier", "batch_sharding", "batch_spec", "device_prefetch",
    "initialize_distributed", "make_mesh", "mesh_topology", "replicated",
    "reshard_replicated", "shard_batch", "topology_mismatch",
    "NAMED_RULESETS", "UnmatchedLeafError", "constrain_batch_sharded",
    "get_ruleset", "imhn_partition_rules", "match_partition_rules",
    "reshard_tree", "rules_fingerprint", "shard_tree", "sharding_summary",
    "train_state_shardings", "tree_shardings",
]
