from .mesh import (
    barrier,
    batch_sharding,
    batch_spec,
    initialize_distributed,
    make_mesh,
    replicated,
    shard_batch,
)
from .prefetch import device_prefetch

__all__ = [
    "barrier", "batch_sharding", "batch_spec", "device_prefetch",
    "initialize_distributed", "make_mesh", "replicated", "shard_batch",
]
