from .mesh import (
    barrier,
    batch_sharding,
    batch_spec,
    initialize_distributed,
    make_mesh,
    mesh_topology,
    replicated,
    reshard_replicated,
    shard_batch,
    topology_mismatch,
)
from .prefetch import device_prefetch

__all__ = [
    "barrier", "batch_sharding", "batch_spec", "device_prefetch",
    "initialize_distributed", "make_mesh", "mesh_topology", "replicated",
    "reshard_replicated", "shard_batch", "topology_mismatch",
]
