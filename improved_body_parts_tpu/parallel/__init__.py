from .mesh import (
    batch_sharding,
    batch_spec,
    initialize_distributed,
    make_mesh,
    replicated,
    shard_batch,
)

__all__ = [
    "batch_sharding", "batch_spec", "initialize_distributed", "make_mesh",
    "replicated", "shard_batch",
]
