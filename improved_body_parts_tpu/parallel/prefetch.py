"""Host→device prefetch: overlap batch placement with the device step.

The reference keeps its GPUs >90% utilized via DataLoader worker prefetch +
``.cuda(non_blocking=True)`` (reference: README.md:34,
train_distributed.py:247-249).  The TPU-native equivalent: a background
thread runs ``shard_batch`` (host→device transfer + sharding) up to ``depth``
batches ahead of the training loop, so the transfer of batch N+1 rides under
the (asynchronously dispatched) device step of batch N instead of serializing
with it.

JAX device placement is thread-safe; the bounded queue caps device-memory
pressure at ``depth`` in-flight batches.
"""
from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator

from ..obs.trace import get_tracer
from .mesh import shard_batch


def device_prefetch(batches: Iterable, mesh, depth: int = 2,
                    spatial_shard: bool = False,
                    phase_stats=None) -> Iterator:
    """Yield device-placed (sharded) batches, produced ``depth`` ahead.

    Exceptions from the underlying iterable (or from device placement) are
    re-raised in the consumer.  Abandoning the generator early (an error in
    the training step, KeyboardInterrupt) stops the producer and drains the
    queue so in-flight device buffers are released rather than pinned in
    device memory until process exit.

    ``phase_stats`` (an ``obs.StepPhases``) attributes the consumer's
    wall clock: time blocked here waiting on the prefetch queue is DATA
    WAIT (the input pipeline fell behind), time the consumer holds the
    thread between batches is COMPUTE (device step + dispatch +
    readback).  The split is the live answer to "why is this step slow"
    that previously required an offline tools/feed_rate.py rerun.
    """
    it = _device_prefetch(batches, mesh, depth, spatial_shard)
    if phase_stats is not None:
        return phase_stats.attribute(it)
    return it


def _device_prefetch(batches: Iterable, mesh, depth: int,
                     spatial_shard: bool) -> Iterator:
    if depth < 1:
        for batch in batches:
            yield shard_batch(batch, mesh, spatial_shard)
        return

    q: queue.Queue = queue.Queue(maxsize=depth)
    sentinel = object()
    error = []
    stop = threading.Event()

    def producer():
        # spans land on this thread's own track ("device-prefetch"), so
        # the timeline shows host->device placement riding under the
        # consumer's compute spans — the overlap this thread exists for
        trace = get_tracer()
        try:
            for batch in batches:
                with trace.span("shard_batch"):
                    placed = shard_batch(batch, mesh, spatial_shard)
                while not stop.is_set():
                    try:
                        q.put(placed, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised by consumer
            error.append(e)
        finally:
            # The sentinel must BLOCK until the (possibly slow) consumer
            # makes room — a full queue here usually means the consumer is
            # still working through earlier batches, and dropping the
            # sentinel would strand it in q.get() forever.  stop is the
            # only abandon signal.
            while not stop.is_set():
                try:
                    q.put(sentinel, timeout=0.1)
                    break
                except queue.Full:
                    continue

    thread = threading.Thread(target=producer, daemon=True,
                              name="device-prefetch")
    thread.start()
    try:
        while True:
            item = q.get()
            if item is sentinel:
                if error:
                    raise error[0]
                return
            yield item
    finally:
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        # join AFTER the drain: the producer's put loops exit on the next
        # 0.1 s poll once stop is set, so this bounds thread shutdown —
        # without it an abandoned generator leaks a thread whose `placed`
        # local pins an in-flight device buffer past the drain (and, for
        # ring-backed sources, keeps a consumed-slot view alive)
        thread.join()
        try:
            while True:  # anything placed between drain start and stop
                q.get_nowait()
        except queue.Empty:
            pass
