"""Device mesh construction and sharding rules.

The TPU-native replacement for the reference's entire parallelism stack
(reference: train_distributed.py:69-146 NCCL process groups + Apex DDP;
parallel_encoding/paralle.py DataParallel/criterion machinery — obsolete under
SPMD).  One jitted program runs on every device; gradient/metric all-reduces
are XLA collectives over ICI inserted automatically from sharding annotations;
multi-host extends the same mesh over DCN via ``jax.distributed.initialize``.

Mesh axes:
- ``data``    batch (data parallel) — the reference's only strategy
- ``model``   optional second axis for spatial sharding of very large inference
              inputs (halo exchange inserted by GSPMD for convs)
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bring-up (replaces ``dist.init_process_group('nccl')``,
    train_distributed.py:82).  No-op for single-process runs."""
    if num_processes is not None and num_processes > 1:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)


def barrier(name: str, timeout_ms: int = 600_000) -> None:
    """Align all processes at a named coordination-service barrier.

    The transport contexts behind the first XLA collective (Gloo pairs on
    CPU; ICI bring-up on TPU slices) have a short fixed rendezvous window
    (~30 s for Gloo's key-value wait), while hosts can legitimately drift
    minutes apart during per-host work — imports, corpus open, parameter
    init, compilation.  A rank that reaches the collective early times
    out waiting for the stragglers and takes the job down (observed:
    ``Gloo context initialization failed: GetKeyValue() timed out``).
    The coordination service's barrier has a long, configurable timeout,
    so re-aligning here lets the collective's own rendezvous start from
    zero skew.  No-op in single-process runs; best-effort if the client
    API is unavailable (the collective then simply keeps its own window).
    """
    import jax

    if jax.process_count() <= 1:
        return
    try:
        from jax._src import distributed

        client = distributed.global_state.client
        if client is not None:
            client.wait_at_barrier(name, timeout_ms)
    except Exception:
        pass


def make_mesh(data: Optional[int] = None, model: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a ('data', 'model') mesh over available devices.

    ``data=None`` uses all devices (divided by ``model``).
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if data is None:
        assert n % model == 0, (n, model)
        data = n // model
    assert data * model <= n, f"need {data * model} devices, have {n}"
    arr = np.asarray(devices[: data * model]).reshape(data, model)
    return Mesh(arr, axis_names=("data", "model"))


def mesh_topology(mesh: Optional[Mesh] = None,
                  partition_rules: Optional[str] = None) -> Dict:
    """JSON-able description of the device layout a run executes on.

    Stamped into every checkpoint's ``COMMIT.json`` so a restart on a
    *different* layout (a respawned spot slice with fewer chips, a
    single-host debug resume of a pod checkpoint) is DETECTED at restore
    time — not discovered as a cryptic sharding error deep inside the
    first donated step.  ``train.supervisor`` compares this against the
    restart's mesh via :func:`topology_mismatch`.

    ``partition_rules`` is the 12-hex ruleset fingerprint
    (``parallel.partition.rules_fingerprint``) of a PARTITIONED run:
    the state's layout is a function of the rules, so a resume under
    different rules is a layout change exactly like a different device
    count — and one the supervisor must refuse loudly (the compiled
    step would silently re-place every restored leaf).  Omitted (the
    replicated regime) the key is absent, and legacy checkpoints
    without it keep resuming unchecked, like every other stamped field.
    """
    devices = jax.devices()
    topo = {
        "process_count": int(jax.process_count()),
        "device_count": len(devices),
        "platform": devices[0].platform if devices else None,
    }
    if mesh is not None:
        topo["mesh_devices"] = int(mesh.devices.size)
        topo["mesh_axes"] = {str(name): int(size) for name, size in
                             zip(mesh.axis_names, mesh.devices.shape)}
    if partition_rules is not None:
        topo["partition_rules"] = str(partition_rules)
    return topo


def topology_mismatch(stamped: Optional[Dict], mesh: Mesh,
                      process_count: Optional[int] = None,
                      partition_rules: Optional[str] = None
                      ) -> Optional[Dict[str, Tuple]]:
    """Compare a checkpoint's stamped topology against the current one.

    Returns ``{field: (stamped, current)}`` for every differing field, or
    None when the layouts match (or the checkpoint predates the stamp —
    a legacy checkpoint carries no topology and nothing can be checked).
    Platform changes (tpu -> cpu) are reported too: numerically legal
    after a reshard, but the operator should know their resume is not
    running where the checkpoint was trained.

    ``partition_rules`` is the CURRENT run's ruleset fingerprint (None
    for the replicated regime).  A checkpoint stamped with a ruleset
    diffs against it like any other layout field — including against
    None, because resuming a partitioned checkpoint without rules would
    silently re-replicate a layout the operator asked for.  A stamp
    WITHOUT the key (legacy / replicated checkpoint) checks nothing, so
    adopting partitioning on an old run stays possible.
    """
    if not stamped:
        return None
    current = mesh_topology(mesh, partition_rules=partition_rules)
    if process_count is not None:
        current["process_count"] = int(process_count)
    diff = {}
    for key in ("process_count", "device_count", "platform",
                "mesh_devices", "mesh_axes"):
        if key in stamped and key in current \
                and stamped[key] != current[key]:
            diff[key] = (stamped[key], current[key])
    if "partition_rules" in stamped \
            and stamped["partition_rules"] != current.get("partition_rules"):
        diff["partition_rules"] = (stamped["partition_rules"],
                                   current.get("partition_rules"))
    return diff or None


def reshard_replicated(tree, mesh: Mesh):
    """Place a (restored, host-resident) state pytree onto ``mesh`` with
    replicated sharding — the reshard-on-restore step for topology
    changes.

    Params/optimizer state are replicated under this repo's pure
    data-parallel regime, so "resharding" to a different device count is
    a re-placement: every leaf is broadcast to the new mesh's devices,
    and placement failures surface HERE, at restore time, instead of as
    a cryptic sharding error inside the first compiled step.

    Call this ONLY when the topology actually changed (the new mesh
    then forces a fresh step compile).  Re-placing restored host leaves
    onto an UNCHANGED mesh hands committed arrays to a donated
    executable loaded from the persistent compilation cache, which the
    jax 0.4.37 CPU backend corrupts: the outputs jax returns were never
    written (NaN losses from the second resumed step on) and the
    executable's in-place writes land in buffers the runtime already
    handed out (SIGSEGV mid-epoch).  Found end-to-end by
    tools/chaos_train.py and reproduced deterministically; the
    unchanged-topology resume keeps host leaves and lets the jit entry
    place them — the path plain ``--resume auto`` has always taken.
    ``may_alias=False`` keeps the placed leaves runtime-owned copies
    rather than adoptions of the checkpoint reader's host buffers
    (defense in depth against the same in-place-write quirk the save
    path documents in ``train.checkpoint.snapshot_to_host``).
    """
    sharding = replicated(mesh)
    return jax.tree.map(
        lambda x: jax.device_put(x, sharding, may_alias=False), tree)


def abstract_with_sharding(tree, sharding):
    """The ``ShapeDtypeStruct`` twin of ``device_put(tree, sharding)``:
    stamp a sharding onto every leaf of an abstract pytree WITHOUT
    materializing anything.  This is how AOT tooling (``jit.lower`` on
    shape trees — the program auditor in ``analysis.program``, export
    paths) expresses "the state is replicated, the batch is sharded"
    for a compile that never sees real data."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=sharding), tree)


def batch_spec(spatial_shard: bool = False) -> P:
    """PartitionSpec for an NHWC batch: batch over 'data'; optionally the
    height axis over 'model' (spatial partitioning for huge inputs)."""
    if spatial_shard:
        return P("data", "model", None, None)
    return P("data", None, None, None)


def batch_sharding(mesh: Mesh, spatial_shard: bool = False) -> NamedSharding:
    return NamedSharding(mesh, batch_spec(spatial_shard))


def replicated(mesh: Mesh) -> NamedSharding:
    """Sharding for params/optimizer state: replicated over the whole mesh
    (pure data parallelism, matching the reference's DDP replication)."""
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh, spatial_shard: bool = False):
    """Place a host batch (pytree of arrays with leading batch dim) onto the
    mesh with batch sharding.

    Single-process: a plain device_put.  Multi-host: each process passes its
    LOCAL slice of the global batch (global_batch // process_count rows) and
    the slices are assembled into one global array
    (``jax.make_array_from_process_local_data``) — the SPMD replacement for
    DistributedSampler feeding each rank its shard
    (reference: train_distributed.py:205-213).

    Placement preserves dtype and COPIES the host memory (verified
    non-aliasing on the CPU backend too): a uint8-wire image batch crosses
    host→device as uint8 — 4x fewer bytes than fp32, normalized on device
    by the train step — and its source buffer (e.g. a ``data.shm_ring``
    slot) is free for reuse as soon as this returns.
    """
    sharding = batch_sharding(mesh, spatial_shard)
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x), batch)
