"""Rule-based GSPMD partitioning of the train state.

The reference's entire answer to model size was "replicate and
all-reduce" (Apex DDP, train_distributed.py:129-139).  The meshed train
step inherited that: ``MULTICHIP_r0*.json`` ran an 8-device
('data', 'model') mesh with EVERY parameter and optimizer slot
replicated — the 'model' axis existed but carried nothing.  This module
promotes the state itself to first-class GSPMD residents:

- :func:`match_partition_rules` maps **regex rules over the flattened
  pytree path names** (``params/Backbone_0/ConvBlock_0/Conv_0/kernel``,
  ``opt_state/1/0/trace/.../kernel``) to ``PartitionSpec``s — the
  pattern every large-scale JAX codebase converges on (SNIPPETS.md [2]).
  Because optimizer momentum mirrors the parameter tree under its own
  prefix, ONE ruleset shards parameters and their optimizer slots
  identically — which is exactly what donation aliasing needs.
- ``strict=True`` makes an unmatched non-scalar leaf a hard
  :class:`UnmatchedLeafError` instead of a silent replicate: on a pod,
  "the rule didn't match" means "this tensor is materialized on every
  chip", and that must be a diff in review, not an OOM at scale.
- :func:`imhn_partition_rules` is the IMHN-specific default: wide
  convolution kernels shard their output-channel axis over ``'model'``
  (channels-last NHWC — the out-channel axis is the reduction-free axis
  a conv can split without halo exchange); biases, BN scale/stats and
  scalars replicate.  Specs are REFINED against real leaf shapes
  (:func:`refine_spec`): an axis the mesh cannot divide evenly, or one
  that would shard below ``min_shard_dim`` elements per device, drops
  to replicated — deterministically, per leaf, never at XLA's whim.
- :func:`train_state_shardings` turns (abstract state, mesh, rules)
  into the ``NamedSharding`` pytree ``make_train_step(mesh=, rules=)``
  compiles with, and :func:`reshard_tree` re-places a *sharded* state
  onto a new mesh on topology-change resume (the sharded twin of
  ``mesh.reshard_replicated``, which silently assumed replication).
- :func:`rules_fingerprint` is the 12-hex hash stamped into every
  checkpoint's ``COMMIT.json`` topology block: resuming under a
  DIFFERENT ruleset recompiles the step with a different layout — the
  stamp turns that into a loud refusal
  (``train.supervisor.reshard_on_topology_change``).

Verification: the partitioned step is a registered graftaudit program
(``train_step_partitioned``) whose compiled executable must show >0
sharded state leaves and full donation aliasing (PRG003/PRG006), and
``tools/scaling_test.py`` drives it into the SCALING.json weak-scaling
artifact.
"""
from __future__ import annotations

import hashlib
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

#: a rule: (regex over the '/'-joined leaf path, PartitionSpec).  First
#: match wins; compile-order is the precedence order.
PartitionRule = Tuple[str, P]

#: leaves at/below this many elements are never worth sharding (and
#: scalar step counters/SWA counts must stay replicated for free)
_SCALAR_ELEMS = 1

#: default floor on per-device shard extent along a sharded axis: a
#: conv kernel whose out-channel axis would split below this many
#: channels per device gains nothing from the shard (the all-gather
#: latency dominates) — the "wide kernels only" half of the IMHN rules
DEFAULT_MIN_SHARD_DIM = 8


class UnmatchedLeafError(ValueError):
    """strict-mode failure: at least one non-scalar leaf matched no
    partition rule.  On a pod, an unmatched leaf is silently replicated
    onto every chip — the error names every offender so the ruleset is
    fixed in review, not discovered as an OOM at scale."""


def _key_name(entry) -> str:
    """One path entry -> its bare name (DictKey 'Conv_0', SequenceKey
    '1', GetAttrKey 'params'), without keystr()'s bracket syntax."""
    for attr in ("key", "idx", "name"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def tree_path_names(tree) -> List[Tuple[str, object]]:
    """(name, leaf) pairs for every leaf, names '/'-joined in flatten
    order: ``params/Backbone_0/ConvBlock_0/Conv_0/kernel``,
    ``opt_state/1/0/trace/Backbone_0/.../kernel``, ``step``."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [("/".join(_key_name(k) for k in path), leaf)
            for path, leaf in flat]


def refine_spec(spec: P, shape: Sequence[int], mesh: Mesh,
                min_shard_dim: int = DEFAULT_MIN_SHARD_DIM) -> P:
    """Drop sharded axes a leaf cannot actually support.

    An axis is kept only when the mesh axis size divides the dimension
    EXACTLY (uneven GSPMD shards pad — and padding breaks the donation
    alias the train step depends on) and the per-device extent stays at
    least ``min_shard_dim``.  Deterministic per (shape, mesh): the
    layout is decided here, in auditable Python, never left to XLA.
    """
    if not spec:
        return spec
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    out = []
    for dim, axes in zip(shape, tuple(spec) + (None,) * (len(shape) - len(spec))):
        if axes is None:
            out.append(None)
            continue
        names = axes if isinstance(axes, tuple) else (axes,)
        total = int(np.prod([axis_sizes.get(a, 1) for a in names]))
        if total <= 1 or dim % total != 0 or dim // total < min_shard_dim:
            out.append(None)
        else:
            out.append(axes)
    while out and out[-1] is None:
        out.pop()
    return P(*out)


def match_partition_rules(rules: Sequence[PartitionRule], tree, *,
                          strict: bool = False, mesh: Optional[Mesh] = None,
                          min_shard_dim: int = DEFAULT_MIN_SHARD_DIM):
    """PartitionSpec pytree for ``tree`` from first-match-wins regex
    rules over '/'-joined leaf paths (``re.search`` semantics,
    SNIPPETS.md [2]).

    Scalar / single-element leaves short-circuit to ``P()`` (a sharded
    step counter is meaningless).  ``strict=True`` raises
    :class:`UnmatchedLeafError` naming EVERY unmatched non-scalar leaf;
    the default replicates them.  With ``mesh`` given, each matched
    spec is refined against the leaf's shape (:func:`refine_spec`) so
    undividable / too-narrow axes replicate deterministically.
    """
    import jax

    compiled = [(re.compile(pat), spec) for pat, spec in rules]
    named = tree_path_names(tree)
    unmatched: List[str] = []
    specs: List[P] = []
    for name, leaf in named:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        if int(np.prod(shape)) <= _SCALAR_ELEMS:
            specs.append(P())
            continue
        for pat, spec in compiled:
            if pat.search(name):
                if mesh is not None:
                    spec = refine_spec(spec, shape, mesh,
                                       min_shard_dim=min_shard_dim)
                specs.append(spec)
                break
        else:
            unmatched.append(name)
            specs.append(P())
    if strict and unmatched:
        shown = ", ".join(unmatched[:8])
        more = f" (+{len(unmatched) - 8} more)" if len(unmatched) > 8 else ""
        raise UnmatchedLeafError(
            f"{len(unmatched)} leaves matched no partition rule under "
            f"strict mode: {shown}{more}. Every leaf must be covered — "
            "add a rule (a trailing ('.*', PartitionSpec()) replicates "
            "the remainder explicitly).")
    structure = jax.tree.structure(tree)
    return jax.tree.unflatten(structure, specs)


def imhn_partition_rules() -> Tuple[PartitionRule, ...]:
    """The IMHN default ruleset: wide conv / transposed-conv kernels
    shard their output-channel (last) axis over ``'model'``; everything
    else — biases, BN scale/bias, batch statistics, SE dense layers
    (tiny), the step counter — replicates via the explicit catch-all,
    so the set is STRICT-complete by construction.

    Flax conv kernels are HWIO (channels last); the optimizer's
    momentum trace mirrors the parameter paths under
    ``opt_state/.../trace/``, so the same two rules shard it
    identically — a donated update leaf keeps one layout across the
    step, which is what PRG003's alias needs.
    """
    return (
        (r"(Conv|ConvTranspose)_\d+/kernel$", P(None, None, None, "model")),
        (r".*", P()),
    )


def imhn_fsdp_rules() -> Tuple[PartitionRule, ...]:
    """FSDP/ZeRO-style variant: wide conv kernels shard over the FULL
    mesh — ``('data', 'model')`` composite axis — so even a pure
    data-parallel mesh (model=1) splits the state across its devices
    and XLA all-gathers each kernel at its use site.  This is the
    memory-first layout (per-device state shrinks ∝ world size); the
    plain ``imhn`` rules are the compute-first layout ('model'-axis
    tensor parallelism).  The weak-scaling artifact
    (``tools/scaling_test.py``) drives this set so every mesh size on
    the curve carries sharded state."""
    return (
        (r"(Conv|ConvTranspose)_\d+/kernel$",
         P(None, None, None, ("data", "model"))),
        (r".*", P()),
    )


#: named rulesets for config/CLI selection (tools/train.py
#: ``--partition-rules``); "replicated" is the explicit everything-P()
#: set — the A/B arm and the PRG006 seeded-regression fixture
NAMED_RULESETS: Dict[str, Tuple[PartitionRule, ...]] = {
    "imhn": imhn_partition_rules(),
    "imhn_fsdp": imhn_fsdp_rules(),
    "replicated": ((r".*", P()),),
}


def get_ruleset(name: str) -> Tuple[PartitionRule, ...]:
    if name not in NAMED_RULESETS:
        raise KeyError(f"unknown partition ruleset {name!r}; "
                       f"available: {sorted(NAMED_RULESETS)}")
    return NAMED_RULESETS[name]


def rules_fingerprint(rules: Sequence[PartitionRule],
                      min_shard_dim: int = DEFAULT_MIN_SHARD_DIM) -> str:
    """12-hex identity of a LAYOUT — stamped into every checkpoint's
    COMMIT.json topology block so a resume under a DIFFERENT layout is
    refused loudly (the compiled step would otherwise silently relayout
    the restored state).  Hashes pattern order + spec content AND the
    refinement floor: ``min_shard_dim`` changes which leaves the same
    rules actually shard, so two fingerprints agree iff (rules, floor)
    partition every tree identically.  Callers using a non-default
    floor must pass the same value here that they build shardings
    with."""
    h = hashlib.sha256()
    for pat, spec in rules:
        h.update(pat.encode())
        h.update(repr(tuple(spec)).encode())
        h.update(b"\0")
    h.update(f"min_shard_dim={int(min_shard_dim)}".encode())
    return h.hexdigest()[:12]


def tree_shardings(tree, mesh: Mesh, rules: Sequence[PartitionRule], *,
                   strict: bool = False,
                   min_shard_dim: int = DEFAULT_MIN_SHARD_DIM):
    """``NamedSharding`` pytree for ``tree``: the rules matched
    (shape-refined against ``mesh``) and bound to it."""
    import jax

    specs = match_partition_rules(rules, tree, strict=strict, mesh=mesh,
                                  min_shard_dim=min_shard_dim)
    return jax.tree.map(lambda spec: NamedSharding(mesh, spec), specs,
                        is_leaf=lambda x: isinstance(x, P))


def train_state_shardings(model, config, optimizer, mesh: Mesh,
                          rules: Sequence[PartitionRule], *,
                          strict: bool = True,
                          min_shard_dim: int = DEFAULT_MIN_SHARD_DIM):
    """The TrainState's NamedSharding pytree, built ABSTRACTLY (zero
    FLOPs, zero data — ``jax.eval_shape`` over the real constructor) so
    ``make_train_step(mesh=, rules=)`` and the graftaudit registry
    derive the layout from one place.  Strict by default: the shipped
    rulesets cover every leaf, and a new parameter that escapes them
    should fail the build, not silently replicate."""
    import jax
    import jax.numpy as jnp

    from ..train.state import create_train_state

    h, w = config.skeleton.height, config.skeleton.width
    abstract = jax.eval_shape(lambda: create_train_state(
        model, config, optimizer, jax.random.PRNGKey(0),
        jnp.zeros((1, h, w, 3), jnp.float32)))
    return tree_shardings(abstract, mesh, rules, strict=strict,
                          min_shard_dim=min_shard_dim)


def shard_tree(tree, shardings):
    """Place a (host- or device-resident) pytree onto its shardings —
    the materializing twin of :func:`tree_shardings`' abstract map."""
    import jax

    return jax.tree.map(
        lambda x, s: jax.device_put(x, s, may_alias=False), tree, shardings)


def reshard_tree(tree, mesh: Mesh, rules: Sequence[PartitionRule], *,
                 min_shard_dim: int = DEFAULT_MIN_SHARD_DIM):
    """Re-place a restored state pytree onto ``mesh`` under ``rules`` —
    the SHARDED twin of ``mesh.reshard_replicated``, which blindly
    broadcast every leaf (correct only for the replicated regime this
    module retires).  Call ONLY on an actual topology change, for the
    same donated-executable reasons ``reshard_replicated`` documents:
    an unchanged mesh keeps host leaves and lets the jit entry (whose
    ``in_shardings`` carry the same rules) place them."""
    shardings = tree_shardings(tree, mesh, rules,
                               min_shard_dim=min_shard_dim)
    return shard_tree(tree, shardings)


def constrain_batch_sharded(tree, mesh: Optional[Mesh]):
    """``with_sharding_constraint`` every array in ``tree`` to
    batch-over-'data' — the activation annotation inside the
    partitioned train step.  Without it XLA is free to resolve a
    sharding conflict by ALL-GATHERING an activation onto every device
    and carrying on, silently: the program stays correct and quietly
    stops scaling.  No-op when ``mesh`` is None (the single-device and
    replicated paths compile the exact same jaxpr as before)."""
    if mesh is None:
        return tree
    import jax

    def constrain(x):
        spec = P("data", *([None] * (x.ndim - 1)))
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    return jax.tree.map(constrain, tree)


def abstract_with_shardings(tree, shardings):
    """Leafwise twin of ``mesh.abstract_with_sharding``: stamp a
    PER-LEAF sharding pytree onto an abstract tree (the partitioned
    registry program's state, where every leaf has its own spec)."""
    import jax

    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s),
        tree, shardings)


def sharding_summary(shardings) -> Dict[str, int]:
    """{sharded, replicated} leaf counts of a NamedSharding pytree —
    the realized-layout number artifacts record (DIST_DRIVE.json,
    SCALING.json) and logs print."""
    import jax

    def is_ns(x):
        return isinstance(x, NamedSharding)

    leaves = jax.tree.leaves(shardings, is_leaf=is_ns)
    sharded = sum(1 for s in leaves
                  if is_ns(s) and any(a is not None for a in s.spec))
    return {"sharded": sharded, "replicated": len(leaves) - sharded}
