"""AST utilities + intra-procedural dataflow for the graftlint rules.

Everything here is deliberately approximate: the rules encode *bug
classes this repo has actually shipped*, so the analyses are tuned to
catch the shipped shape of each bug (and the fixture tests pin exactly
that) while passing the repaired idioms that replaced them.  Names, not
objects, are tracked; flow through containers is modeled only where a
historical bug needed it (``pending.append(loss)`` → windowed
readback).  Where the analysis cannot tell, it stays silent — a lint
that cries wolf gets disabled, and then catches nothing.
"""
from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------- AST helpers


def add_parents(tree: ast.AST) -> ast.AST:
    """Annotate every node with ``.graftlint_parent`` (None on the root)."""
    tree.graftlint_parent = None  # type: ignore[attr-defined]
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child.graftlint_parent = node  # type: ignore[attr-defined]
    return tree


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "graftlint_parent", None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    p = parent(node)
    while p is not None:
        yield p
        p = parent(p)


def stmt_ancestor(node: ast.AST) -> ast.AST:
    """The nearest enclosing statement (the node itself when it is one)."""
    n: Optional[ast.AST] = node
    while n is not None and not isinstance(n, ast.stmt):
        n = parent(n)
    return n if n is not None else node


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_callee(call: ast.Call) -> Optional[str]:
    return dotted(call.func)


def call_kwarg(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def assigned_names(target: ast.expr) -> List[str]:
    """Simple names bound by an assignment target (tuple/list unpacking
    flattened; starred, attribute and subscript targets skipped)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in target.elts:
            if isinstance(elt, ast.Starred):
                elt = elt.value
            out.extend(assigned_names(elt))
        return out
    return []


def stmt_bound_names(stmt: ast.stmt) -> List[str]:
    """Names (re)bound by a statement — assignment targets, ``for``
    targets, ``with ... as`` names, aug-assign targets."""
    out: List[str] = []
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            out.extend(assigned_names(t))
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        out.extend(assigned_names(stmt.target))
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        out.extend(assigned_names(stmt.target))
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                out.extend(assigned_names(item.optional_vars))
    return out


def functions(tree: ast.AST) -> List[ast.AST]:
    """Every function/lambda-free analysis scope: the module itself plus
    each (async) function definition.  Cached on the tree — every rule
    asks for the same scope list."""
    cached = getattr(tree, "_graftlint_scopes", None)
    if cached is not None:
        return cached
    scopes: List[ast.AST] = [tree]
    scopes.extend(node for node in ast.walk(tree)
                  if isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)))
    tree._graftlint_scopes = scopes  # type: ignore[attr-defined]
    return scopes


def own_statements(scope: ast.AST) -> List[ast.stmt]:
    """Statements belonging to ``scope`` itself — nested function bodies
    excluded (they are their own analysis scopes).  Cached on the scope
    node (several rules re-walk the same scopes)."""
    cached = getattr(scope, "_graftlint_own_stmts", None)
    if cached is not None:
        return cached
    out: List[ast.stmt] = []

    def visit(stmts: Sequence[ast.stmt]) -> None:
        for s in stmts:
            out.append(s)
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            for name in ("body", "orelse", "finalbody"):
                visit(getattr(s, name, []) or [])
            for handler in getattr(s, "handlers", []) or []:
                visit(handler.body)

    body = scope.body if hasattr(scope, "body") else []
    visit(body)
    scope._graftlint_own_stmts = out  # type: ignore[attr-defined]
    return out


def walk_scope(node: ast.AST) -> Iterator[ast.AST]:
    """``ast.walk`` pruned at scope boundaries: nested function / class
    / lambda bodies are not descended into (each is its own analysis
    scope — walking through them is how per-scope state leaks across
    functions).  The def/class node itself is not yielded either: a
    statement that *is* one contributes nothing to its enclosing
    scope's dataflow."""
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)) and n is not node:
            continue
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)) and n is node:
            continue
        stack.extend(ast.iter_child_nodes(n))
    return


def loops_in(scope: ast.AST) -> List[ast.AST]:
    """For/While statements owned by ``scope`` (nested defs excluded)."""
    return [s for s in own_statements(scope)
            if isinstance(s, (ast.For, ast.AsyncFor, ast.While))]


def is_within(node: ast.AST, ancestor: ast.AST) -> bool:
    return any(a is ancestor for a in ancestors(node))


def in_nested_function(node: ast.AST, scope: ast.AST) -> bool:
    """True when ``node`` sits inside a def nested under ``scope`` —
    i.e. it does not execute on scope's own control flow."""
    for a in ancestors(node):
        if a is scope:
            return False
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            return True
    return False


def guarded_within(node: ast.AST, loop: ast.AST) -> bool:
    """True when an ``if`` sits between ``loop``'s body and ``node`` —
    the windowed-readback idiom (``if step % freq == 0: float(...)``)
    that repaired the PR 3 per-batch sync runs the sync conditionally,
    not once per iteration."""
    for a in ancestors(node):
        if a is loop:
            return False
        if isinstance(a, ast.If):
            return True
        if isinstance(a, (ast.For, ast.AsyncFor, ast.While)):
            # an inner loop is the one whose per-iteration cost matters;
            # the caller iterates innermost-first so just stop here
            return False
    return False


def name_loads(scope_node: ast.AST, name: str) -> List[ast.Name]:
    return [n for n in ast.walk(scope_node)
            if isinstance(n, ast.Name) and n.id == name
            and isinstance(n.ctx, ast.Load)]


# ------------------------------------------------------------- device taint

#: dotted-callee prefixes whose call results live on device
DEVICE_NAMESPACES = (
    "jnp.", "jax.numpy.", "jax.lax.", "jax.nn.", "jax.random.",
    "jax.image.", "jax.scipy.",
)

#: callee patterns that return device values in this codebase: jitted
#: step functions and flax ``.apply``
_STEP_NAME_RE = re.compile(r"(^|_)step(_fn)?$")

#: sync sinks: (callee dotted name, arg index) — ``float(x)`` etc.
SYNC_CALLEES = {"float": 0, "int": 0, "jax.device_get": 0,
                "jax.block_until_ready": 0, "np.asarray": 0,
                "numpy.asarray": 0}
#: sync methods on the value itself
SYNC_METHODS = ("item", "tolist", "block_until_ready")
#: of the sinks above, the ones that read back regardless of taint
#: heuristics — jax.* syncs are unambiguous
ALWAYS_SYNC_CALLEES = ("jax.device_get", "jax.block_until_ready")


class DeviceTaint:
    """Forward, flow-insensitive-ish name taint for one analysis scope.

    Two passes over the scope's own statements approximate loop
    back-edges; the result is the set of names that *may* hold device
    values anywhere in the scope.  Sinks then pair that set with
    position (inside an unguarded loop body) to decide.
    """

    def __init__(self, scope: ast.AST, jit_bound: Set[str],
                 extra_producers: Sequence[str] = ()):
        self.scope = scope
        self.jit_bound = jit_bound
        self.extra = [re.compile(p) for p in extra_producers]
        self.tainted: Set[str] = set()
        for _ in range(2):
            self._pass()

    # -- producers ---------------------------------------------------------
    def _producer_call(self, call: ast.Call) -> bool:
        callee = call_callee(call)
        if callee:
            if callee in ("jax.device_get", "np.asarray", "numpy.asarray"):
                return False  # these RETURN host values
            if any(callee.startswith(ns) for ns in DEVICE_NAMESPACES):
                return True
            if callee == "jax.device_put":
                return True
            base = callee.split(".")[-1]
            if _STEP_NAME_RE.search(base):
                return True
            if base == "apply" or callee.endswith(".apply"):
                return True
            if callee.split(".")[0] in self.jit_bound and "." not in callee:
                return True
            if any(p.search(callee) for p in self.extra):
                return True
        # jax.jit(f)(x) / pjit(f)(x): callee is itself a call expression
        if isinstance(call.func, ast.Call):
            inner = call_callee(call.func)
            if inner in ("jax.jit", "jax.pmap", "pjit", "jax.pjit",
                         "jax.experimental.pjit.pjit"):
                return True
        return False

    def is_tainted(self, expr: ast.expr) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in self.tainted
        if isinstance(expr, ast.Attribute):
            return self.is_tainted(expr.value)
        if isinstance(expr, ast.Subscript):
            return self.is_tainted(expr.value)
        if isinstance(expr, ast.BinOp):
            return self.is_tainted(expr.left) or self.is_tainted(expr.right)
        if isinstance(expr, ast.UnaryOp):
            return self.is_tainted(expr.operand)
        if isinstance(expr, ast.IfExp):
            return self.is_tainted(expr.body) or self.is_tainted(expr.orelse)
        if isinstance(expr, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in expr.elts)
        if isinstance(expr, ast.Starred):
            return self.is_tainted(expr.value)
        if isinstance(expr, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            # taint flows out of a comprehension iff it flows in: either
            # the element expression or an iterated source is tainted
            # (the comprehension targets are bound from the iterables)
            if any(self.is_tainted(g.iter) for g in expr.generators):
                return True
            return self.is_tainted(expr.elt)
        if isinstance(expr, ast.Call):
            callee = call_callee(expr)
            if callee in SYNC_CALLEES or (
                    isinstance(expr.func, ast.Attribute)
                    and expr.func.attr in SYNC_METHODS):
                return False  # the sync RESULT is a host value
            if self._producer_call(expr):
                return True
            # a method on a tainted receiver keeps the value on device
            # (loss.mean(), state.replace(...))
            if isinstance(expr.func, ast.Attribute):
                return self.is_tainted(expr.func.value)
            return False
        return False

    # -- one forward pass --------------------------------------------------
    def _pass(self) -> None:
        for stmt in own_statements(self.scope):
            if isinstance(stmt, ast.Assign):
                t = self.is_tainted(stmt.value)
                for target in stmt.targets:
                    for name in assigned_names(target):
                        (self.tainted.add if t
                         else self.tainted.discard)(name)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                t = self.is_tainted(stmt.value)
                for name in assigned_names(stmt.target):
                    (self.tainted.add if t else self.tainted.discard)(name)
            elif isinstance(stmt, ast.AugAssign):
                if self.is_tainted(stmt.value):
                    self.tainted.update(assigned_names(stmt.target))
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                if self.is_tainted(stmt.iter):
                    self.tainted.update(assigned_names(stmt.target))
            elif isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                           ast.Call):
                # container.append(tainted) taints the container — the
                # buffered-readback idiom iterates it later
                call = stmt.value
                if (isinstance(call.func, ast.Attribute)
                        and call.func.attr in ("append", "extend", "add",
                                               "insert")
                        and isinstance(call.func.value, ast.Name)
                        and any(self.is_tainted(a) for a in call.args)):
                    self.tainted.add(call.func.value.id)


def collect_jit_bound(tree: ast.AST) -> Set[str]:
    """Names anywhere in the module assigned from ``jax.jit`` /
    ``jax.pmap`` / ``pjit`` calls — calling them yields device values."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            callee = call_callee(node.value)
            if callee in ("jax.jit", "jax.pmap", "pjit", "jax.pjit"):
                for t in node.targets:
                    out.update(assigned_names(t))
    return out


def sync_call_argument(call: ast.Call) -> Optional[ast.expr]:
    """The device-value operand of a host-sync call, or None when the
    call is not a sync sink."""
    callee = call_callee(call)
    if callee in SYNC_CALLEES:
        idx = SYNC_CALLEES[callee]
        if len(call.args) == 1 + idx and not call.keywords:
            return call.args[idx]
        # np.asarray(x, dtype) converts — a copy, not a zero-cost view
        # readback; float(x)/int(x) never take extra args for arrays
        if callee in ALWAYS_SYNC_CALLEES and call.args:
            return call.args[0]
        return None
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr in SYNC_METHODS and not call.args:
        return call.func.value
    return None
