"""graftlint — a JAX-aware static-analysis suite encoding this repo's
shipped bug classes as enforced rules.

The three worst bugs in this repo's history were statically detectable:
the donated-buffer read that corrupted in-flight checkpoints (PR 5),
the persistent-cache donated-executable corruption on resume (PR 6),
and the per-batch ``float(loss)`` sync that defeated
``device_prefetch`` (PR 3).  Each rule here turns one such postmortem
into a machine-checked invariant; ``tools/lint.py`` is the runner and
``tests/test_graftlint.py::test_self_scan_clean`` keeps the tree clean
in tier-1.

Stdlib-only by construction: linting parses source with ``ast`` and
never imports the linted code, so it runs in seconds with no jax
bring-up and cannot execute repo side effects.

Rules (severity in parentheses; suppression:
``# graftlint: disable=JGL00N -- reason``, reason required):

- JGL001 donation-safety (error)  — reads after ``donate_argnums``
  donation; escaping zero-copy ``np.asarray`` views of state leaves
- JGL002 hidden-host-sync (error) — per-batch ``float()``/``.item()``/
  ``device_get``/... on device values in train/serve/infer loops
- JGL003 recompile-hazard (warning) — jit-in-loop over fresh function
  objects, mutable static args, jitted closures over mutated names
- JGL004 strict-json (error)      — ``json.dumps`` not routed through
  ``obs.events`` strict emission (bare-NaN-token class)
- JGL005 resource-lifecycle (warning) — threads/pools/shm/processes
  without cleanup on any path
- JGL006 metric-names (error)     — Prometheus naming contract at
  ``Registry`` call sites
- JGL007 bare-print (warning)     — stdout prints in library code
- JGL008 dtype-hygiene (warning)  — f64 literals flowing into jnp
  constructors in library code (PRG002's source-tier mirror)
- JGL000 (error)                  — suppressions without a reason,
  unknown rule ids, unparseable files

Config: ``[tool.graftlint]`` in ``pyproject.toml`` (see
``analysis/config.py``).

The sibling subpackage ``analysis.program`` (graftaudit) is the
SECOND tier: it audits what XLA actually compiled for every registered
entry-point program — host-interop primitives, dtype drift, donation
aliasing, constant bloat, sharding coverage, and an HLO cost
fingerprint gated against the committed ``PROGRAM_AUDIT.json``.
Unlike this tier it imports jax (abstract tracing + AOT compiles, zero
data); importing ``analysis`` itself stays stdlib-only.
"""
from .config import ConfigError, LintConfig, load_config  # noqa: F401
from .core import (  # noqa: F401
    GRAFTLINT_VERSION,
    Finding,
    LintResult,
    Rule,
    all_rules,
    iter_lint_files,
    lint_paths,
    lint_source,
    ruleset_hash,
)
