"""The program registry: every compiled entry point the repo ships,
buildable ABSTRACTLY.

Each :class:`ProgramSpec` names one real program — the donated train
step (health sentinel on and off, device-GT variant), the eval step,
the compact and FUSED-decode serve programs per bucket shape (the
latter with a declared bounded `while`: the assembly kernel's
candidate walk), the flip-TTA peaks program, the SWA running average,
the legacy replicated meshed step, and the fully GSPMD-PARTITIONED
train step (rule-sharded state; ISSUE 12) — together with the
declarations the checks verify (donated argnums, bf16-compute,
hot-path status, mesh/sharded-param expectations).  The distilled fast
tier (ISSUE 13) adds three: the student forward and student fused
decode with bf16 PARAM storage (the quantized artifact's programs —
``tools/export_model.py`` gates exports on their blessed fingerprints),
and the distillation train step (student state donated, frozen teacher
variables a non-donated argument).  The on-chip campaign (ISSUE 20)
adds two more: the student fused decode with INT8 weight-only storage
(per-output-channel scales, dequant chain audited in-program by
PRG002's expect_int8 facet), and the fused multi-scale TTA compact
program (the whole scale×rotation grid as one dispatch).

``build()`` returns the jitted callable plus ``ShapeDtypeStruct``
example arguments: tracing/lowering/compiling them runs ZERO model
arithmetic and moves zero real data (``jax.eval_shape`` builds even the
parameter/optimizer trees abstractly).  Programs are registered on the
``tiny`` config: the audit checks *program structure* — transfers,
dtypes, aliasing, sharding — which the depth/width of the flagship
model does not change, and the tiny IMHN keeps the AOT sweep minutes,
not hours, on a CPU host.  Structural deviations the flagship could
introduce (a new primitive, a new dtype) would come from code changes
this registry compiles too.

The registry is append-only by convention: removing a program (or
renaming one) shows up as a loud diff against the committed
``PROGRAM_AUDIT.json``.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

#: batch size used by the batched registry programs — small (abstract
#: tracing cost is shape-independent, but compile time is not) yet >1 so
#: batch semantics (vmapped extraction, batch-dim sharding) are real
_B = 2


@dataclass(frozen=True)
class BuiltProgram:
    """What ``ProgramSpec.build`` returns: a jitted callable plus the
    abstract arguments to trace/lower it with."""

    fn: Callable
    args: Tuple


@dataclass(frozen=True)
class ProgramSpec:
    name: str
    description: str
    #: lazily builds the program — jax and model imports happen inside
    build: Callable[[], BuiltProgram]
    #: hot programs forbid host-interop primitives (PRG001)
    hot: bool = True
    #: positional argnums DECLARED donated — PRG003 verifies the
    #: compiled executable realized every one as an input/output alias
    donate_argnums: Tuple[int, ...] = ()
    #: program is declared bf16-compute: PRG002 requires bf16 to appear
    expect_bf16: bool = False
    #: program is declared int8-quantized (weight-only storage with the
    #: in-program dequant chain): PRG002 requires int8 to appear — the
    #: refusal facet that keeps the quantization chain honest exactly
    #: like the bf16 cast chain
    expect_int8: bool = False
    #: f64 anywhere is an error unless explicitly allowed
    allow_f64: bool = False
    #: a `while` primitive is a hazard unless declared intentional
    allow_while: bool = False
    #: sharding-coverage checks (PRG006) apply
    meshed: bool = False
    #: the program's partition rules must shard >0 donated state leaves
    #: (PRG006's partitioned facet): batch-only sharding — rules that
    #: shard zero leaves — is a failing audit, not a quiet fallback
    expect_sharded_params: bool = False
    #: minimum device count the program needs (the meshed step needs the
    #: virtual 8-device CPU mesh); short hosts record a skip, not a crash
    requires_devices: int = 1
    #: extra tags recorded into the report (e.g. the serve bucket shape)
    tags: Tuple[str, ...] = field(default_factory=tuple)


# --------------------------------------------------------- shared builders


def _tiny_setup(name: str = "tiny"):
    """(config, model, optimizer) for the registry's programs — one
    construction path shared by every spec so the audited programs are
    built exactly like ``tools/train.py`` builds them.  ``name`` selects
    the config (``tiny_student`` for the distilled fast tier's
    programs)."""
    from ...config import get_config
    from ...models import build_model
    from ...train.schedule import step_decay_schedule
    from ...train.state import make_optimizer

    cfg = get_config(name)
    model = build_model(cfg)
    optimizer = make_optimizer(cfg, step_decay_schedule(cfg.train, 10))
    return cfg, model, optimizer


def _abstract_state(cfg, model, optimizer):
    """The TrainState as a ShapeDtypeStruct pytree: parameter shapes,
    optimizer slots and the step counter, built with zero FLOPs."""
    import jax
    import jax.numpy as jnp

    from ...train.state import create_train_state

    h, w = cfg.skeleton.height, cfg.skeleton.width
    return jax.eval_shape(lambda: create_train_state(
        model, cfg, optimizer, jax.random.PRNGKey(0),
        jnp.zeros((1, h, w, 3), jnp.float32)))


def _train_batch(cfg, batch: int):
    """(images, mask_miss, gt) ShapeDtypeStructs on the uint8 wire —
    the shm-ring pipeline's actual feed format."""
    import jax
    import jax.numpy as jnp

    h, w = cfg.skeleton.height, cfg.skeleton.width
    gh, gw = cfg.skeleton.grid_shape
    return (jax.ShapeDtypeStruct((batch, h, w, 3), jnp.uint8),
            jax.ShapeDtypeStruct((batch, gh, gw, 1), jnp.float32),
            jax.ShapeDtypeStruct((batch, gh, gw, cfg.skeleton.num_layers),
                                 jnp.float32))


def _build_train_step(health: bool = False) -> BuiltProgram:
    from ...train.step import make_train_step

    cfg, model, optimizer = _tiny_setup()
    state = _abstract_state(cfg, model, optimizer)
    images, mask, gt = _train_batch(cfg, _B)
    fn = make_train_step(model, cfg, optimizer, health=health)
    return BuiltProgram(fn=fn, args=(state, images, mask, gt))


def _train_donate_argnums():
    from ...train.step import TRAIN_STEP_DONATE_ARGNUMS

    return TRAIN_STEP_DONATE_ARGNUMS


def _build_train_step_device_gt() -> BuiltProgram:
    import jax
    import jax.numpy as jnp

    from ...train.step import make_train_step

    cfg, model, optimizer = _tiny_setup()
    state = _abstract_state(cfg, model, optimizer)
    images, mask, _ = _train_batch(cfg, _B)
    gh, gw = cfg.skeleton.grid_shape
    joints = jax.ShapeDtypeStruct((_B, 4, cfg.skeleton.num_parts, 3),
                                  jnp.float32)
    mask_all = jax.ShapeDtypeStruct((_B, gh, gw, 1), jnp.float32)
    fn = make_train_step(model, cfg, optimizer, device_gt=True)
    return BuiltProgram(fn=fn, args=(state, images, mask, joints, mask_all))


def _build_eval_step() -> BuiltProgram:
    from ...train.step import make_eval_step

    cfg, model, optimizer = _tiny_setup()
    state = _abstract_state(cfg, model, optimizer)
    images, mask, gt = _train_batch(cfg, _B)
    fn = make_eval_step(model, cfg)
    return BuiltProgram(fn=fn, args=(state, images, mask, gt))


def _build_swa_update() -> BuiltProgram:
    import jax

    from ...train.state import start_swa, update_swa

    cfg, model, optimizer = _tiny_setup()
    state = _abstract_state(cfg, model, optimizer)
    swa_state = jax.eval_shape(start_swa, state)
    return BuiltProgram(fn=jax.jit(update_swa), args=(swa_state,))


def _abstract_predictor(name: str = "tiny", bf16_params: bool = False,
                        int8_params: bool = False):
    """A Predictor over abstract variables: ``_ensemble_fn`` only ever
    threads the variables through to the jitted program, so the
    ShapeDtypeStruct tree traces/lowers exactly like real weights.

    ``bf16_params=True`` casts the abstract parameter tree to bf16
    storage (via ``utils.precision.bf16_params`` under ``eval_shape`` —
    the SAME cast ``tools/export_model.py --dtype bf16`` applies to real
    weights, so the audited program and the exported artifact share one
    fingerprint).  ``int8_params=True`` runs the weight-only int8
    quantization the same way (``apply_serve_dtype("int8", ...)`` under
    ``eval_shape``): int8 weights + fp32 scales as program inputs, the
    dequant chain as program ops."""
    import jax

    from ...infer.predict import Predictor

    cfg, model, _ = _tiny_setup(name)
    h, w = cfg.skeleton.height, cfg.skeleton.width

    def init():
        import jax.numpy as jnp

        return model.init(jax.random.PRNGKey(0),
                          jnp.zeros((1, h, w, 3), jnp.float32), train=False)

    variables = jax.eval_shape(init)
    if bf16_params:
        from ...utils.precision import bf16_params as cast

        variables = jax.eval_shape(cast, variables)
    if int8_params:
        from ...utils.precision import DequantizingModel, quantize_int8

        variables = jax.eval_shape(quantize_int8, variables)
        model = DequantizingModel(model)
    return cfg, Predictor(model, variables, cfg.skeleton)


def _build_serve_compact() -> BuiltProgram:
    import jax
    import jax.numpy as jnp

    _, p = _abstract_predictor()
    b = p.bucket
    fn = p.compact_program((b, b))
    img = jax.ShapeDtypeStruct((b, b, 3), jnp.float32)
    valid = jax.ShapeDtypeStruct((), jnp.int32)
    return BuiltProgram(fn=fn, args=(p.variables, img, valid, valid))


def _build_serve_compact_batch() -> BuiltProgram:
    import jax
    import jax.numpy as jnp

    _, p = _abstract_predictor()
    b = p.bucket
    fn = p.compact_program((b, b), batch=_B)
    imgs = jax.ShapeDtypeStruct((_B, b, b, 3), jnp.float32)
    valid = jax.ShapeDtypeStruct((_B,), jnp.int32)
    return BuiltProgram(fn=fn, args=(p.variables, imgs, valid, valid))


def _build_serve_decode() -> BuiltProgram:
    import jax
    import jax.numpy as jnp

    _, p = _abstract_predictor()
    b = p.bucket
    fn = p.decode_program((b, b))
    img = jax.ShapeDtypeStruct((b, b, 3), jnp.float32)
    valid = jax.ShapeDtypeStruct((), jnp.int32)
    return BuiltProgram(fn=fn, args=(p.variables, img, valid, valid))


def _build_serve_decode_batch() -> BuiltProgram:
    import jax
    import jax.numpy as jnp

    _, p = _abstract_predictor()
    b = p.bucket
    fn = p.decode_program((b, b), batch=_B)
    imgs = jax.ShapeDtypeStruct((_B, b, b, 3), jnp.float32)
    valid = jax.ShapeDtypeStruct((_B,), jnp.int32)
    return BuiltProgram(fn=fn, args=(p.variables, imgs, valid, valid))


def _build_student_forward() -> BuiltProgram:
    """The student fast tier's flip-TTA forward + on-device NMS, with
    bf16 PARAM STORAGE — the quantized artifact's program
    (``tools/export_model.py --config tiny_student --dtype bf16``)."""
    import jax
    import jax.numpy as jnp

    _, p = _abstract_predictor("tiny_student", bf16_params=True)
    b = p.bucket
    fn = p.peaks_program((b, b))
    img = jax.ShapeDtypeStruct((b, b, 3), jnp.float32)
    valid = jax.ShapeDtypeStruct((), jnp.int32)
    return BuiltProgram(fn=fn, args=(p.variables, img, valid, valid))


def _build_student_serve_decode() -> BuiltProgram:
    """The student tier's FUSED end-to-end decode serve program (bf16
    param storage): what the cascade's fast lane actually dispatches,
    and what the gated export serializes."""
    import jax
    import jax.numpy as jnp

    _, p = _abstract_predictor("tiny_student", bf16_params=True)
    b = p.bucket
    fn = p.decode_program((b, b))
    img = jax.ShapeDtypeStruct((b, b, 3), jnp.float32)
    valid = jax.ShapeDtypeStruct((), jnp.int32)
    return BuiltProgram(fn=fn, args=(p.variables, img, valid, valid))


def _build_student_serve_decode_int8() -> BuiltProgram:
    """The student tier's fused decode serve program with INT8 weight
    storage (``tools/export_model.py --config tiny_student --dtype
    int8``): int8 weights + per-output-channel fp32 scales as inputs,
    the dequant multiply traced into the program — PRG002's expect_int8
    facet refuses the artifact if the chain ever folds out."""
    import jax
    import jax.numpy as jnp

    _, p = _abstract_predictor("tiny_student", int8_params=True)
    b = p.bucket
    fn = p.decode_program((b, b))
    img = jax.ShapeDtypeStruct((b, b, 3), jnp.float32)
    valid = jax.ShapeDtypeStruct((), jnp.int32)
    return BuiltProgram(fn=fn, args=(p.variables, img, valid, valid))


def _build_fused_tta_compact() -> BuiltProgram:
    """The FUSED multi-scale TTA program (``Predictor._fused_grid_fn``):
    the whole (scale × rotation) grid — rotation lanes and width-flips
    batched into the lane dim, on-device regrid + averaging + compact
    extraction — as ONE program, the accuracy tier's
    1-dispatch-per-image path.  Registered on a 2-scale × 2-rotation
    grid so the lane batching, the rotation warps and the multi-shape
    accumulate are all structurally audited."""
    import jax
    import jax.numpy as jnp

    _, p = _abstract_predictor()
    b = p.bucket
    # two scales (full bucket + a half-valid entry) × (0°, 30°)
    entries = (((b, b), (b, b)), ((b, b), (b // 2, b // 2)))
    angles = (0.0, 30.0)
    prm = p.params
    fn = p._fused_grid_fn(entries, (b, b), angles, prm.thre1,
                          p._compact_spec(prm), "compact")
    imgs = [jax.ShapeDtypeStruct((b, b, 3), jnp.float32)
            for _ in entries]
    return BuiltProgram(fn=fn, args=(p.variables, *imgs))


def _build_distill_train_step() -> BuiltProgram:
    """The heatmap-distillation step (``train.distill``): student state
    DONATED, the frozen teacher's variables a second NON-donated
    argument — PRG003 verifies the alias realized on the student state
    only, with the teacher buffers untouched across steps."""
    import jax

    from ...train.distill import make_distill_train_step

    s_cfg, s_model, s_opt = _tiny_setup("tiny_student")
    t_cfg, t_model, _ = _tiny_setup("tiny")
    state = _abstract_state(s_cfg, s_model, s_opt)
    h, w = s_cfg.skeleton.height, s_cfg.skeleton.width

    def t_init():
        import jax.numpy as jnp

        return t_model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, h, w, 3), jnp.float32),
                            train=False)

    teacher_vars = jax.eval_shape(t_init)
    images, mask, gt = _train_batch(s_cfg, _B)
    fn = make_distill_train_step(s_model, t_model, s_cfg, s_opt)
    return BuiltProgram(fn=fn,
                        args=(state, teacher_vars, images, mask, gt))


def _build_flip_tta_peaks() -> BuiltProgram:
    import jax
    import jax.numpy as jnp

    _, p = _abstract_predictor()
    b = p.bucket
    fn = p.peaks_program((b, b))
    img = jax.ShapeDtypeStruct((b, b, 3), jnp.float32)
    valid = jax.ShapeDtypeStruct((), jnp.int32)
    return BuiltProgram(fn=fn, args=(p.variables, img, valid, valid))


def _build_train_step_mesh() -> BuiltProgram:
    """The legacy meshed train step: state REPLICATED, batch sharded
    over 'data' on a ('data', 'model') mesh — the dryrun regime
    ``train_step_partitioned`` retires, kept registered so the two
    layouts stay separately fingerprinted (and the replicated program
    keeps compiling for topology-adjust resumes of old checkpoints)."""
    from ...parallel.mesh import (
        abstract_with_sharding,
        batch_sharding,
        make_mesh,
        replicated,
    )
    from ...train.step import make_train_step

    cfg, model, optimizer = _tiny_setup()
    state = _abstract_state(cfg, model, optimizer)
    mesh = make_mesh(data=4, model=2)
    state = abstract_with_sharding(state, replicated(mesh))
    images, mask, gt = (abstract_with_sharding(a, batch_sharding(mesh))
                        for a in _train_batch(cfg, 4))
    fn = make_train_step(model, cfg, optimizer)
    return BuiltProgram(fn=fn, args=(state, images, mask, gt))


def _build_train_step_partitioned(rules=None) -> BuiltProgram:
    """The fully GSPMD-PARTITIONED train step (ISSUE 12's tentpole):
    param/optimizer state sharded by the IMHN partition ruleset (wide
    conv kernels' output channels over 'model'), batch over 'data',
    activations pinned by with_sharding_constraint, state donated with
    in==out shardings.  PRG003 verifies the alias held UNDER sharding
    (per-device shard bytes), PRG006 that the rules sharded >0 state
    leaves.  ``rules`` overrides the ruleset — the seeded-regression
    fixture passes the all-replicated set to prove the zero-leaf case
    flags."""
    from ...parallel.mesh import abstract_with_sharding, batch_sharding, \
        make_mesh
    from ...parallel.partition import (
        abstract_with_shardings,
        imhn_partition_rules,
        train_state_shardings,
    )
    from ...train.step import make_train_step

    cfg, model, optimizer = _tiny_setup()
    rules = imhn_partition_rules() if rules is None else rules
    mesh = make_mesh(data=4, model=2)
    state_sh = train_state_shardings(model, cfg, optimizer, mesh, rules)
    state = abstract_with_shardings(
        _abstract_state(cfg, model, optimizer), state_sh)
    images, mask, gt = (abstract_with_sharding(a, batch_sharding(mesh))
                        for a in _train_batch(cfg, 4))
    fn = make_train_step(model, cfg, optimizer, mesh=mesh, rules=rules,
                         state_shardings=state_sh)
    return BuiltProgram(fn=fn, args=(state, images, mask, gt))


# ---------------------------------------------------------------- registry


def program_registry() -> List[ProgramSpec]:
    """Every program the audit sweeps, in stable (committed-artifact)
    order.  ≥ 6 real entry points by construction — the acceptance
    floor of the audit tier."""
    # the declaration the audit verifies is the step's OWN constant —
    # if train.step ever changes what it donates, the registry follows
    donate = _train_donate_argnums()
    return [
        ProgramSpec(
            name="train_step",
            description="donated jitted train step (uint8 wire, focal "
                        "loss, abnormal-batch select), health off",
            build=_build_train_step,
            donate_argnums=donate, expect_bf16=True),
        ProgramSpec(
            name="train_step_health",
            description="donated train step with the health sentinel's "
                        "grad-norm extra output",
            build=lambda: _build_train_step(health=True),
            donate_argnums=donate, expect_bf16=True),
        ProgramSpec(
            name="train_step_device_gt",
            description="donated train step with on-device GT synthesis "
                        "(joints wire instead of label maps)",
            build=_build_train_step_device_gt,
            donate_argnums=donate, expect_bf16=True),
        ProgramSpec(
            name="eval_step",
            description="jitted validation step (loss only, running BN "
                        "averages)",
            build=_build_eval_step, expect_bf16=True),
        ProgramSpec(
            name="swa_update",
            description="SWA running-average parameter update",
            build=_build_swa_update),
        ProgramSpec(
            name="serve_compact_b1",
            description="compact serve program, bucket 128, batch 1 "
                        "(deadline-straggler singleton flush)",
            build=_build_serve_compact,
            expect_bf16=True, tags=("bucket=128x128", "batch=1")),
        ProgramSpec(
            name="serve_compact_batch_b2",
            description="compact-batch serve program, bucket 128, "
                        "batch 2 (the DynamicBatcher's pow2-chunk unit)",
            build=_build_serve_compact_batch,
            expect_bf16=True, tags=("bucket=128x128", f"batch={_B}")),
        ProgramSpec(
            name="serve_decode_b1",
            description="FUSED end-to-end decode serve program, bucket "
                        "128, batch 1: forward + compact extraction + "
                        "greedy assembly (the device-decode lane's "
                        "singleton flush).  allow_while: the assembly's "
                        "candidate walk is a DECLARED bounded "
                        "lax.while_loop (trip count <= the candidate "
                        "cap; ops/assembly.py)",
            build=_build_serve_decode,
            expect_bf16=True, allow_while=True,
            tags=("bucket=128x128", "batch=1")),
        ProgramSpec(
            name="serve_decode_batch_b2",
            description="FUSED end-to-end decode serve program, bucket "
                        "128, batch 2 (the device-decode lane's "
                        "pow2-chunk unit); declared bounded while, as "
                        "serve_decode_b1",
            build=_build_serve_decode_batch,
            expect_bf16=True, allow_while=True,
            tags=("bucket=128x128", f"batch={_B}")),
        ProgramSpec(
            name="student_forward",
            description="student fast-tier flip-TTA ensemble + "
                        "on-device NMS (tiny_student, bf16 param "
                        "storage — the quantized artifact's forward)",
            build=_build_student_forward, expect_bf16=True,
            tags=("tier=student", "params=bf16")),
        ProgramSpec(
            name="student_serve_decode_b1",
            description="student FUSED end-to-end decode serve "
                        "program, bucket 128, batch 1, bf16 param "
                        "storage — the cascade fast lane's program and "
                        "the gated export's subject; declared bounded "
                        "while, as serve_decode_b1",
            build=_build_student_serve_decode,
            expect_bf16=True, allow_while=True,
            tags=("tier=student", "params=bf16", "bucket=128x128",
                  "batch=1")),
        ProgramSpec(
            name="student_serve_decode_int8_b1",
            description="student FUSED decode serve program, bucket "
                        "128, batch 1, INT8 weight-only storage "
                        "(per-output-channel scales, dequant chain in "
                        "the program) — the int8 artifact's subject; "
                        "declared bounded while, as serve_decode_b1",
            build=_build_student_serve_decode_int8,
            expect_bf16=True, expect_int8=True, allow_while=True,
            tags=("tier=student", "params=int8", "bucket=128x128",
                  "batch=1")),
        ProgramSpec(
            name="fused_tta_compact",
            description="FUSED multi-scale TTA compact program: 2 "
                        "scales x 2 rotations with flip pairs in the "
                        "lane dim, device-resident regrid + averaging "
                        "+ compact extraction in ONE dispatch (the "
                        "accuracy tier's grid path)",
            build=_build_fused_tta_compact, expect_bf16=True,
            tags=("grid=2x2", "bucket=128x128")),
        ProgramSpec(
            name="distill_train_step",
            description="heatmap-distillation train step "
                        "(tiny_student from tiny): student state "
                        "donated, teacher variables a non-donated "
                        "second argument, teacher forward folded in "
                        "under stop_gradient",
            build=_build_distill_train_step,
            donate_argnums=donate, expect_bf16=True,
            tags=("tier=student",)),
        ProgramSpec(
            name="flip_tta_peaks",
            description="flip-TTA ensemble + on-device NMS peaks "
                        "program (the fast single-scale path)",
            build=_build_flip_tta_peaks, expect_bf16=True),
        ProgramSpec(
            name="train_step_mesh",
            description="GSPMD train step on a ('data': 4, 'model': 2) "
                        "mesh — state replicated, batch sharded (the "
                        "legacy dryrun layout, kept for old-checkpoint "
                        "resumes)",
            build=_build_train_step_mesh,
            donate_argnums=donate, expect_bf16=True, meshed=True,
            requires_devices=8),
        ProgramSpec(
            name="train_step_partitioned",
            description="fully GSPMD-PARTITIONED train step on a "
                        "('data': 4, 'model': 2) mesh — param/optimizer "
                        "state sharded by the IMHN partition rules "
                        "(wide conv kernels over 'model'), batch over "
                        "'data', donated with in==out shardings",
            build=_build_train_step_partitioned,
            donate_argnums=donate, expect_bf16=True, meshed=True,
            expect_sharded_params=True, requires_devices=8),
    ]


def get_program(name: str) -> Optional[ProgramSpec]:
    for spec in program_registry():
        if spec.name == name:
            return spec
    return None
