"""Audit orchestration: sweep the program registry, run the checks,
fingerprint, and gate against the committed golden registry.

The committed ``PROGRAM_AUDIT.json`` at the repo root IS the golden
registry: ``tools/program_audit.py`` audits the current tree, compares
against it, and only ``--bless`` rewrites it — so any drift (a new
transfer, a new dtype, a lost alias, a >tolerance cost jump) is a loud
diff against a reviewed artifact, never a silent change.

A program that fails to build/trace/compile is a PRG000 error — a
crashed audit must never read as a clean one (the graftlint exit-code
contract, applied here).
"""
from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .checks import (
    AuditFinding,
    run_compiled_checks,
    run_trace_checks,
)
from .compiled import compile_program
from .config import AuditConfig
from .fingerprint import (
    compare_compiled,
    compare_trace,
    compiled_fingerprint,
    trace_fingerprint,
)
from .registry import ProgramSpec, program_registry
from .trace import trace_program

GRAFTAUDIT_VERSION = "1.0.0"

#: audit levels, cheap to expensive: ``trace`` = jaxpr only (tier-1's
#: sweep), ``compile`` = + AOT lower/compile on the CPU backend
LEVELS = ("trace", "compile")


def audit_ruleset_hash() -> str:
    """12 hex chars over the program subpackage's own source — the
    graftaudit twin of graftlint's ``ruleset_hash()`` (which covers the
    whole analysis package, this subtree included).  Fingerprints and
    verdicts are only comparable between identical check sets."""
    h = hashlib.sha256()
    pkg = os.path.dirname(os.path.abspath(__file__))
    for fn in sorted(f for f in os.listdir(pkg) if f.endswith(".py")):
        h.update(fn.encode())
        with open(os.path.join(pkg, fn), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:12]


@dataclass
class ProgramVerdict:
    name: str
    description: str
    #: "ok" | "findings" | "skipped" | "crashed"
    status: str
    findings: List[AuditFinding] = field(default_factory=list)
    #: fingerprint-drift diff records (field/golden/current/drift_pct)
    drift: List[Dict] = field(default_factory=list)
    fingerprint: Dict = field(default_factory=dict)
    note: Optional[str] = None
    tags: List[str] = field(default_factory=list)
    declarations: Dict = field(default_factory=dict)

    def as_dict(self) -> Dict:
        return {
            "description": self.description,
            "status": self.status,
            "tags": list(self.tags),
            "declarations": self.declarations,
            "findings": [f.as_dict() for f in self.findings],
            "drift": list(self.drift),
            "fingerprint": self.fingerprint,
            "note": self.note,
        }


@dataclass
class AuditReport:
    level: str
    verdicts: List[ProgramVerdict] = field(default_factory=list)
    jax_version: str = ""
    backend: str = ""
    golden_jax_version: Optional[str] = None

    def counts(self) -> Dict[str, int]:
        from ..config import SEVERITIES

        out = {s: 0 for s in reversed(SEVERITIES)}
        for v in self.verdicts:
            for f in v.findings:
                out[f.severity] += 1
        return out

    @property
    def ok(self) -> bool:
        return self.counts()["error"] == 0

    def findings(self) -> List[AuditFinding]:
        return [f for v in self.verdicts for f in v.findings]

    def as_dict(self) -> Dict:
        return {
            "graftaudit": {"version": GRAFTAUDIT_VERSION,
                           "ruleset": audit_ruleset_hash()},
            "jax_version": self.jax_version,
            "backend": self.backend,
            "level": self.level,
            "programs": {v.name: v.as_dict() for v in self.verdicts},
            "counts": self.counts(),
            "ok": self.ok,
        }


def _declarations(spec: ProgramSpec) -> Dict:
    return {
        "hot": spec.hot,
        "donate_argnums": list(spec.donate_argnums),
        "expect_bf16": spec.expect_bf16,
        "allow_f64": spec.allow_f64,
        "allow_while": spec.allow_while,
        "meshed": spec.meshed,
        "expect_sharded_params": spec.expect_sharded_params,
        "requires_devices": spec.requires_devices,
    }


def _crash_finding(spec: ProgramSpec, stage: str, exc: BaseException
                   ) -> AuditFinding:
    return AuditFinding(
        program=spec.name, rule="PRG000", severity="error",
        message=f"audit {stage} crashed: {type(exc).__name__}: {exc} — "
                "a program that cannot be audited must not read as clean")


def audit_program(spec: ProgramSpec, level: str = "compile",
                  config: Optional[AuditConfig] = None,
                  golden: Optional[Dict] = None,
                  drift_severity: str = "error") -> ProgramVerdict:
    """Audit one registry program.  ``golden`` is this program's entry
    from the committed registry (``{"fingerprint": {...}}``) or None;
    ``drift_severity`` lets callers downgrade PRG007 when the golden
    was recorded under a different jax version."""
    import jax

    config = config or AuditConfig()
    verdict = ProgramVerdict(name=spec.name, description=spec.description,
                             status="ok", tags=list(spec.tags),
                             declarations=_declarations(spec))

    if spec.requires_devices > len(jax.devices()):
        verdict.status = "skipped"
        verdict.note = (f"needs {spec.requires_devices} devices, host has "
                        f"{len(jax.devices())} (run under XLA_FLAGS="
                        "--xla_force_host_platform_device_count=8)")
        return verdict

    try:
        built = spec.build()
    except Exception as e:  # noqa: BLE001 — crash must surface as finding
        verdict.status = "crashed"
        verdict.findings.append(_crash_finding(spec, "build", e))
        return verdict

    try:
        trace = trace_program(built)
    except Exception as e:  # noqa: BLE001 — crash must surface as finding
        verdict.status = "crashed"
        verdict.findings.append(_crash_finding(spec, "trace", e))
        return verdict

    verdict.findings.extend(run_trace_checks(spec, trace, config))
    verdict.fingerprint = {"trace": trace_fingerprint(trace)}

    if level == "compile":
        try:
            compiled, _ = compile_program(built)
        except Exception as e:  # noqa: BLE001 — crash must surface
            verdict.status = "crashed"
            verdict.findings.append(_crash_finding(spec, "compile", e))
            return verdict
        verdict.findings.extend(
            run_compiled_checks(spec, built, compiled, config))
        verdict.fingerprint["compiled"] = compiled_fingerprint(compiled)

    if golden:
        gfp = golden.get("fingerprint", {})
        drift = compare_trace(gfp.get("trace"),
                              verdict.fingerprint["trace"],
                              config.cost_tolerance_pct)
        if level == "compile" and "compiled" in verdict.fingerprint:
            drift += compare_compiled(gfp.get("compiled"),
                                      verdict.fingerprint["compiled"],
                                      config.cost_tolerance_pct)
        verdict.drift = drift
        if drift:
            fields = ", ".join(
                f"{d['field']} {d['golden']!r}->{d['current']!r}"
                + (f" ({d['drift_pct']}%)" if d.get("drift_pct") else "")
                for d in drift)
            verdict.findings.append(AuditFinding(
                program=spec.name, rule="PRG007",
                severity=config.severity.get("PRG007", drift_severity),
                message="fingerprint drifted from the committed golden "
                        f"registry: {fields} — if intentional, bless "
                        "with tools/program_audit.py --bless"))

    if verdict.findings:
        verdict.status = "findings"
    return verdict


def audit_registry(level: str = "compile",
                   config: Optional[AuditConfig] = None,
                   golden: Optional[Dict] = None,
                   names: Optional[List[str]] = None) -> AuditReport:
    """Sweep the program registry.  ``golden`` is the parsed committed
    ``PROGRAM_AUDIT.json`` (or None to skip drift gating); ``names``
    restricts the sweep."""
    import jax

    assert level in LEVELS, level
    config = config or AuditConfig()
    golden_programs = (golden or {}).get("programs", {})
    golden_jax = (golden or {}).get("jax_version")
    # structural fingerprints are only exact within one jax version: a
    # golden recorded elsewhere still gates, but as warnings
    drift_severity = ("error" if not golden or golden_jax == jax.__version__
                      else "warning")

    report = AuditReport(level=level, jax_version=jax.__version__,
                         backend=jax.default_backend(),
                         golden_jax_version=golden_jax)
    for spec in program_registry():
        if names is not None and spec.name not in names:
            continue
        if spec.name in config.exclude:
            verdict = ProgramVerdict(
                name=spec.name, description=spec.description,
                status="skipped", tags=list(spec.tags),
                declarations=_declarations(spec),
                note="excluded via [tool.graftaudit] exclude")
            report.verdicts.append(verdict)
            continue
        report.verdicts.append(audit_program(
            spec, level=level, config=config,
            golden=golden_programs.get(spec.name),
            drift_severity=drift_severity))
    return report
