"""Abstract jaxpr tracing and structural extraction.

``jax.make_jaxpr`` over ``ShapeDtypeStruct``s runs the Python of a
program once under tracing — no device math, no data — and yields the
full jaxpr.  This module walks it (recursing into every sub-jaxpr:
pjit calls, scan/while/cond bodies, custom-derivative wrappers) and
reduces it to the structural facts the checks and the trace-level
fingerprint consume: primitive counts, the dtype lattice, baked-in
constant sizes, host-callback sites, and control-flow shape.

Trace-level work is CHEAP (~1 s/program for the registry) — it is what
the tier-1 sweep runs on every program; the expensive AOT compile tier
lives in ``compiled.py``.
"""
from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

#: primitives that round-trip through the host — forbidden in hot
#: programs (PRG001).  ``debug_callback`` is what ``jax.debug.print``
#: lowers to; infeed/outfeed are the raw host-transfer ops.
HOST_INTEROP_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed",
})

#: control-flow primitives tracked for the dynamic-shape/while hazard
_WHILE_PRIMITIVES = frozenset({"while"})
_BOUNDED_LOOP_PRIMITIVES = frozenset({"scan"})


@dataclass
class TraceInfo:
    """Structural summary of one program's jaxpr."""

    eqn_count: int = 0
    primitives: Counter = field(default_factory=Counter)
    dtypes: set = field(default_factory=set)
    #: host-interop primitive name -> occurrence count
    callbacks: Counter = field(default_factory=Counter)
    while_count: int = 0
    scan_count: int = 0
    #: byte size of every jaxpr constant (closure-captured arrays baked
    #: into the program)
    const_bytes: List[int] = field(default_factory=list)
    #: "shape/dtype" signature per flattened input / output
    in_signature: List[str] = field(default_factory=list)
    out_signature: List[str] = field(default_factory=list)

    @property
    def const_total(self) -> int:
        return sum(self.const_bytes)

    @property
    def const_max(self) -> int:
        return max(self.const_bytes, default=0)


def _aval_sig(aval) -> str:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return str(aval)
    return f"{'x'.join(map(str, shape))}/{dtype}"


def _nbytes(value) -> int:
    size = getattr(value, "size", None)
    itemsize = getattr(value, "itemsize", None)
    if itemsize is None:
        itemsize = getattr(getattr(value, "dtype", None), "itemsize", 0)
    if size is None or not itemsize:
        return 0
    return int(size) * int(itemsize)


def _record_aval(info: TraceInfo, aval) -> None:
    dtype = getattr(aval, "dtype", None)
    if dtype is not None:
        info.dtypes.add(str(dtype))


def _walk_jaxpr(jaxpr, info: TraceInfo, seen: set) -> None:
    """Accumulate one (inner) jaxpr into ``info``, recursing into every
    sub-jaxpr found in equation params."""
    import jax

    if id(jaxpr) in seen:  # a shared sub-jaxpr counts once
        return
    seen.add(id(jaxpr))

    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        _record_aval(info, getattr(v, "aval", None))
    for eqn in jaxpr.eqns:
        info.eqn_count += 1
        name = eqn.primitive.name
        info.primitives[name] += 1
        if name in HOST_INTEROP_PRIMITIVES:
            info.callbacks[name] += 1
        if name in _WHILE_PRIMITIVES:
            info.while_count += 1
        if name in _BOUNDED_LOOP_PRIMITIVES:
            info.scan_count += 1
        for ov in eqn.outvars:
            _record_aval(info, getattr(ov, "aval", None))
        for value in eqn.params.values():
            items = value if isinstance(value, (list, tuple)) else (value,)
            for item in items:
                if isinstance(item, jax.core.ClosedJaxpr):
                    # consts dedup with the same `seen` discipline as
                    # equations: a sub-jaxpr shared by two call sites
                    # bakes its constants into the program ONCE
                    if id(item) not in seen:
                        seen.add(id(item))
                        for const in item.consts:
                            info.const_bytes.append(_nbytes(const))
                    _walk_jaxpr(item.jaxpr, info, seen)
                elif isinstance(item, jax.core.Jaxpr):
                    _walk_jaxpr(item, info, seen)


def trace_program(built) -> TraceInfo:
    """Trace a :class:`~.registry.BuiltProgram` abstractly and return
    its :class:`TraceInfo`.  Zero model FLOPs execute."""
    import jax

    closed = jax.make_jaxpr(built.fn)(*built.args)
    info = TraceInfo()
    for const in closed.consts:
        info.const_bytes.append(_nbytes(const))
    _walk_jaxpr(closed.jaxpr, info, seen=set())
    info.in_signature = [_aval_sig(v.aval) for v in closed.jaxpr.invars]
    info.out_signature = [_aval_sig(v.aval) for v in closed.jaxpr.outvars]
    return info


def _shard_factor(leaf) -> int:
    """How many devices split this leaf: the product of mesh-axis sizes
    named in its ``NamedSharding`` spec (1 for replicated / unsharded
    leaves).  A PARTITIONED donated leaf aliases only its per-device
    shard, so PRG003's expected alias bytes must divide accordingly —
    ``memory_analysis`` reports per-device bytes."""
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    mesh = getattr(sharding, "mesh", None)
    if spec is None or mesh is None:
        return 1
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    factor = 1
    for axes in spec:
        if axes is None:
            continue
        for name in (axes if isinstance(axes, tuple) else (axes,)):
            factor *= int(sizes.get(name, 1))
    return max(factor, 1)


def donated_leaves(built, donate_argnums: Tuple[int, ...]
                   ) -> Tuple[int, int]:
    """(leaf count, total per-device bytes) of the flattened donated
    arguments — what PRG003 expects the compiled executable to alias.
    Sharded leaves (``ShapeDtypeStruct.sharding`` carrying a spec)
    count their per-device shard, matching ``memory_analysis``'s
    per-device accounting."""
    import jax

    count = 0
    total = 0
    for i in donate_argnums:
        for leaf in jax.tree.leaves(built.args[i]):
            count += 1
            total += _nbytes(leaf) // _shard_factor(leaf)
    return count, total
