"""The program-audit checks (PRG001–PRG007).

Each check is a pure function over the structural summaries
(``TraceInfo`` / ``CompiledInfo``) plus the program's declarations
(``ProgramSpec``); findings carry the program name instead of a source
location — the "line number" of a compiled-program defect is the
program itself.

Severity defaults can be overridden per check via
``[tool.graftaudit.severity]`` (same mechanism as graftlint).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from ..config import SEVERITIES
from .compiled import CompiledInfo, _spec_is_sharded
from .config import AuditConfig
from .registry import ProgramSpec
from .trace import TraceInfo, donated_leaves


@dataclass(frozen=True)
class AuditFinding:
    program: str
    rule: str
    severity: str
    message: str

    def format(self) -> str:
        return (f"{self.program}: {self.severity.upper()} {self.rule} "
                f"{self.message}")

    def as_dict(self) -> dict:
        return {"program": self.program, "rule": self.rule,
                "severity": self.severity, "message": self.message}


@dataclass(frozen=True)
class ProgramRule:
    id: str
    name: str
    severity: str
    doc: str


#: the rule table — ``tools/program_audit.py --rules`` prints it and
#: TRAINING.md §8a mirrors it
PROGRAM_RULES = (
    ProgramRule(
        "PRG001", "host-interop", "error",
        "host round-trip primitives (pure_callback/io_callback/"
        "debug_callback/infeed/outfeed) inside a hot program — every "
        "dispatch would stall the device on the host"),
    ProgramRule(
        "PRG002", "dtype-drift", "error",
        "float64 anywhere in the program (silent upcasts double memory "
        "and are 10-100x slower on TPU), a program declared "
        "bf16-compute that compiled with no bf16 left in it, or a "
        "program declared int8-quantized (expect_int8) whose jaxpr "
        "carries no int8 — the dequant chain was folded out and the "
        "artifact silently serves full-precision weights"),
    ProgramRule(
        "PRG003", "donation-aliasing", "error",
        "a donate_argnums declaration the compiled executable did not "
        "realize as input_output_alias entries — jax drops donation "
        "silently, and an unaliased donated buffer is exactly the "
        "PR 5/6 corruption-or-2x-memory class"),
    ProgramRule(
        "PRG004", "constant-bloat", "warning",
        "a giant literal baked into the jaxpr (closure-captured array) "
        "— it is re-uploaded with every executable and bloats the "
        "compile cache"),
    ProgramRule(
        "PRG005", "dynamic-while", "warning",
        "a `while` primitive in a program that did not declare one — "
        "unbounded trip counts defeat static scheduling and can hide "
        "data-dependent host syncs"),
    ProgramRule(
        "PRG006", "sharding-coverage", "error",
        "a meshed program whose inputs are all left unconstrained by "
        "the partition rules, a program declaring sharded parameters "
        "(expect_sharded_params) whose compiled state leaves are all "
        "replicated — rules that shard zero leaves — or a donated leaf "
        "whose input/output shardings diverge (the alias cannot be "
        "established)"),
    ProgramRule(
        "PRG007", "fingerprint-drift", "error",
        "the program's fingerprint (cost analysis, structure) drifted "
        "beyond tolerance from the committed golden registry — bless "
        "intentional changes with tools/program_audit.py --bless"),
)

_RULES_BY_ID = {r.id: r for r in PROGRAM_RULES}


def _make(config: AuditConfig, spec: ProgramSpec, rule_id: str,
          message: str) -> AuditFinding:
    rule = _RULES_BY_ID[rule_id]
    severity = config.severity.get(rule_id, rule.severity)
    assert severity in SEVERITIES, severity
    return AuditFinding(program=spec.name, rule=rule_id,
                        severity=severity, message=message)


# ------------------------------------------------------- trace-level checks


def check_host_interop(spec: ProgramSpec, trace: TraceInfo,
                       config: AuditConfig) -> List[AuditFinding]:
    if not spec.hot or not trace.callbacks:
        return []
    detail = ", ".join(f"{name} x{n}"
                       for name, n in sorted(trace.callbacks.items()))
    return [_make(config, spec, "PRG001",
                  f"host-interop primitives in a hot program: {detail}")]


def check_dtype_drift(spec: ProgramSpec, trace: TraceInfo,
                      config: AuditConfig) -> List[AuditFinding]:
    out = []
    # int64 is legal (counters, indices); 64-bit floats are the drift
    f64 = sorted(d for d in trace.dtypes
                 if d in ("float64", "complex128"))
    if f64 and not spec.allow_f64:
        out.append(_make(
            config, spec, "PRG002",
            f"64-bit float dtypes in the program: {', '.join(f64)} — "
            "a silent upcast (np.float64 literal, python float chain) "
            "doubles memory and dies on TPU"))
    if spec.expect_bf16 and "bfloat16" not in trace.dtypes:
        out.append(_make(
            config, spec, "PRG002",
            "program is declared bf16-compute but no bfloat16 appears "
            "in its jaxpr — the mixed-precision path silently upcast "
            f"to {{{', '.join(sorted(trace.dtypes))}}}"))
    if spec.expect_int8 and "int8" not in trace.dtypes:
        out.append(_make(
            config, spec, "PRG002",
            "program is declared int8-quantized (expect_int8) but no "
            "int8 appears in its jaxpr — the weight-only quantization "
            "chain (utils.precision.quantize_int8) is not in the "
            "program, so the artifact would serve dequantized or "
            "full-precision weights unaudited"))
    return out


def check_constant_bloat(spec: ProgramSpec, trace: TraceInfo,
                         config: AuditConfig) -> List[AuditFinding]:
    out = []
    if trace.const_max >= config.const_bloat_bytes:
        out.append(_make(
            config, spec, "PRG004",
            f"largest jaxpr constant is {trace.const_max} bytes "
            f"(threshold {config.const_bloat_bytes}) — a closure "
            "captured an array that should be an argument"))
    elif trace.const_total >= config.const_total_bytes:
        out.append(_make(
            config, spec, "PRG004",
            f"{len(trace.const_bytes)} jaxpr constants total "
            f"{trace.const_total} bytes (threshold "
            f"{config.const_total_bytes})"))
    return out


def check_dynamic_while(spec: ProgramSpec, trace: TraceInfo,
                        config: AuditConfig) -> List[AuditFinding]:
    if trace.while_count and not spec.allow_while:
        return [_make(
            config, spec, "PRG005",
            f"{trace.while_count} `while` primitive(s) in a program "
            "that declared none (scan/fori with static trip counts "
            "lower as `scan`; declare allow_while for an intentional "
            "bounded-iteration kernel)")]
    return []


# ---------------------------------------------------- compiled-level checks


def check_donation(spec: ProgramSpec, built, compiled: CompiledInfo,
                   config: AuditConfig) -> List[AuditFinding]:
    """Every declared donation must be REALIZED by the executable."""
    if not spec.donate_argnums:
        return []
    leaf_count, leaf_bytes = donated_leaves(built, spec.donate_argnums)
    if leaf_count == 0:
        return []
    out = []
    if not compiled.aliases and compiled.alias_bytes == 0:
        out.append(_make(
            config, spec, "PRG003",
            f"donate_argnums={spec.donate_argnums} declared "
            f"({leaf_count} leaves, {leaf_bytes} bytes) but the "
            "compiled executable established ZERO input/output aliases "
            "— donation was silently dropped; the step runs at 2x "
            "state memory (or worse: PR 5/6's corruption window)"))
    elif compiled.alias_bytes < leaf_bytes:
        out.append(_make(
            config, spec, "PRG003",
            f"donation only partially realized: {compiled.alias_bytes} "
            f"of {leaf_bytes} donated bytes aliased "
            f"({compiled.aliased_param_count} of {leaf_count} leaves) "
            "— some state leaves changed shape/dtype/sharding between "
            "input and output"))
    return out


def check_sharding_coverage(spec: ProgramSpec, built,
                            compiled: CompiledInfo,
                            config: AuditConfig) -> List[AuditFinding]:
    if not spec.meshed:
        if spec.expect_sharded_params:
            # the declaration would be serialized into the audited
            # declarations while checking NOTHING — refuse the inert
            # combination instead of quietly skipping
            return [_make(
                config, spec, "PRG006",
                "expect_sharded_params declared on a non-meshed "
                "program — the sharded-param facet only applies to "
                "meshed programs; the declaration is unenforceable")]
        return []
    if spec.expect_sharded_params and not spec.donate_argnums:
        return [_make(
            config, spec, "PRG006",
            "expect_sharded_params declared without donate_argnums — "
            "the facet locates the state through the donated "
            "arguments, so the declaration is unenforceable as "
            "written")]
    out = []
    specs = compiled.input_specs
    if not specs:
        out.append(_make(
            config, spec, "PRG006",
            "meshed program but the compiled executable exposes no "
            "sharding metadata — the mesh never reached the program"))
        return out
    nontrivial = [s for s in specs if _spec_is_sharded(s)]
    if not nontrivial:
        out.append(_make(
            config, spec, "PRG006",
            f"all {len(specs)} input leaves are fully replicated — "
            "nothing is sharded over the mesh; the partition rules "
            "cover no input"))
    elif spec.expect_sharded_params and spec.donate_argnums:
        # the PARTITIONED-program facet: a batch-only sharding (every
        # state leaf replicated) means the rules shard zero leaves —
        # exactly the silent regression a pod run would discover as an
        # OOM.  Flattened inputs follow argument order, so each donated
        # argnum's leaves occupy the slice between its neighbours'
        # cumulative leaf counts (NOT necessarily a front prefix).
        import jax

        leaf_counts = [len(jax.tree.leaves(a)) for a in built.args]
        offsets = [0]
        for c in leaf_counts:
            offsets.append(offsets[-1] + c)
        state_specs = []
        for i in spec.donate_argnums:
            state_specs.extend(specs[offsets[i]:offsets[i + 1]])
        n_sharded = sum(1 for s in state_specs if _spec_is_sharded(s))
        if n_sharded == 0:
            out.append(_make(
                config, spec, "PRG006",
                f"program declares sharded parameters but all "
                f"{len(state_specs)} state leaves compiled fully "
                "replicated — the partition rules shard ZERO "
                "param/optimizer leaves (batch-only sharding is the "
                "dryrun regime this program exists to retire)"))
    for out_idx, param_idx in sorted(compiled.aliases.items()):
        if (param_idx < len(compiled.input_specs)
                and out_idx < len(compiled.output_specs)
                and compiled.input_specs[param_idx]
                != compiled.output_specs[out_idx]):
            out.append(_make(
                config, spec, "PRG006",
                f"donated leaf sharding diverges across the step: "
                f"input {param_idx} {compiled.input_specs[param_idx]} "
                f"vs output {out_idx} "
                f"{compiled.output_specs[out_idx]} — the alias cannot "
                "hold and the update silently materializes a resharded "
                "copy"))
    return out


def run_trace_checks(spec: ProgramSpec, trace: TraceInfo,
                     config: Optional[AuditConfig] = None
                     ) -> List[AuditFinding]:
    config = config or AuditConfig()
    out: List[AuditFinding] = []
    out += check_host_interop(spec, trace, config)
    out += check_dtype_drift(spec, trace, config)
    out += check_constant_bloat(spec, trace, config)
    out += check_dynamic_while(spec, trace, config)
    return out


def run_compiled_checks(spec: ProgramSpec, built, compiled: CompiledInfo,
                        config: Optional[AuditConfig] = None
                        ) -> List[AuditFinding]:
    config = config or AuditConfig()
    out: List[AuditFinding] = []
    out += check_donation(spec, built, compiled, config)
    out += check_sharding_coverage(spec, built, compiled, config)
    return out
