"""graftaudit configuration: ``[tool.graftaudit]`` in ``pyproject.toml``.

Reuses graftlint's TOML-subset parser (``analysis.config``) — same
file, same value shapes, same loud failure on unknown keys.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Tuple

from ..config import ConfigError, parse_graftlint_tables


@dataclass(frozen=True)
class AuditConfig:
    """Resolved graftaudit configuration (defaults mirror the committed
    ``[tool.graftaudit]`` section so ``AuditConfig()`` behaves like the
    repo checkout)."""

    #: max relative drift (percent) tolerated per numeric fingerprint
    #: field before PRG007 fires — cost-analysis numbers move a little
    #: with XLA minor versions, a real regression moves a lot
    cost_tolerance_pct: float = 25.0
    #: a single jaxpr constant at/above this many bytes is PRG004
    const_bloat_bytes: int = 1 << 20
    #: total baked-in constants at/above this many bytes is PRG004
    const_total_bytes: int = 8 << 20
    #: program names excluded from the sweep (escape hatch for a
    #: program under active rework; the audit reports the exclusion)
    exclude: Tuple[str, ...] = ()
    #: per-check severity overrides, e.g. {"PRG005": "info"}
    severity: Dict[str, str] = None  # type: ignore[assignment]

    def __post_init__(self):
        if self.severity is None:
            object.__setattr__(self, "severity", {})


def audit_config_from_tables(tables: Dict[str, Dict[str, object]],
                             path: str = "pyproject.toml") -> AuditConfig:
    from ..config import SEVERITIES

    root = dict(tables.get("", {}))
    severity = {str(k).upper(): str(v)
                for k, v in tables.get("severity", {}).items()}
    for rid, sev in severity.items():
        if sev not in SEVERITIES:
            raise ConfigError(
                f"{path}: [tool.graftaudit.severity] {rid} = {sev!r} "
                f"(must be one of {SEVERITIES})")
    kwargs: Dict[str, object] = {}
    for key, typ in (("cost_tolerance_pct", (int, float)),
                     ("const_bloat_bytes", int),
                     ("const_total_bytes", int)):
        if key in root:
            val = root.pop(key)
            if not isinstance(val, typ) or isinstance(val, bool):
                raise ConfigError(f"{path}: {key} must be a number")
            kwargs[key] = float(val) if key == "cost_tolerance_pct" else val
    if "exclude" in root:
        val = root.pop("exclude")
        if not isinstance(val, list):
            raise ConfigError(f"{path}: exclude must be an array")
        kwargs["exclude"] = tuple(str(v) for v in val)
    if root:
        raise ConfigError(
            f"{path}: unknown [tool.graftaudit] keys {sorted(root)}")
    return AuditConfig(severity=severity, **kwargs)


def load_audit_config(root: str) -> AuditConfig:
    """Read ``<root>/pyproject.toml``'s graftaudit tables; defaults when
    the file or the section is absent."""
    pp = os.path.join(root, "pyproject.toml")
    if not os.path.exists(pp):
        return AuditConfig()
    with open(pp, encoding="utf-8") as f:
        text = f.read()
    return audit_config_from_tables(
        parse_graftlint_tables(text, pp, section="tool.graftaudit"), pp)
