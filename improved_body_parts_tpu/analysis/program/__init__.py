"""graftaudit — compiled-program auditing: the second static-analysis
tier, checking what XLA *actually compiled* for every program the repo
ships.

graftlint (the sibling ``analysis/rules`` tier) reasons about source
text; this tier traces every registered entry-point program
**abstractly** — ``jax.eval_shape`` / ``jax.make_jaxpr`` over
``ShapeDtypeStruct``s, plus AOT ``.lower().compile()`` on the CPU
backend — with zero real data and zero FLOPs of model execution, and
audits the jaxpr and the compiled artifact:

- PRG001 host-interop   — ``pure_callback`` / ``io_callback`` /
  ``debug_callback`` / infeed / outfeed inside hot programs
- PRG002 dtype-drift    — any f64 anywhere; a program declared
  bf16-compute that compiled with no bf16 left in it
- PRG003 donation-aliasing — every ``donate_argnums`` declaration must
  be REALIZED as ``input_output_alias`` entries in the compiled
  executable (the PR 5/6 corruption class, checked per program)
- PRG004 constant-bloat — giant literals baked into the jaxpr
- PRG005 dynamic-while  — unbounded ``while`` in programs that did not
  declare one
- PRG006 sharding-coverage — under a mesh: inputs left unconstrained by
  the partition rules; donated leaves whose in/out shardings diverge
  (an alias cannot be established across a sharding change)
- PRG007 fingerprint-drift — HLO cost-analysis fingerprint (flops,
  bytes accessed, peak temp memory, instruction count) and jaxpr
  structure vs the committed golden registry (``PROGRAM_AUDIT.json``)

``registry.program_registry()`` enumerates the real entry points;
``tools/program_audit.py`` is the runner and
``tests/test_program_audit.py`` wires the sweep into tier-1.

Unlike the lint tier this package imports jax and repo code by
construction — but only ever traces/compiles abstract values, so no
model arithmetic executes and no accelerator is touched (the audit
pins the CPU backend).
"""
from .audit import (  # noqa: F401
    GRAFTAUDIT_VERSION,
    AuditReport,
    ProgramVerdict,
    audit_registry,
    audit_ruleset_hash,
)
from .checks import PROGRAM_RULES, AuditFinding  # noqa: F401
from .config import AuditConfig, load_audit_config  # noqa: F401
from .fingerprint import compare_fingerprints  # noqa: F401
from .registry import BuiltProgram, ProgramSpec, program_registry  # noqa: F401
