"""Program fingerprints and golden-registry comparison (PRG007).

Two tiers, matching the audit's two cost tiers:

- the **trace fingerprint** (jaxpr structure: equation counts by
  primitive, the dtype lattice, constant bytes, control-flow shape,
  input/output signatures) is deterministic for a given jax version
  and costs ~1 s — tier-1 gates on it for every program;
- the **compiled fingerprint** (XLA cost analysis: flops, bytes
  accessed, peak temp memory, instruction count, realized aliases)
  needs the AOT compile — ``tools/program_audit.py`` computes it for
  the committed artifact and the bench key.

Comparison semantics: STRUCTURAL fields must match exactly (a new
dtype, a new host callback, a changed signature, a lost alias is a
regression, full stop); NUMERIC fields tolerate
``cost_tolerance_pct`` relative drift (XLA minor versions jiggle
instruction counts and fusion decisions; real regressions move far
more).  Every diff names the field, both values, and the relative
change — the "diff, not a 2-day debugging session" contract.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .compiled import CompiledInfo
from .trace import TraceInfo

#: trace-fingerprint fields compared exactly
TRACE_EXACT = ("dtypes", "callbacks", "while_count", "scan_count",
               "in_signature", "out_signature")
#: trace-fingerprint fields compared under tolerance (fusion-adjacent
#: rewrites move equation counts slightly across jax versions)
TRACE_NUMERIC = ("eqn_count", "const_total", "const_max")
#: compiled-fingerprint fields compared exactly
COMPILED_EXACT = ("argument_bytes", "output_bytes", "alias_bytes",
                  "aliased_params", "sharded_inputs")
#: compiled-fingerprint fields compared under tolerance
COMPILED_NUMERIC = ("flops", "bytes_accessed", "temp_bytes",
                    "hlo_instruction_count")


def _signature_summary(sig) -> Dict:
    """A flattened-leaves signature as {count, 12-hex hash}: exact
    equality still detects ANY leaf shape/dtype/order change, while the
    committed artifact stays small (the train state alone is 762
    leaves — the full list per program tripled PROGRAM_AUDIT.json)."""
    import hashlib

    h = hashlib.sha256()
    for s in sig:
        h.update(s.encode())
        h.update(b"\0")
    return {"count": len(sig), "hash": h.hexdigest()[:12]}


def trace_fingerprint(trace: TraceInfo) -> Dict:
    return {
        "eqn_count": trace.eqn_count,
        "primitives": dict(sorted(trace.primitives.items())),
        "dtypes": sorted(trace.dtypes),
        "callbacks": dict(sorted(trace.callbacks.items())),
        "while_count": trace.while_count,
        "scan_count": trace.scan_count,
        "const_count": len(trace.const_bytes),
        "const_total": trace.const_total,
        "const_max": trace.const_max,
        "in_signature": _signature_summary(trace.in_signature),
        "out_signature": _signature_summary(trace.out_signature),
    }


def compiled_fingerprint(compiled: CompiledInfo) -> Dict:
    return {
        "flops": int(compiled.flops),
        "bytes_accessed": int(compiled.bytes_accessed),
        "argument_bytes": compiled.argument_bytes,
        "output_bytes": compiled.output_bytes,
        "alias_bytes": compiled.alias_bytes,
        "temp_bytes": compiled.temp_bytes,
        "hlo_instruction_count": compiled.hlo_instruction_count,
        "aliased_params": compiled.aliased_param_count,
        "sharded_inputs": compiled.sharded_input_count,
        "input_spec_kinds": sorted(set(compiled.input_specs)),
        "output_spec_kinds": sorted(set(compiled.output_specs)),
    }


def _rel_pct(old: float, new: float) -> float:
    if old == new:
        return 0.0
    base = max(abs(old), 1e-12)
    return 100.0 * abs(new - old) / base


def compare_fingerprints(golden: Dict, current: Dict,
                         tolerance_pct: float,
                         exact_keys, numeric_keys) -> List[Dict]:
    """Diff two fingerprint dicts.  Returns one record per drifted
    field: ``{"field", "golden", "current", "drift_pct"|None}`` —
    empty list means no drift beyond tolerance."""
    diffs: List[Dict] = []
    for key in exact_keys:
        if golden.get(key) != current.get(key):
            diffs.append({"field": key, "golden": golden.get(key),
                          "current": current.get(key), "drift_pct": None})
    for key in numeric_keys:
        old, new = golden.get(key), current.get(key)
        if old is None or new is None:
            if old != new:
                diffs.append({"field": key, "golden": old, "current": new,
                              "drift_pct": None})
            continue
        pct = _rel_pct(float(old), float(new))
        if pct > tolerance_pct:
            diffs.append({"field": key, "golden": old, "current": new,
                          "drift_pct": round(pct, 2)})
    return diffs


def compare_trace(golden: Optional[Dict], current: Dict,
                  tolerance_pct: float) -> List[Dict]:
    if not golden:
        return []
    return compare_fingerprints(golden, current, tolerance_pct,
                                TRACE_EXACT, TRACE_NUMERIC)


def compare_compiled(golden: Optional[Dict], current: Dict,
                     tolerance_pct: float) -> List[Dict]:
    if not golden:
        return []
    return compare_fingerprints(golden, current, tolerance_pct,
                                COMPILED_EXACT, COMPILED_NUMERIC)
