"""AOT lowering/compilation and compiled-artifact extraction.

``jitted.lower(*ShapeDtypeStructs).compile()`` builds the real XLA
executable on the CPU backend without running it — zero data, zero
model FLOPs, but the artifact is exactly what a run would execute
(modulo backend codegen).  From it we extract:

- the realized ``input_output_alias`` map (HLO module header) — the
  ground truth for donation verification (PRG003): jax drops a
  donation silently when shapes/dtypes/shardings prevent aliasing,
  and the PR 5/6 corruption class lived precisely in that gap;
- cost analysis (flops / bytes accessed) and memory analysis
  (argument / output / alias / peak-temp bytes) — the fingerprint;
- input/output shardings for the mesh-coverage check (PRG006).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

_INSTR_RE = re.compile(r"^\s+(?:ROOT\s+)?%?[\w.\-]+\s+=\s+", re.M)
_ALIAS_ENTRY_RE = re.compile(r"\{([\d,\s]*)\}:\s*\((\d+)")


def _spec_is_sharded(spec_str: str) -> bool:
    """True only for a ``PartitionSpec(...)`` with at least one named
    axis.  A single-device program's ``SingleDeviceSharding(...)``
    strings (and any future non-PartitionSpec sharding text) are NOT
    sharded: nothing is split — treating unknown strings as sharded
    would make every replicated input count."""
    return (spec_str.startswith("PartitionSpec(")
            and spec_str != "PartitionSpec()")


@dataclass
class CompiledInfo:
    """Summary of one compiled executable."""

    flops: float = 0.0
    bytes_accessed: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    alias_bytes: int = 0
    temp_bytes: int = 0
    generated_code_bytes: int = 0
    hlo_instruction_count: int = 0
    #: flat output index -> flat parameter index, parsed from the HLO
    #: module header's ``input_output_alias`` map
    aliases: Dict[int, int] = field(default_factory=dict)
    #: str(PartitionSpec) per flattened input / output (empty when the
    #: program was not built with explicit shardings)
    input_specs: List[str] = field(default_factory=list)
    output_specs: List[str] = field(default_factory=list)

    @property
    def aliased_param_count(self) -> int:
        return len(set(self.aliases.values()))

    @property
    def sharded_input_count(self) -> int:
        """Inputs whose realized spec actually splits an axis — the
        number PRG006 gates on (>0 for a meshed program) and the
        fingerprint pins so a layout can't silently collapse to
        replicated between blessings."""
        return sum(1 for s in self.input_specs if _spec_is_sharded(s))


def parse_input_output_aliases(hlo_text: str) -> Dict[int, int]:
    """Parse the ``input_output_alias={ ... }`` map out of an HLO module
    header.  Entries look like ``{3}: (3, {}, may-alias)`` — output
    tuple index -> (parameter number, param subindex, kind); the output
    tuple of a jax program is the flattened result, so the top-level
    index IS the flat output leaf index."""
    start = hlo_text.find("input_output_alias=")
    if start < 0:
        return {}
    i = hlo_text.index("{", start)
    depth, j = 0, i
    while j < len(hlo_text):
        if hlo_text[j] == "{":
            depth += 1
        elif hlo_text[j] == "}":
            depth -= 1
            if depth == 0:
                break
        j += 1
    body = hlo_text[i + 1:j]
    aliases: Dict[int, int] = {}
    for m in _ALIAS_ENTRY_RE.finditer(body):
        out_path = [p for p in m.group(1).replace(" ", "").split(",") if p]
        if not out_path:
            continue
        aliases[int(out_path[0])] = int(m.group(2))
    return aliases


def _sharding_specs(shardings) -> List[str]:
    """Flatten a compiled executable's input/output shardings into
    ``str(PartitionSpec)`` per leaf (best-effort: backends without
    sharding metadata yield an empty list)."""
    import jax

    def is_leaf(x):
        return hasattr(x, "spec") or hasattr(x, "device_set")

    out = []
    for s in jax.tree.leaves(shardings, is_leaf=is_leaf):
        spec = getattr(s, "spec", None)
        out.append(str(spec) if spec is not None else str(s))
    return out


def _alias_bytes_from_args(aliases: Dict[int, int], args) -> int:
    """Total bytes of the aliased parameters, costed from the built
    example args' avals: flat leaf order matches HLO parameter order for
    the registry's programs (every argument is consumed, so jax prunes
    nothing).  Returns 0 when an alias points past the flattened args —
    the caller keeps the executable's own (zero) readout then."""
    import jax
    import numpy as np

    leaves = jax.tree.leaves(args)
    params = set(aliases.values())
    if not params or max(params) >= len(leaves):
        return 0
    return sum(int(np.prod(leaves[p].shape))
               * np.dtype(leaves[p].dtype).itemsize for p in params)


def compile_program(built) -> Tuple[CompiledInfo, object]:
    """AOT-compile a :class:`~.registry.BuiltProgram` and extract its
    :class:`CompiledInfo`.  Returns ``(info, compiled)`` — the compiled
    object itself for callers that need more (never executed here).

    The persistent compilation cache is bypassed for the compile: an
    executable deserialized from the cache loses its memory analysis
    (``alias_size_in_bytes`` reads 0), which would both fail PRG003 on
    a correctly-donated step and make ``alias_bytes`` — a COMPILED_EXACT
    fingerprint field — drift between cold and warm runs.  An audit
    must fingerprint what the compiler emits, not what a cache replays.
    """
    import jax

    cache_was = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        compiled = built.fn.lower(*built.args).compile()
    finally:
        jax.config.update("jax_enable_compilation_cache", cache_was)
    info = CompiledInfo()

    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if cost:
        info.flops = float(cost.get("flops", 0.0))
        info.bytes_accessed = float(cost.get("bytes accessed", 0.0))

    mem = compiled.memory_analysis()
    if mem is not None:
        info.argument_bytes = int(mem.argument_size_in_bytes)
        info.output_bytes = int(mem.output_size_in_bytes)
        info.alias_bytes = int(mem.alias_size_in_bytes)
        info.temp_bytes = int(mem.temp_size_in_bytes)
        info.generated_code_bytes = int(mem.generated_code_size_in_bytes)

    text = compiled.as_text()
    info.hlo_instruction_count = len(_INSTR_RE.findall(text))
    info.aliases = parse_input_output_aliases(text)

    if info.aliases and info.alias_bytes == 0:
        # memory_analysis() nondeterministically reads 0 aliased bytes
        # on the CPU backend even when the HLO header realized the
        # donation (observed flaking run-to-run on identical programs).
        # alias_bytes is a COMPILED_EXACT fingerprint field and PRG003's
        # partial-donation signal, so a flaky readout would both fail a
        # correctly-donated step and make blessing nondeterministic.
        # Fall back to the ground truth this module already trusts: the
        # realized alias map, costed with the built args' avals.  (The
        # avals are GLOBAL shapes — for a meshed program the healthy
        # readout is per-device bytes, so this fallback only replaces a
        # degenerate zero, never a live measurement.)
        info.alias_bytes = _alias_bytes_from_args(info.aliases,
                                                  built.args)

    try:
        info.input_specs = _sharding_specs(compiled.input_shardings)
        info.output_specs = _sharding_specs(compiled.output_shardings)
    except Exception:  # noqa: BLE001 — sharding metadata is best-effort
        pass
    return info, compiled
