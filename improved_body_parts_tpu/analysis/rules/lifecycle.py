"""JGL005 — resource lifecycle.

Postmortems encoded (PRs 2/4/6): the prefetch producer thread leaked
and pinned an in-flight device buffer (PR 2); the orbax
``AsyncCheckpointer`` leaked its commit thread per manager (PR 5); ring
workers outlived SIGKILLed consumers (PR 6).  Every one was a
concurrency primitive created without a join/close on the exit path.

Flagged: a thread / pool / executor / shared-memory segment /
subprocess bound to a *local* name with **no** cleanup call
(``join``/``close``/``shutdown``/``terminate``/``kill``/``wait``/
``unlink``/``stop``/``release``) anywhere in the function.

Exempt (ownership is elsewhere or lifetime is the process):

- created with ``daemon=True`` (dies with the process by design);
- stored on ``self``/an attribute/a subscript (object lifecycle);
- returned or yielded (caller owns it);
- used as a context manager (``with``);
- appended to a container that is itself cleaned up in a loop
  (``for t in threads: t.join()``).

The rule checks *existence* of cleanup, not full path coverage — the
all-exit-paths discipline (try/finally) is reviewed where the cleanup
sits; a missing cleanup is the shipped bug class.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .. import dataflow as df
from ..core import ModuleContext, Rule, register

_CONSTRUCTOR_SUFFIXES = (
    "threading.Thread", "Thread",
    "ThreadPoolExecutor", "ProcessPoolExecutor",
    "multiprocessing.Pool", "mp.Pool",
    "shared_memory.SharedMemory", "SharedMemory",
    "subprocess.Popen", "Popen",
)
_CLEANUPS = ("join", "close", "shutdown", "terminate", "kill", "wait",
             "unlink", "stop", "release")


def _is_constructor(callee: Optional[str]) -> bool:
    if callee is None:
        return False
    return any(callee == s or callee.endswith("." + s)
               for s in _CONSTRUCTOR_SUFFIXES)


@register
class ResourceLifecycle(Rule):
    id = "JGL005"
    name = "resource-lifecycle"
    severity = "warning"
    postmortem = ("PR 2: leaked prefetch thread pinned a device buffer; "
                  "PR 5: leaked orbax commit threads; PR 6: orphaned "
                  "ring workers")

    #: cheap source precheck — most files construct none of these, and
    #: the dataflow walk below is the scan's hottest rule without it
    _TOKENS = ("Thread", "Pool", "Executor", "SharedMemory", "Popen")

    def check(self, ctx: ModuleContext) -> None:
        if not any(tok in ctx.source for tok in self._TOKENS):
            return
        for scope in df.functions(ctx.tree):
            if not isinstance(scope, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            self._check_scope(ctx, scope)

    def _check_scope(self, ctx: ModuleContext, fn: ast.AST) -> None:
        stmts = df.own_statements(fn)
        created: Dict[str, ast.Call] = {}
        appended_to: Dict[str, str] = {}  # resource container -> example
        cleaned: Set[str] = set()
        escaped: Set[str] = set()
        containers_cleaned: Set[str] = set()

        for stmt in stmts:
            for node in df.walk_scope(stmt):
                if isinstance(node, ast.Call):
                    callee = df.call_callee(node)
                    if _is_constructor(callee):
                        daemon = df.call_kwarg(node, "daemon")
                        if isinstance(daemon, ast.Constant) and \
                                daemon.value is True:
                            continue
                        parent_stmt = df.stmt_ancestor(node)
                        if isinstance(parent_stmt, (ast.With,
                                                    ast.AsyncWith)):
                            continue
                        if isinstance(parent_stmt, ast.Return):
                            continue  # `return Thread(...)`: caller owns
                        if isinstance(parent_stmt, ast.Assign) and \
                                parent_stmt.value is node:
                            names = []
                            attr_store = False
                            for t in parent_stmt.targets:
                                if isinstance(t, (ast.Attribute,
                                                  ast.Subscript)):
                                    attr_store = True
                                names.extend(df.assigned_names(t))
                            if attr_store:
                                continue
                            for name in names:
                                created[name] = node
                        elif isinstance(node.graftlint_parent, ast.Call):
                            # SomeContainer.append(Thread(...)) — track
                            # the container
                            outer = node.graftlint_parent
                            if isinstance(outer.func, ast.Attribute) and \
                                    outer.func.attr in ("append",
                                                        "add") and \
                                    isinstance(outer.func.value,
                                               ast.Name):
                                appended_to[outer.func.value.id] = \
                                    callee or "resource"
                                created.setdefault(
                                    "@" + outer.func.value.id, node)
                    # cleanup calls on names
                    if isinstance(node.func, ast.Attribute) and \
                            node.func.attr in _CLEANUPS and \
                            isinstance(node.func.value, ast.Name):
                        cleaned.add(node.func.value.id)
            # escape routes
            if isinstance(stmt, (ast.Return,)) and stmt.value is not None:
                for n in ast.walk(stmt.value):
                    if isinstance(n, ast.Name):
                        escaped.add(n.id)
            for n in ast.walk(stmt):
                if isinstance(n, (ast.Yield, ast.YieldFrom)) and \
                        n.value is not None:
                    for nn in ast.walk(n.value):
                        if isinstance(nn, ast.Name):
                            escaped.add(nn.id)
            if isinstance(stmt, ast.Assign):
                # self.x = t  /  d[k] = t: ownership transferred
                if any(isinstance(t, (ast.Attribute, ast.Subscript))
                       for t in stmt.targets):
                    for n in ast.walk(stmt.value):
                        if isinstance(n, ast.Name):
                            escaped.add(n.id)
            # resource appended to a container cleaned in a loop:
            # `for t in threads: t.join()`
            if isinstance(stmt, (ast.For, ast.AsyncFor)) and \
                    isinstance(stmt.iter, ast.Name) and \
                    stmt.iter.id in appended_to:
                targets = df.assigned_names(stmt.target)
                for node in df.walk_scope(stmt):
                    if isinstance(node, ast.Call) and \
                            isinstance(node.func, ast.Attribute) and \
                            node.func.attr in _CLEANUPS and \
                            isinstance(node.func.value, ast.Name) and \
                            node.func.value.id in targets:
                        containers_cleaned.add(stmt.iter.id)

        for name, call in created.items():
            if name.startswith("@"):
                container = name[1:]
                if container in containers_cleaned or \
                        container in escaped or container in cleaned:
                    continue
            elif name in cleaned or name in escaped:
                continue
            # `t` passed whole to another call (handoff: supervisor,
            # registry) — treat as ownership transfer
            if not name.startswith("@") and self._passed_on(stmts, name):
                continue
            what = df.call_callee(call) or "resource"
            ctx.finding(
                self, call,
                f"`{what}` created here has no "
                f"join/close/shutdown on any path in this function and "
                "never escapes it — a leaked worker pins its resources "
                "past the run (PR 2/5/6 leak class); clean up in a "
                "finally block or hand ownership somewhere that does")

    @staticmethod
    def _passed_on(stmts: List[ast.stmt], name: str) -> bool:
        for stmt in stmts:
            for node in df.walk_scope(stmt):
                if isinstance(node, ast.Call):
                    for a in list(node.args) + [k.value
                                                for k in node.keywords]:
                        if isinstance(a, ast.Name) and a.id == name:
                            return True
        return False
