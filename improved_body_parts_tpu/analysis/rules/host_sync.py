"""JGL002 — hidden host sync in per-batch loops.

Postmortem encoded (PR 3): ``eval_epoch`` called ``float(loss)`` on
every batch — each call blocks the host on that step's device result,
serializing host placement against dispatch and defeating
``device_prefetch`` for the whole pass.  The repair buffers the device
scalars and reads them back in *windows* (``if len(pending) >=
readback_freq: float(...)``), which is exactly the shape this rule
passes: a sync guarded by an ``if`` inside the loop runs once per
window, not once per iteration.

Scope: files under ``improved_body_parts_tpu/{train,serve,infer,
stream}`` — the per-batch/per-frame hot paths.  A sync is flagged when
all of:

- it is a host-sync operation (``float()``, ``int()``, ``.item()``,
  ``.tolist()``, ``np.asarray()``, ``jax.device_get()``,
  ``block_until_ready``);
- its operand may hold a device value (taint from ``jnp.*`` /
  ``jax.lax.*`` calls, jitted-name calls, ``*_step`` calls, ``.apply``,
  with propagation through assignment, arithmetic, buffering
  ``.append`` and iteration);
- it executes on *every* iteration of a loop (not nested under an
  ``if``, not in a nested function).
"""
from __future__ import annotations

import ast
from typing import Set

from .. import dataflow as df
from ..core import ModuleContext, Rule, register


@register
class HiddenHostSync(Rule):
    id = "JGL002"
    name = "hidden-host-sync"
    severity = "error"
    postmortem = ("PR 3: per-batch float(loss) in eval_epoch defeated "
                  "device_prefetch; fixed by windowed readback")

    SCOPE = ("improved_body_parts_tpu/train",
             # the whole serve/ tree, including the ISSUE 11 pool/
             # policy/breaker control plane — failover and health-probe
             # code runs on completion threads per request
             "improved_body_parts_tpu/serve",
             "improved_body_parts_tpu/infer",
             # the streaming sessions run per-frame on serve threads —
             # the same hot-path discipline applies
             "improved_body_parts_tpu/stream",
             # the parallel tree: device_prefetch's producer thread runs
             # per batch, and the ISSUE 12 partition module's
             # sharding/resharding helpers sit on the train entry path
             "improved_body_parts_tpu/parallel",
             # the ISSUE 15 per-request observability layer: reqtrace
             # nodes are opened/finished and SLO outcomes recorded ON
             # the serve threads for every request — the same hot-path
             # discipline as the engines themselves (the rest of obs/
             # is scrape-time/export code and stays out of scope)
             "improved_body_parts_tpu/obs/reqtrace.py",
             "improved_body_parts_tpu/obs/slo.py",
             # worker-side telemetry publishes into the shm block and
             # records flight-ring milestones ON the serve loop between
             # batches — same hot-path discipline
             "improved_body_parts_tpu/obs/fleet.py",
             # the ISSUE 19 history sampler scrapes every registry
             # collector at a fixed cadence while serving is live — a
             # hidden host sync inside its tick would stall the same
             # GIL the dispatch threads run on, so it keeps the serve
             # tree's discipline
             "improved_body_parts_tpu/obs/history.py",
             # the ISSUE 20 decode-payload ops: peaks.py is traced into
             # every compact decode program and pallas_peaks.py is its
             # config-selectable Mosaic twin — both sit under the serve
             # dispatch path, where a hidden readback would serialize
             # the whole program queue
             "improved_body_parts_tpu/ops/peaks.py",
             "improved_body_parts_tpu/ops/pallas_peaks.py")

    def check(self, ctx: ModuleContext) -> None:
        if not ctx.under(*self.SCOPE):
            return
        jit_bound = df.collect_jit_bound(ctx.tree)
        for scope in df.functions(ctx.tree):
            taint = df.DeviceTaint(scope, jit_bound,
                                   ctx.config.extra_device_producers)
            if not taint.tainted:
                continue
            reported: Set[int] = set()
            for stmt in df.own_statements(scope):
                for node in ast.walk(stmt):
                    if not isinstance(node, ast.Call) or \
                            id(node) in reported:
                        continue
                    reported.add(id(node))
                    operand = df.sync_call_argument(node)
                    if operand is None or not taint.is_tainted(operand):
                        continue
                    if df.in_nested_function(node, scope):
                        continue
                    # walk enclosing loops innermost-first, skipping
                    # loops that dispatch no device work themselves —
                    # the windowed-readback repair DRAINS a buffer in an
                    # inner producer-free loop (`for v in pending:
                    # float(v)`), and draining N already-computed
                    # scalars is the amortized idiom, not the stall.
                    # The first producer loop decides: guarded by an if
                    # on the way up -> windowed -> pass.
                    if not self._unguarded_in_producer_loop(node, scope,
                                                            taint):
                        continue
                    op = df.call_callee(node) or \
                        f".{node.func.attr}()"  # type: ignore[union-attr]
                    ctx.finding(
                        self, node,
                        f"`{op}` on a device value inside a per-batch "
                        "loop syncs the host every iteration and defeats "
                        "device_prefetch (the PR 3 eval stall); buffer "
                        "the device scalars and read back in windows "
                        "(`if len(pending) >= N: ...`)")

    @staticmethod
    def _loop_dispatches(loop: ast.AST, taint: df.DeviceTaint) -> bool:
        """True when the loop body itself produces device values (calls
        a jitted step / jnp op / .apply) — the loops where a
        per-iteration sync serializes host against dispatch."""
        for stmt in df.own_statements(loop):
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and \
                        taint._producer_call(node):
                    return True
        return False

    def _unguarded_in_producer_loop(self, node: ast.AST, scope: ast.AST,
                                    taint: df.DeviceTaint) -> bool:
        guarded = False
        for a in df.ancestors(node):
            if a is scope:
                return False
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                return False
            if isinstance(a, ast.If):
                guarded = True
            if isinstance(a, (ast.For, ast.AsyncFor, ast.While)):
                if self._loop_dispatches(a, taint):
                    return not guarded
                # producer-free drain loop: one windowed readback costs
                # one iteration of the NEXT enclosing loop — keep
                # walking out (an If above this drain loop still
                # guards the outer producer loop)
        return False
