"""JGL004 — strict JSON emission.

Postmortem encoded (PRs 4–5): ``json.dumps`` on a record carrying a
non-finite float emits bare ``NaN`` / ``Infinity`` tokens — which are
not JSON — and the records most likely to carry them (a diverged loss,
an empty histogram's quantiles) are exactly the ones a strict consumer
(jq, Go, JS, the telemetry report) must parse.  Both the event sink and
the checkpoint COMMIT markers shipped this bug before being routed
through ``obs.events._definan``.

A ``json.dumps`` / ``json.dump`` call passes when any of:

- ``allow_nan=False`` is passed (the failure is loud at the emit site,
  the ``EventSink._write`` first-try idiom);
- the payload is wrapped in ``_definan(...)`` / ``definan(...)``;
- the call goes through ``obs.events.strict_dumps`` /
  ``strict_dump`` (they are the two idioms above packaged).

``obs/events.py`` itself (the implementation site) is exempt.
"""
from __future__ import annotations

import ast

from .. import dataflow as df
from ..core import ModuleContext, Rule, register

_SANITIZERS = ("_definan", "definan", "strict_dumps", "strict_dump")


@register
class StrictJson(Rule):
    id = "JGL004"
    name = "strict-json"
    severity = "error"
    postmortem = ("PR 4/5: bare-NaN tokens in sink records and COMMIT "
                  "markers broke strict consumers; fixed via "
                  "obs.events._definan")

    def check(self, ctx: ModuleContext) -> None:
        if ctx.rel_path.endswith("obs/events.py"):
            return
        if "json.dump" not in ctx.source:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = df.call_callee(node)
            if callee not in ("json.dumps", "json.dump"):
                continue
            allow_nan = df.call_kwarg(node, "allow_nan")
            if isinstance(allow_nan, ast.Constant) and \
                    allow_nan.value is False:
                continue
            if node.args:
                payload = node.args[0]
                if isinstance(payload, ast.Call):
                    inner = df.call_callee(payload)
                    if inner and inner.split(".")[-1] in _SANITIZERS:
                        continue
            ctx.finding(
                self, node,
                f"`{callee}` emits bare NaN/Infinity tokens (not JSON) "
                "for non-finite floats — and diverged-loss records are "
                "exactly what strict consumers must parse; route "
                "through obs.events.strict_dumps/_definan or pass "
                "allow_nan=False")
