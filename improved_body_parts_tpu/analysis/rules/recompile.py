"""JGL003 — recompile hazards.

Postmortem encoded (PR 3/4): the obs ``CompileWatch`` exists because
post-warmup XLA recompiles silently multiply step time; the recompile
patterns it catches *at runtime* are statically visible at the call
site.  Three shapes:

1. **jit-in-loop** — ``jax.jit(...)`` invoked inside a ``for``/``while``
   body over a lambda or locally-defined function creates a *fresh*
   wrapped callable each iteration: every call retraces (the jit cache
   keys on function identity).  Hoist the jit, or cache the wrapper
   behind a dict-miss guard (a jit call under an ``if`` inside the loop
   is the caching idiom and passes).
2. **mutable static arg** — a list/dict/set display (or ``list()`` /
   ``dict()`` / ``set()`` call) passed in a ``static_argnums`` position
   compares unequal (or unhashably) call-to-call → recompile every
   call.
3. **closure over a mutated name** — a function passed to ``jax.jit``
   that reads an enclosing-scope name which the enclosing scope
   *mutates* (``.append``/``.update``/subscript-store/augassign): the
   traced value is baked at first call, so the mutation silently never
   reaches the compiled program (or forces a retrace via shape change).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .. import dataflow as df
from ..core import ModuleContext, Rule, register

_JIT_CALLEES = ("jax.jit", "jax.pmap", "pjit", "jax.pjit")
_MUTATORS = ("append", "extend", "add", "insert", "update", "setdefault",
             "pop", "remove", "clear")


def _static_positions(call: ast.Call) -> Tuple[int, ...]:
    kw = df.call_kwarg(call, "static_argnums")
    if kw is None:
        return ()
    try:
        val = ast.literal_eval(kw)
    except ValueError:
        return ()
    if isinstance(val, int):
        return (val,)
    try:
        return tuple(int(v) for v in val)
    except TypeError:
        return ()


def _is_fresh_mutable(node: ast.expr) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return df.call_callee(node) in ("list", "dict", "set")
    return False


@register
class RecompileHazard(Rule):
    id = "JGL003"
    name = "recompile-hazard"
    severity = "warning"
    postmortem = ("PR 3/4: CompileWatch exists because post-warmup "
                  "recompiles silently multiply step time")

    def check(self, ctx: ModuleContext) -> None:
        # cheap source precheck: every pattern needs a jit/pmap call
        if not any(tok in ctx.source for tok in ("jit(", "pmap(")):
            return
        self._check_jit_in_loop(ctx)
        self._check_static_mutables(ctx)
        self._check_closure_mutables(ctx)

    # ----------------------------------------------------------- jit-in-loop
    def _check_jit_in_loop(self, ctx: ModuleContext) -> None:
        for scope in df.functions(ctx.tree):
            local_defs = {s.name: s for s in df.own_statements(scope)
                          if isinstance(s, (ast.FunctionDef,
                                            ast.AsyncFunctionDef))}
            for loop in df.loops_in(scope):
                defs_in_loop = {s.name for s in df.own_statements(loop)
                                if isinstance(s, (ast.FunctionDef,
                                                  ast.AsyncFunctionDef))}
                for node in ast.walk(loop):
                    if not (isinstance(node, ast.Call)
                            and df.call_callee(node) in _JIT_CALLEES
                            and node.args):
                        continue
                    if df.in_nested_function(node, scope) or \
                            not df.is_within(node, loop):
                        continue
                    if df.guarded_within(node, loop):
                        # `if key not in cache: cache[key] = jax.jit(...)`
                        # — the caching idiom jits once per key
                        continue
                    target = node.args[0]
                    fresh = isinstance(target, ast.Lambda) or (
                        isinstance(target, ast.Name)
                        and target.id in defs_in_loop)
                    if fresh:
                        ctx.finding(
                            self, node,
                            "jax.jit over a function object created "
                            "inside this loop retraces every iteration "
                            "(the jit cache keys on function identity); "
                            "hoist the jit out of the loop or cache the "
                            "wrapper behind a dict-miss guard")

    # ------------------------------------------------------ static mutables
    def _check_static_mutables(self, ctx: ModuleContext) -> None:
        # name -> static positions, from module-wide jit assignments
        static_bound: Dict[str, Tuple[int, ...]] = {}
        static_names: Dict[str, Tuple[str, ...]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    df.call_callee(node.value) in _JIT_CALLEES:
                pos = _static_positions(node.value)
                kw = df.call_kwarg(node.value, "static_argnames")
                names: Tuple[str, ...] = ()
                if kw is not None:
                    try:
                        v = ast.literal_eval(kw)
                        names = (v,) if isinstance(v, str) else tuple(v)
                    except ValueError:
                        names = ()
                if not pos and not names:
                    continue
                for t in node.targets:
                    for name in df.assigned_names(t):
                        if pos:
                            static_bound[name] = pos
                        if names:
                            static_names[name] = names
        if not static_bound and not static_names:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = df.call_callee(node)
            if callee is None:
                continue
            base = callee.split(".")[0] if "." not in callee else None
            if base is None:
                continue
            for pos in static_bound.get(base, ()):
                if pos < len(node.args) and \
                        _is_fresh_mutable(node.args[pos]):
                    ctx.finding(
                        self, node.args[pos],
                        f"freshly-constructed mutable passed in static "
                        f"position {pos} of jitted `{base}`: unhashable "
                        "or unequal across calls, so every call "
                        "recompiles; pass a tuple / frozen value")
            for kw in node.keywords:
                if kw.arg in static_names.get(base, ()) and \
                        _is_fresh_mutable(kw.value):
                    ctx.finding(
                        self, kw.value,
                        f"freshly-constructed mutable passed as static "
                        f"arg `{kw.arg}` of jitted `{base}`: every call "
                        "recompiles; pass a tuple / frozen value")

    # ------------------------------------------------------ closure mutables
    def _check_closure_mutables(self, ctx: ModuleContext) -> None:
        for scope in df.functions(ctx.tree):
            if not isinstance(scope, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            nested = {s.name: s for s in df.own_statements(scope)
                      if isinstance(s, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}
            if not nested:
                continue
            mutated = self._mutated_names(scope)
            if not mutated:
                continue
            for node in ast.walk(scope):
                if not (isinstance(node, ast.Call)
                        and df.call_callee(node) in _JIT_CALLEES
                        and node.args
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id in nested):
                    continue
                fn = nested[node.args[0].id]
                for free in sorted(self._free_reads(fn) & mutated):
                    ctx.finding(
                        self, node,
                        f"jitted `{fn.name}` closes over `{free}`, which "
                        "this scope mutates: the traced value is baked "
                        "at first call, so later mutations never reach "
                        "the compiled program (or force a retrace); "
                        "pass it as an argument instead")

    @staticmethod
    def _mutated_names(scope: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for stmt in df.own_statements(scope):
            if isinstance(stmt, ast.AugAssign) and \
                    isinstance(stmt.target, ast.Name):
                out.add(stmt.target.id)
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Subscript) and \
                            isinstance(t.value, ast.Name):
                        out.add(t.value.id)
            if isinstance(stmt, ast.Expr) and \
                    isinstance(stmt.value, ast.Call) and \
                    isinstance(stmt.value.func, ast.Attribute) and \
                    stmt.value.func.attr in _MUTATORS and \
                    isinstance(stmt.value.func.value, ast.Name):
                out.add(stmt.value.func.value.id)
        return out

    @staticmethod
    def _free_reads(fn: ast.AST) -> Set[str]:
        bound: Set[str] = set()
        args = fn.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs
                  + ([args.vararg] if args.vararg else [])
                  + ([args.kwarg] if args.kwarg else [])):
            bound.add(a.arg)
        for stmt in df.own_statements(fn):
            bound.update(df.stmt_bound_names(stmt))
        reads: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id not in bound:
                reads.add(node.id)
        return reads
