"""JGL008 — dtype hygiene at the source tier.

The source-level mirror of graftaudit's PRG002 dtype-drift check
(``analysis/program``): a ``float64`` literal flowing into a jnp
constructor compiles into an f64 program — silently doubled memory on
CPU, an outright error on TPU (or a silent demotion, depending on
``jax_enable_x64``) — and by the time the auditor sees it in the jaxpr
the source site takes real digging to find.  This rule flags the
source sites:

- ``dtype=np.float64`` / ``dtype="float64"`` / ``dtype=float`` (the
  bare builtin IS float64 in numpy) passed to a ``jnp.*`` /
  ``jax.numpy.*`` constructor;
- ``jnp.float64`` used anywhere;
- an ``.astype(np.float64)`` / ``.astype("float64")`` result passed
  directly into a jnp call.

Scope: ``improved_body_parts_tpu/`` library modules only.  HOST-side
``np.float64`` is untouched — the decode/OKS path uses f64 on purpose
for reference parity, and it never crosses into a compiled program.
"""
from __future__ import annotations

import ast

from .. import dataflow as df
from ..core import ModuleContext, Rule, register

#: spellings of the f64 dtype as a call argument
_F64_NAMES = ("np.float64", "numpy.float64", "jnp.float64",
              "jax.numpy.float64")
#: jnp members that build/convert device arrays and accept dtype=
_JNP_PREFIXES = ("jnp.", "jax.numpy.")


def _is_f64_literal(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant) and node.value in ("float64",
                                                         "double"):
        return True
    if isinstance(node, ast.Name) and node.id == "float":
        return True  # bare builtin float == numpy float64
    dotted = df.dotted(node)
    return dotted in _F64_NAMES


def _is_jnp_call(call: ast.Call) -> bool:
    callee = df.call_callee(call)
    return bool(callee) and callee.startswith(_JNP_PREFIXES)


def _is_f64_astype(node: ast.expr) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "astype"
            and node.args and _is_f64_literal(node.args[0]))


@register
class DtypeHygiene(Rule):
    id = "JGL008"
    name = "dtype-hygiene"
    severity = "warning"
    postmortem = ("graftaudit PRG002's source-tier mirror: f64 literals "
                  "reaching jnp constructors compile f64 programs — "
                  "2x memory, dead on TPU")

    def check(self, ctx: ModuleContext) -> None:
        if not ctx.under("improved_body_parts_tpu"):
            return
        src = ctx.source
        if ("float64" not in src and "double" not in src
                and "dtype=float" not in src
                and "dtype = float" not in src):
            return
        for node in ast.walk(ctx.tree):
            if df.dotted(node) == "jnp.float64":
                ctx.finding(
                    self, node,
                    "jnp.float64 in library code: f64 compiles to a "
                    "double-memory program (and dies on TPU); use "
                    "jnp.float32 — or keep the value on the host as "
                    "np.float64 if reference parity needs it")
                continue
            if not isinstance(node, ast.Call) or not _is_jnp_call(node):
                continue
            dtype = df.call_kwarg(node, "dtype")
            if dtype is not None and _is_f64_literal(dtype):
                spelled = (ast.unparse(dtype) if hasattr(ast, "unparse")
                           else "float64")
                ctx.finding(
                    self, node,
                    f"dtype={spelled} flowing into a jnp constructor "
                    "builds an f64 device array (bare `float` IS "
                    "float64); pass jnp.float32, or construct on the "
                    "host with np.* if f64 is intentional")
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if _is_f64_astype(arg):
                    ctx.finding(
                        self, node,
                        "an .astype(float64) result passed straight "
                        "into a jnp call uploads an f64 array; cast to "
                        "float32 at the device boundary")
