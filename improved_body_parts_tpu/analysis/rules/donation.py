"""JGL001 — donation safety.

Postmortems encoded (PR 5, PR 6): a ``jit(..., donate_argnums=...)``
step writes its outputs *in place* through its donated input buffers;
with a donated executable served from the persistent compilation cache
(jax 0.4.37, host platform) it does so WITHOUT marking the donated
array deleted — so a value read after it flowed into a donated call, or
a zero-copy ``np.asarray`` view of a state leaf that escapes without
``.copy()``, silently corrupts whatever still references it (the PR 5
in-flight-checkpoint corruption, the PR 6 resume corruption).

Two checks, both intra-procedural:

1. **read-after-donation** — a name passed in a donated position of a
   call to a known donating callable is *consumed*; any later read of
   that name in the same scope (before rebinding) is an error.  Inside
   a loop, a donating call whose donated name is never rebound in the
   loop body is flagged at the call itself: the next iteration reads a
   donated buffer.
2. **escaping asarray view** — in a module that manipulates donated
   buffers (mentions ``donate_argnums`` / ``copy_to_host_async``), an
   ``np.asarray(x)`` result that escapes the function (returned,
   yielded, stored, appended) without a ``.copy()`` is an error: on the
   CPU backend ``np.asarray`` of a device array is a zero-copy view of
   a donatable buffer.

Donating callables: names assigned from ``jax.jit(..., donate_argnums=
...)`` in the same module, plus the configured factories
(``donating-factories`` in ``[tool.graftlint]``, default
``make_train_step:0``).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .. import dataflow as df
from ..core import ModuleContext, Rule, register

_JIT_CALLEES = ("jax.jit", "jax.pmap", "pjit", "jax.pjit")


def _donated_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Donated positions of a ``jax.jit(...)`` call, or None when the
    call does not donate.  Non-literal ``donate_argnums`` expressions
    (``(0,) if donate else ()``) conservatively donate position 0."""
    kw = df.call_kwarg(call, "donate_argnums")
    if kw is None:
        if df.call_kwarg(call, "donate_argnames") is not None:
            return (0,)
        return None
    try:
        val = ast.literal_eval(kw)
    except ValueError:
        return (0,)
    if val is None:
        return None
    if isinstance(val, int):
        return (val,)
    positions = tuple(int(v) for v in val)
    return positions or None


def _collect_donating(tree: ast.AST, ctx: ModuleContext
                      ) -> Dict[str, Tuple[int, ...]]:
    """name -> donated positions, for names assigned from donating
    ``jax.jit`` calls or configured donating factories."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        callee = df.call_callee(node.value)
        positions: Optional[Tuple[int, ...]] = None
        if callee in _JIT_CALLEES:
            positions = _donated_positions(node.value)
        elif callee:
            positions = ctx.config.donated_positions(callee.split(".")[-1])
            if positions:
                # an explicit donate=False at the factory call site
                # opts out (make_train_step(..., donate=False))
                donate = df.call_kwarg(node.value, "donate")
                if isinstance(donate, ast.Constant) and \
                        donate.value is False:
                    positions = None
        if positions:
            for t in node.targets:
                for name in df.assigned_names(t):
                    out[name] = positions
    return out


@register
class DonationSafety(Rule):
    id = "JGL001"
    name = "donation-safety"
    severity = "error"
    postmortem = ("PR 5: snapshot views of donated state corrupted "
                  "in-flight checkpoints; PR 6: cache-served donated "
                  "executable corrupted resumed runs")

    def check(self, ctx: ModuleContext) -> None:
        # cheap source precheck: donation requires a jit call or a
        # configured donating factory by name
        factory_names = tuple(spec.partition(":")[0] for spec
                              in ctx.config.donating_factories)
        if any(tok in ctx.source
               for tok in ("jit(", "pmap(") + factory_names):
            donating = _collect_donating(ctx.tree, ctx)
            if donating:
                for scope in df.functions(ctx.tree):
                    self._check_read_after_donation(ctx, scope, donating)
        if ("donate_argnums" in ctx.source
                or "copy_to_host_async" in ctx.source):
            for scope in df.functions(ctx.tree):
                if isinstance(scope, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    self._check_asarray_escape(ctx, scope)

    # ------------------------------------------------- read after donation
    def _check_read_after_donation(self, ctx: ModuleContext,
                                   scope: ast.AST,
                                   donating: Dict[str, Tuple[int, ...]]
                                   ) -> None:
        stmts = df.own_statements(scope)
        # (donated name, consuming call, rebound-by-same-stmt?)
        consumed: Dict[str, ast.Call] = {}
        for stmt in stmts:
            rebound = set(df.stmt_bound_names(stmt))
            donated_here: List[Tuple[str, ast.Call]] = []
            for node in df.walk_scope(stmt):
                if not isinstance(node, ast.Call):
                    continue
                callee = df.call_callee(node)
                if callee is None or callee not in donating:
                    continue
                for pos in donating[callee]:
                    if pos < len(node.args) and isinstance(node.args[pos],
                                                           ast.Name):
                        donated_here.append((node.args[pos].id, node))
            # reads in this statement of PREVIOUSLY consumed names
            for name_node in df.walk_scope(stmt):
                if (isinstance(name_node, ast.Name)
                        and isinstance(name_node.ctx, ast.Load)
                        and name_node.id in consumed):
                    call = consumed[name_node.id]
                    # the donating call's own argument is the consumption
                    # site, not a read-after
                    if any(name_node is a for a in call.args):
                        continue
                    ctx.finding(self, name_node,
                                f"`{name_node.id}` is read after being "
                                f"donated to the jitted call on line "
                                f"{call.lineno}; a donated buffer may "
                                "already hold the step's outputs "
                                "(rebind the result, or snapshot with "
                                "an owned copy first)")
                    del consumed[name_node.id]  # one finding per donation
            for name in rebound:
                consumed.pop(name, None)
            for name, call in donated_here:
                if name not in rebound:
                    consumed[name] = call
        # loop bodies: a donated name never rebound anywhere in the loop
        # body is handed to the donating call again on the next
        # iteration — flag the call itself (`out = step(state, b)` in a
        # loop without `state = ...` is the classic)
        for loop in df.loops_in(scope):
            loop_stmts = df.own_statements(loop)
            bound_in_loop: Set[str] = set()
            for stmt in loop_stmts:
                bound_in_loop.update(df.stmt_bound_names(stmt))
            seen: Set[Tuple[str, int]] = set()
            for stmt in loop_stmts:
                for node in df.walk_scope(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = df.call_callee(node)
                    if callee is None or callee not in donating:
                        continue
                    for pos in donating[callee]:
                        if pos < len(node.args) and \
                                isinstance(node.args[pos], ast.Name):
                            name = node.args[pos].id
                            key = (name, node.lineno)
                            if name not in bound_in_loop and \
                                    key not in seen:
                                seen.add(key)
                                ctx.finding(
                                    self, node,
                                    f"`{name}` is donated to this call "
                                    "every loop iteration but never "
                                    "rebound in the loop body; the next "
                                    "iteration reads a donated buffer "
                                    "(rebind: `"
                                    f"{name}, ... = {callee}(...)`)")

    # --------------------------------------------------- asarray view escape
    def _check_asarray_escape(self, ctx: ModuleContext,
                              fn: ast.AST) -> None:
        stmts = df.own_statements(fn)
        views: Dict[str, ast.Call] = {}
        copied: Set[str] = set()
        for stmt in stmts:
            for node in df.walk_scope(stmt):
                if not isinstance(node, ast.Call):
                    continue
                callee = df.call_callee(node)
                if callee in ("np.asarray", "numpy.asarray") and \
                        len(node.args) == 1 and not node.keywords and \
                        isinstance(node.args[0], ast.Name):
                    parent_stmt = df.stmt_ancestor(node)
                    if isinstance(parent_stmt, ast.Assign) and \
                            parent_stmt.value is node:
                        for t in parent_stmt.targets:
                            for name in df.assigned_names(t):
                                views[name] = node
                    elif isinstance(parent_stmt, ast.Return):
                        # `return np.asarray(x)` — escapes uncopied
                        ctx.finding(self, node, self._escape_msg(
                            node.args[0].id))
                # name.copy() sanitizes the view wherever it appears —
                # including the conditional-copy repair idiom
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "copy" and \
                        isinstance(node.func.value, ast.Name):
                    copied.add(node.func.value.id)
        for name, call in views.items():
            if name in copied:
                continue
            if self._escapes(fn, name):
                ctx.finding(self, call, self._escape_msg(
                    call.args[0].id, via=name))

    @staticmethod
    def _escape_msg(src: str, via: str = "") -> str:
        head = (f"`np.asarray({src})`"
                + (f" (as `{via}`)" if via and via != src else ""))
        return (f"{head} may be a zero-copy view of a donatable device "
                "buffer and escapes this function without `.copy()`; a "
                "later donated step writes through it (PR 5/6 in-flight "
                "checkpoint corruption) — copy when "
                "`not arr.flags.owndata`")

    def _escapes(self, fn: ast.AST, name: str) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)) \
                    and node.value is not None:
                if any(n.id == name for n in ast.walk(node.value)
                       if isinstance(n, ast.Name)
                       and isinstance(n.ctx, ast.Load)):
                    return True
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in ("append", "extend", "add", "put",
                                       "update", "insert"):
                if any(isinstance(a, ast.Name) and a.id == name
                       for a in node.args):
                    return True
            if isinstance(node, ast.Assign):
                stores_out = any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in node.targets)
                if stores_out and any(
                        isinstance(n, ast.Name) and n.id == name
                        and isinstance(n.ctx, ast.Load)
                        for n in ast.walk(node.value)):
                    return True
        return False
