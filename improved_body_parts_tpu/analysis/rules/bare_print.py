"""JGL007 — bare print in library code.

Postmortem encoded (PR 3): every signal the reference printed died in
stdout; the obs stack exists so library-layer reports reach the run's
structured event stream.  ``utils.profiling.timed`` is the pattern:
emit through ``obs.events.get_sink()`` when a run installed one, fall
back to print otherwise — call sites keep working with telemetry off,
and stop polluting stdout the moment a run turns it on.

Scope: ``improved_body_parts_tpu/`` library modules only.  CLI tools
(``tools/``), tests and the package's ``demo``/CLI entry points print
by design.
"""
from __future__ import annotations

import ast

from .. import dataflow as df
from ..core import ModuleContext, Rule, register

#: library files whose job is interactive stdout (CLI entry points)
_EXEMPT_SUFFIXES = ("/demo.py",)


@register
class BarePrint(Rule):
    id = "JGL007"
    name = "bare-print"
    severity = "warning"
    postmortem = ("PR 3: signals printed to stdout are invisible to the "
                  "run's event stream; route via obs.events.get_sink() "
                  "with a print fallback (utils.profiling.timed)")

    def check(self, ctx: ModuleContext) -> None:
        if not ctx.under("improved_body_parts_tpu"):
            return
        if ctx.rel_path.endswith(_EXEMPT_SUFFIXES):
            return
        if "print(" not in ctx.source:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    df.call_callee(node) == "print":
                ctx.finding(
                    self, node,
                    "bare print() in library code never reaches the "
                    "run's event stream; emit through "
                    "obs.events.get_sink() when enabled and fall back "
                    "to print (the utils.profiling.timed pattern)")
