"""graftlint rules — importing this package registers every rule.

Each module encodes one bug class this repo has actually shipped; the
rule docstrings carry the postmortem.  Add a rule by dropping a module
here with a ``@register``-decorated :class:`~..core.Rule` subclass and
importing it below — the fixture-test contract in
``tests/test_graftlint.py`` (bad snippet flags / fixed idiom passes /
suppressed site is silent) applies to new rules too.
"""
from . import (  # noqa: F401 — imported for registration side effect
    bare_print,
    donation,
    dtype_hygiene,
    host_sync,
    lifecycle,
    metric_names,
    recompile,
    strict_json,
)
