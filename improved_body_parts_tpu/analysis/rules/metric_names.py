"""JGL006 — metric naming at Registry call sites.

Postmortem encoded (PR 4): the obs exposition lint
(``tests/test_obs.py::TestMetricNameLint``) runs at *runtime* over
whatever one instrumented dry-run happened to register — a bad name on
a path the dry-run misses ships to the production scrape.  This rule
promotes the same contract to a static check over every
``.counter(...)`` / ``.gauge(...)`` / ``.histogram(...)`` call site
with a literal name (or a ``prefix + "literal"`` suffix):

- names match the Prometheus charset ``[a-zA-Z_:][a-zA-Z0-9_:]*``;
- counters end in ``_total`` (the convention the scrape-side rules
  assume; ``Registry.span`` appends ``_seconds`` itself and is exempt);
- literal label keys match ``[a-zA-Z_][a-zA-Z0-9_]*``.

Non-literal names are skipped — the runtime lint still covers those.
"""
from __future__ import annotations

import ast
import re
from typing import Optional, Tuple

from .. import dataflow as df
from ..core import ModuleContext, Rule, register

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SUFFIX_RE = re.compile(r"^[a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

_METHODS = ("counter", "gauge", "histogram")


def _literal_name(expr: ast.expr) -> Optional[Tuple[str, bool]]:
    """(text, is_full_name) for a literal or prefix+literal name."""
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value, True
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add) and \
            isinstance(expr.right, ast.Constant) and \
            isinstance(expr.right.value, str):
        return expr.right.value, False
    return None


@register
class MetricNames(Rule):
    id = "JGL006"
    name = "metric-names"
    severity = "error"
    postmortem = ("PR 4: exposition naming enforced only at runtime "
                  "over one dry-run's registrations")

    def check(self, ctx: ModuleContext) -> None:
        if not any(f".{m}(" in ctx.source for m in _METHODS):
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _METHODS
                    and node.args):
                continue
            lit = _literal_name(node.args[0])
            if lit is None:
                continue
            text, full = lit
            if full and not _NAME_RE.match(text):
                ctx.finding(self, node.args[0],
                            f"metric name {text!r} is not Prometheus-"
                            "legal ([a-zA-Z_:][a-zA-Z0-9_:]*)")
                continue
            if not full and not _SUFFIX_RE.match(text):
                ctx.finding(self, node.args[0],
                            f"metric name suffix {text!r} contains "
                            "characters outside [a-zA-Z0-9_:]")
                continue
            if node.func.attr == "counter" and \
                    not text.endswith("_total"):
                ctx.finding(self, node.args[0],
                            f"counter {text!r} must end in `_total` "
                            "(the scrape-side convention "
                            "tests/test_obs.py enforces at runtime)")
            self._check_labels(ctx, node)

    def _check_labels(self, ctx: ModuleContext, node: ast.Call) -> None:
        labels = df.call_kwarg(node, "labels")
        if labels is None and len(node.args) >= 3:
            labels = node.args[2]
        if not isinstance(labels, ast.Dict):
            return
        for key in labels.keys:
            if isinstance(key, ast.Constant) and \
                    isinstance(key.value, str) and \
                    not _LABEL_RE.match(key.value):
                ctx.finding(self, key,
                            f"label key {key.value!r} is not "
                            "Prometheus-legal ([a-zA-Z_][a-zA-Z0-9_]*)")
