"""graftlint core: findings, the rule registry, inline suppressions and
the per-file / per-tree orchestration.

The linter encodes this repo's shipped bug classes as machine-checked
invariants (see ``analysis/rules/``); this module is the plumbing those
rules share.  Design points:

- **Suppressions require a reason.**  ``# graftlint: disable=JGL002 --
  warmup precompile syncs on purpose`` silences a finding on that line;
  a pragma with no ``-- reason`` suppresses *nothing* and is itself an
  error (JGL000) — the whole point is that every silenced postmortem
  pattern carries its justification in the diff.
- **tests/ findings are downgraded** to warnings by default (config
  ``tests_downgrade``): test code reproduces bad patterns on purpose,
  and the acceptance gate ("zero error-severity findings") is about
  product code.  JGL000 keeps its severity everywhere — a reasonless
  suppression is a process bug wherever it sits.
- Rules are pure functions of a parsed module; no imports of the
  linted code ever happen, so linting cannot execute repo code and the
  linter itself needs nothing beyond the stdlib.
"""
from __future__ import annotations

import ast
import fnmatch
import hashlib
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from . import dataflow
from .config import SEVERITIES, LintConfig

GRAFTLINT_VERSION = "1.0.0"

_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\s]+?)"
    r"(?:\s*--\s*(.*\S))?\s*$")


@dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.severity.upper()} {self.rule} {self.message}")

    def as_dict(self) -> dict:
        return {"rule": self.rule, "severity": self.severity,
                "path": self.path, "line": self.line, "col": self.col,
                "message": self.message}


@dataclass
class Suppression:
    line: int
    ids: Set[str]          # upper-cased rule ids, may contain "ALL"
    reason: Optional[str]  # None when the pragma carries no reason
    used: int = 0

    def covers(self, rule_id: str) -> bool:
        return "ALL" in self.ids or rule_id in self.ids


class ModuleContext:
    """Everything a rule sees for one file: the parented AST, raw lines,
    the repo-relative posix path and the resolved config."""

    def __init__(self, source: str, rel_path: str, config: LintConfig):
        self.source = source
        self.rel_path = rel_path.replace(os.sep, "/")
        self.config = config
        self.lines = source.splitlines()
        self.tree = dataflow.add_parents(ast.parse(source))
        self._findings: List[Finding] = []

    # -- path scoping ------------------------------------------------------
    def under(self, *prefixes: str) -> bool:
        return any(self.rel_path == p or self.rel_path.startswith(p + "/")
                   for p in prefixes)

    @property
    def in_tests(self) -> bool:
        return self.under("tests")

    # -- emission ----------------------------------------------------------
    def finding(self, rule: "Rule", node: ast.AST, message: str,
                severity: Optional[str] = None) -> None:
        self._findings.append((Finding(
            rule=rule.id,
            severity=severity or rule.severity,
            path=self.rel_path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0) + 1,
            message=message), node))


class Rule:
    """One bug class.  Subclasses set the class attributes and implement
    ``check``; registration happens via the ``@register`` decorator."""

    id: str = ""
    name: str = ""
    severity: str = "error"
    #: one-line pointer at the postmortem this rule encodes
    postmortem: str = ""

    def check(self, ctx: ModuleContext) -> None:
        raise NotImplementedError


_RULES: Dict[str, Rule] = {}


def register(cls):
    inst = cls()
    assert inst.id and inst.id not in _RULES, inst.id
    assert inst.severity in SEVERITIES, inst.severity
    _RULES[inst.id] = inst
    return cls


def all_rules() -> List[Rule]:
    from . import rules  # noqa: F401 — importing registers them

    return [_RULES[k] for k in sorted(_RULES)]


def known_rule_ids() -> Set[str]:
    return {r.id for r in all_rules()} | {"JGL000"}


def ruleset_hash() -> str:
    """12 hex chars over the analysis package's own source — any rule
    change (new rule, tuned heuristic, severity default) changes the
    stamp, so lint counts in bench provenance are only compared between
    identical rule sets."""
    h = hashlib.sha256()
    pkg = os.path.dirname(os.path.abspath(__file__))
    for dirpath, dirnames, filenames in sorted(os.walk(pkg)):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for fn in sorted(f for f in filenames if f.endswith(".py")):
            p = os.path.join(dirpath, fn)
            h.update(os.path.relpath(p, pkg).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()[:12]


# ------------------------------------------------------------- suppressions


def parse_suppressions(source: str) -> Dict[int, Suppression]:
    """Pragmas from actual COMMENT tokens only — a docstring *describing*
    the suppression syntax (this repo documents it in several places)
    must not register as one."""
    import io
    import tokenize

    out: Dict[int, Suppression] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _PRAGMA_RE.search(tok.string)
            if not m:
                continue
            ids = {s.strip().upper() for s in m.group(1).split(",")
                   if s.strip()}
            line = tok.start[0]
            out[line] = Suppression(line=line, ids=ids, reason=m.group(2))
    except (tokenize.TokenError, SyntaxError):
        pass  # the ast.parse in ModuleContext reports the syntax error
    return out


def _suppression_for(finding: Finding, span: Tuple[int, int],
                     sups: Dict[int, Suppression]) -> Optional[Suppression]:
    first, last = span
    for ln in range(first, last + 1):
        s = sups.get(ln)
        if s is not None and s.covers(finding.rule):
            return s
    return None


# ------------------------------------------------------------ orchestration


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)
    files: int = 0
    suppressed: int = 0
    #: files that failed to parse are reported as JGL000 errors AND
    #: counted here so a syntax error can never read as "clean"
    parse_errors: int = 0

    def count(self, severity: str) -> int:
        return sum(1 for f in self.findings if f.severity == severity)

    def counts(self) -> Dict[str, int]:
        return {s: self.count(s) for s in reversed(SEVERITIES)}


def _effective_severity(finding: Finding, ctx: ModuleContext) -> str:
    sev = ctx.config.severity.get(finding.rule, finding.severity)
    if (ctx.config.tests_downgrade and ctx.in_tests and sev == "error"
            and finding.rule != "JGL000"):
        sev = "warning"
    return sev


def lint_source(source: str, rel_path: str,
                config: Optional[LintConfig] = None
                ) -> Tuple[List[Finding], int]:
    """Lint one source string as if it lived at ``rel_path``.

    Returns ``(findings, suppressed_count)``.  ``rel_path`` drives the
    path-scoped rules (JGL002 only looks at train/serve/infer, JGL007
    only at library code), which is also what lets the fixture tests
    exercise every scope without touching the real tree.
    """
    config = config or LintConfig()
    rel_path = rel_path.replace(os.sep, "/")
    sups = parse_suppressions(source)
    try:
        ctx = ModuleContext(source, rel_path, config)
    except SyntaxError as e:
        return [Finding("JGL000", "error", rel_path, e.lineno or 1,
                        (e.offset or 0) + 1,
                        f"file does not parse: {e.msg}")], 0

    disabled = set(config.disable)
    for rule in all_rules():
        if rule.id in disabled:
            continue
        rule.check(ctx)

    # a pragma anywhere on the lines of the flagged node's enclosing
    # STATEMENT suppresses the finding — multi-line calls put the
    # comment wherever it reads best
    findings: List[Finding] = []
    suppressed = 0
    for f, node in ctx._findings:
        stmt = dataflow.stmt_ancestor(node)
        first = getattr(stmt, "lineno", f.line)
        last = getattr(stmt, "end_lineno", None) or f.line
        sup = _suppression_for(f, (min(first, f.line), max(last, f.line)),
                               sups)
        if sup is not None:
            if sup.reason:
                sup.used += 1
                suppressed += 1
                continue
            # reasonless pragma: it suppresses nothing (JGL000 below
            # fires on the pragma line); fall through and keep f
        findings.append(Finding(f.rule, _effective_severity(f, ctx),
                                f.path, f.line, f.col, f.message))

    known = known_rule_ids()
    for sup in sups.values():
        if not sup.reason:
            findings.append(Finding(
                "JGL000", "error", rel_path, sup.line, 1,
                "graftlint suppression requires a reason: "
                "`# graftlint: disable=JGL00N -- why`"))
        unknown = sorted(i for i in sup.ids if i != "ALL" and i not in known)
        if unknown:
            findings.append(Finding(
                "JGL000", "error", rel_path, sup.line, 1,
                f"unknown rule id(s) in suppression: {', '.join(unknown)}"))
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings, suppressed


def iter_lint_files(paths: Sequence[str], root: str,
                    config: LintConfig) -> List[str]:
    """Expand configured roots into a sorted list of repo-relative .py
    paths, honoring ``exclude`` patterns (``__pycache__`` always)."""
    rels: Set[str] = set()
    for p in paths:
        ap = os.path.join(root, p)
        if os.path.isfile(ap):
            rels.add(os.path.relpath(ap, root))
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if fn.endswith(".py"):
                    rels.add(os.path.relpath(os.path.join(dirpath, fn),
                                             root))
    out = []
    for rel in sorted(rels):
        posix = rel.replace(os.sep, "/")
        if any(fnmatch.fnmatch(posix, pat) for pat in config.exclude):
            continue
        out.append(rel)
    return out


def lint_paths(paths: Sequence[str], root: str,
               config: Optional[LintConfig] = None) -> LintResult:
    config = config or LintConfig()
    result = LintResult()
    for p in paths:
        if not os.path.exists(os.path.join(root, p)):
            # a typo'd/renamed root must not read as a clean scan of
            # zero files — the exact silent failure the gate exists to
            # prevent
            result.findings.append(Finding(
                "JGL000", "error", str(p).replace(os.sep, "/"), 1, 1,
                "lint root does not exist (typo'd path in "
                "[tool.graftlint] paths or on the command line?)"))
    for rel in iter_lint_files(paths, root, config):
        try:
            with open(os.path.join(root, rel), encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            result.findings.append(Finding(
                "JGL000", "error", rel.replace(os.sep, "/"), 1, 1,
                f"unreadable file: {e}"))
            result.parse_errors += 1
            continue
        result.files += 1
        findings, suppressed = lint_source(source, rel, config)
        result.parse_errors += sum(
            1 for f in findings if "does not parse" in f.message)
        result.findings.extend(findings)
        result.suppressed += suppressed
    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return result
