"""graftlint configuration: ``[tool.graftlint]`` in ``pyproject.toml``.

Python 3.10 has no ``tomllib``, and the repo bakes in no third-party
TOML parser, so this module reads the *subset* of TOML the graftlint
sections actually use: ``[tool.graftlint]`` / ``[tool.graftlint.*]``
tables with string / bool / int values and (possibly multi-line) arrays
of strings.  Everything outside those sections is skipped unparsed —
the rest of ``pyproject.toml`` is setuptools' problem, not ours.
"""
from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

#: severities in increasing order of badness
SEVERITIES = ("info", "warning", "error")

_SECTION_RE = re.compile(r"^\s*\[([^\]]+)\]\s*(?:#.*)?$")
_KEY_RE = re.compile(r"^\s*([A-Za-z0-9_\-\.]+)\s*=\s*(.*)$")
_STR_RE = re.compile(r'"((?:[^"\\]|\\.)*)"|\'([^\']*)\'')


@dataclass(frozen=True)
class LintConfig:
    """Resolved graftlint configuration (defaults mirror the committed
    ``[tool.graftlint]`` section so ``LintConfig()`` behaves like the
    repo checkout)."""

    #: lint roots, relative to the repo root (files or directories)
    paths: Tuple[str, ...] = ("improved_body_parts_tpu", "tools",
                              "tests", "bench.py")
    #: fnmatch patterns (against the repo-relative posix path) to skip
    exclude: Tuple[str, ...] = ()
    #: rule ids disabled globally
    disable: Tuple[str, ...] = ()
    #: per-rule severity overrides, e.g. {"JGL005": "info"}
    severity: Dict[str, str] = field(default_factory=dict)
    #: callables whose RESULT is a donating jitted step: "name:pos[,pos]"
    donating_factories: Tuple[str, ...] = ("make_train_step:0",
                                           "make_distill_train_step:0")
    #: extra regexes over dotted callee names that produce device values
    extra_device_producers: Tuple[str, ...] = ()
    #: error-severity findings in tests/ are reported as warnings — test
    #: code exercises bad patterns on purpose; JGL000 stays an error
    tests_downgrade: bool = True

    def donated_positions(self, callee: str) -> Optional[Tuple[int, ...]]:
        """Donated positional-arg indices for a configured factory name,
        or None when ``callee`` is not a donating factory."""
        for spec in self.donating_factories:
            name, _, positions = spec.partition(":")
            if name == callee:
                if not positions:
                    return (0,)
                return tuple(int(p) for p in positions.split(",") if p)
        return None


class ConfigError(ValueError):
    """Malformed ``[tool.graftlint]`` content (bad severity, bad value
    shape) — loud, so a typo'd config cannot silently lint nothing."""


def _parse_value(raw: str, path: str, key: str):
    raw = raw.strip()
    if raw.startswith("["):
        body = raw[1:raw.rindex("]")]
        items = []
        for m in _STR_RE.finditer(body):
            items.append(m.group(1) if m.group(1) is not None
                         else m.group(2))
        return items
    if raw.startswith(("\"", "'")):
        m = _STR_RE.match(raw)
        if not m:
            raise ConfigError(f"{path}: unterminated string for {key!r}")
        return m.group(1) if m.group(1) is not None else m.group(2)
    bare = raw.split("#", 1)[0].strip()
    if bare in ("true", "false"):
        return bare == "true"
    try:
        return int(bare)
    except ValueError:
        raise ConfigError(
            f"{path}: unsupported value {bare!r} for {key!r} (graftlint "
            "accepts strings, bools, ints and arrays of strings)") from None


def parse_graftlint_tables(text: str, path: str = "pyproject.toml",
                           section: str = "tool.graftlint"
                           ) -> Dict[str, Dict[str, object]]:
    """``{section_suffix: {key: value}}`` for every ``[<section>*]``
    table in ``text`` (suffix "" for the root table, "severity" for
    ``[tool.graftlint.severity]``, ...).  ``section`` defaults to
    graftlint's table; the program auditor reuses the same TOML-subset
    parser for ``[tool.graftaudit]``."""
    tables: Dict[str, Dict[str, object]] = {}
    current: Optional[Dict[str, object]] = None
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = lines[i]
        i += 1
        sect = _SECTION_RE.match(line)
        if sect:
            name = sect.group(1).strip()
            if name == section:
                current = tables.setdefault("", {})
            elif name.startswith(section + "."):
                current = tables.setdefault(
                    name[len(section) + 1:], {})
            else:
                current = None
            continue
        if current is None:
            continue
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        kv = _KEY_RE.match(line)
        if not kv:
            raise ConfigError(f"{path}: cannot parse line {i}: {line!r}")
        key, raw = kv.group(1), kv.group(2)
        # multi-line array: keep consuming lines until brackets balance
        # (string contents never contain brackets in our config keys)
        while raw.count("[") > raw.count("]"):
            if i >= len(lines):
                raise ConfigError(
                    f"{path}: unterminated array for {key!r}")
            raw += " " + lines[i].strip()
            i += 1
        current[key.replace("-", "_")] = _parse_value(raw, path, key)
    return tables


def config_from_tables(tables: Dict[str, Dict[str, object]],
                       path: str = "pyproject.toml") -> LintConfig:
    root = dict(tables.get("", {}))
    severity = {str(k).upper(): str(v)
                for k, v in tables.get("severity", {}).items()}
    for rid, sev in severity.items():
        if sev not in SEVERITIES:
            raise ConfigError(
                f"{path}: [tool.graftlint.severity] {rid} = {sev!r} "
                f"(must be one of {SEVERITIES})")
    kwargs = {}
    for key, default in (("paths", None), ("exclude", None),
                         ("disable", None),
                         ("donating_factories", None),
                         ("extra_device_producers", None)):
        if key in root:
            val = root.pop(key)
            if not isinstance(val, list):
                raise ConfigError(f"{path}: {key} must be an array")
            kwargs[key] = tuple(str(v) for v in val)
    if "tests_downgrade" in root:
        val = root.pop("tests_downgrade")
        if not isinstance(val, bool):
            raise ConfigError(f"{path}: tests_downgrade must be a bool")
        kwargs["tests_downgrade"] = val
    if root:
        raise ConfigError(
            f"{path}: unknown [tool.graftlint] keys {sorted(root)}")
    if "disable" in kwargs:
        kwargs["disable"] = tuple(r.upper() for r in kwargs["disable"])
    return LintConfig(severity=severity, **kwargs)


def load_config(root: str) -> LintConfig:
    """Read ``<root>/pyproject.toml``'s graftlint tables; defaults when
    the file or the section is absent."""
    pp = os.path.join(root, "pyproject.toml")
    if not os.path.exists(pp):
        return LintConfig()
    with open(pp, encoding="utf-8") as f:
        text = f.read()
    return config_from_tables(parse_graftlint_tables(text, pp), pp)
