"""Pallas sketch of the assembly kernel's inner candidate walk.

``ops.assembly.greedy_assemble`` expresses the per-limb one-to-one
used-peak filter (reference: evaluate.py:260-271) as a
``lax.while_loop`` inside the fused decode program; XLA schedules that
walk serially against the rest of the program.  This module is the
hand-scheduled Mosaic variant of exactly that inner loop — the hot
sequential part — as a Pallas kernel: one grid step per limb, the
used-A/used-B occupancy masks and the candidate slots living in SMEM
(scalar-indexed loads/stores are natural there; the walk is pure
scalar control flow, no vector work).

Status: a SKETCH, gated behind ``tools/pallas_check.py --assembly``
like the focal kernel before it — parity-tested in interpreter mode on
CPU (tests/test_assembly.py), to be timed under the real Mosaic
lowering the moment a chip is available.  Wire it into
``greedy_assemble`` only if it wins on hardware; the XLA while_loop
path stays the shipped default either way.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _walk_kernel(slot_a_ref, slot_b_ref, valid_ref, limit_ref, sel_ref,
                 used_a, used_b):
    """One limb's walk: mark the rank-ordered candidates that survive
    the one-to-one used filter, up to ``limit`` selections."""
    k = used_a.shape[0]
    m_cap = sel_ref.shape[-1]

    def clear(i, carry):
        used_a[i] = 0
        used_b[i] = 0
        return carry

    jax.lax.fori_loop(0, k, clear, 0)
    lim = limit_ref[0]

    def body(m, nrows):
        sa = slot_a_ref[0, m]
        sb = slot_b_ref[0, m]
        ok = ((valid_ref[0, m] > 0) & (nrows < lim)
              & (used_a[sa] == 0) & (used_b[sb] == 0))
        sel_ref[0, m] = jnp.where(ok, 1, 0)

        @pl.when(ok)
        def _take():
            used_a[sa] = 1
            used_b[sb] = 1

        return nrows + jnp.where(ok, 1, 0)

    jax.lax.fori_loop(0, m_cap, body, jnp.int32(0))


def candidate_walk_pallas(slot_a, slot_b, valid, limit, k: int,
                          interpret: bool = False):
    """Selection flags (L, M) int32 for the per-limb one-to-one walk.

    :param slot_a, slot_b: (L, M) int32 candidate endpoint slots in
        [0, k) — ``ops.peaks.LimbCandidates`` order (rank-sorted,
        validity a prefix)
    :param valid: (L, M) bool/int32 acceptance flags
    :param limit: (L,) int32 per-limb selection cap (min of the two
        endpoint channels' true peak counts)
    :param k: top-K slot capacity (the used-mask width)
    """
    n_limbs, m_cap = slot_a.shape
    spec_row = pl.BlockSpec((1, m_cap), lambda li: (li, 0),
                            memory_space=pltpu.SMEM)
    return pl.pallas_call(
        _walk_kernel,
        grid=(n_limbs,),
        in_specs=[spec_row, spec_row, spec_row,
                  pl.BlockSpec((1,), lambda li: (li,),
                               memory_space=pltpu.SMEM)],
        out_specs=spec_row,
        out_shape=jax.ShapeDtypeStruct((n_limbs, m_cap), jnp.int32),
        scratch_shapes=[pltpu.SMEM((k,), jnp.int32),
                        pltpu.SMEM((k,), jnp.int32)],
        interpret=interpret,
    )(jnp.asarray(slot_a, jnp.int32), jnp.asarray(slot_b, jnp.int32),
      jnp.asarray(valid, jnp.int32), jnp.asarray(limit, jnp.int32))


def candidate_walk_reference(slot_a, slot_b, valid, limit):
    """Host NumPy reference — the literal per-limb walk of
    ``infer.decode.decode_compact`` (used filter + limit), the
    semantics both the XLA while_loop and the Pallas kernel implement."""
    import numpy as np

    n_limbs, m_cap = slot_a.shape
    sel = np.zeros((n_limbs, m_cap), np.int32)
    for li in range(n_limbs):
        used_a, used_b = set(), set()
        taken = 0
        for m in range(m_cap):
            if not valid[li, m] or taken >= limit[li]:
                break  # validity is a prefix; the host walk stops here
            sa, sb = int(slot_a[li, m]), int(slot_b[li, m])
            if sa in used_a or sb in used_b:
                continue
            used_a.add(sa)
            used_b.add(sb)
            sel[li, m] = 1
            taken += 1
    return sel


def walk_parity_benchmark(n_limbs: int = 30, m_cap: int = 128,
                          k: int = 64, trials: int = 8, iters: int = 20,
                          interpret: bool = False) -> dict:
    """Parity + timing of the Pallas candidate walk vs the host
    reference, on randomized rank-ordered candidate sets.  The single
    check ``tools/pallas_check.py --assembly`` runs."""
    import time

    import numpy as np

    rng = np.random.default_rng(0)
    ok = True
    fixtures = []
    for _ in range(trials):
        slot_a = rng.integers(0, k, (n_limbs, m_cap)).astype(np.int32)
        slot_b = rng.integers(0, k, (n_limbs, m_cap)).astype(np.int32)
        counts = rng.integers(0, m_cap + 1, n_limbs)
        valid = (np.arange(m_cap)[None, :] < counts[:, None])
        limit = rng.integers(0, k + 1, n_limbs).astype(np.int32)
        fixtures.append((slot_a, slot_b, valid, limit))
        got = np.asarray(candidate_walk_pallas(
            slot_a, slot_b, valid, limit, k, interpret=interpret))
        want = candidate_walk_reference(slot_a, slot_b, valid, limit)
        ok = ok and bool((got == want).all())

    slot_a, slot_b, valid, limit = fixtures[0]
    run = jax.jit(lambda a, b, v, li: candidate_walk_pallas(
        a, b, v, li, k, interpret=interpret))
    jax.block_until_ready(run(slot_a, slot_b, valid, limit))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run(slot_a, slot_b, valid, limit)
    jax.block_until_ready(out)
    pallas_ms = (time.perf_counter() - t0) / iters * 1e3
    t0 = time.perf_counter()
    for _ in range(iters):
        candidate_walk_reference(slot_a, slot_b, valid, limit)
    host_ms = (time.perf_counter() - t0) / iters * 1e3
    return {"parity_ok": ok, "pallas_ms": pallas_ms, "host_ms": host_ms,
            "trials": trials}
