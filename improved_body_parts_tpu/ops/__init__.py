from .losses import avg_pool_to, downsample_mask, focal_l2, l1, l2, multi_task_loss
from .gt_device import make_gt_synthesizer
from .nms import gaussian_blur, keypoint_nms, peak_mask_np, refine_peaks
from .peaks import (
    LimbCandidates,
    PairStats,
    TopKPeaks,
    limb_pair_stats,
    limb_topk_candidates,
    topk_peaks,
)

__all__ = ["avg_pool_to", "downsample_mask", "focal_l2", "l1", "l2",
           "multi_task_loss", "gaussian_blur", "keypoint_nms",
           "peak_mask_np", "refine_peaks", "make_gt_synthesizer",
           "LimbCandidates", "PairStats", "TopKPeaks", "limb_pair_stats",
           "limb_topk_candidates", "topk_peaks"]
