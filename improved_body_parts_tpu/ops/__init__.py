from .losses import avg_pool_to, downsample_mask, focal_l2, l2, multi_task_loss

__all__ = ["avg_pool_to", "downsample_mask", "focal_l2", "l2",
           "multi_task_loss"]
