"""Peak NMS (jitted, on-device) + vectorized sub-pixel refinement (host).

Reference: utils/util.py:177-183 ``keypoint_heatmap_nms`` (3x3 max-pool with
reflect padding, threshold thre1) and :186-211 ``refine_centroid`` (weighted
centroid over a (2r+1)² box; falls back to the raw anchor when the box
crosses the border).  The reference refines peak-by-peak in Python; here all
peaks refine in one vectorized gather.
"""
from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("kernel",))
def keypoint_nms(heat: jnp.ndarray, kernel: int = 3, thre: float = 0.1
                 ) -> jnp.ndarray:
    """heat: (H, W, C) score maps → same shape with non-peaks zeroed."""
    pad = (kernel - 1) // 2
    padded = jnp.pad(heat, ((pad, pad), (pad, pad), (0, 0)), mode="reflect")
    hmax = jax.lax.reduce_window(
        padded, -jnp.inf, jax.lax.max,
        window_dimensions=(kernel, kernel, 1),
        window_strides=(1, 1, 1), padding="VALID")
    keep = (hmax == heat) & (heat >= thre)
    return jnp.where(keep, heat, 0.0)


def peak_mask_np(heat: np.ndarray, thre: float = 0.1) -> np.ndarray:
    """Boolean 3x3-NMS peak mask (reflect padding), NumPy host path — the
    maps are already on the host after prediction, so a device round-trip
    just for NMS would cost more than the op."""
    padded = np.pad(heat, ((1, 1), (1, 1), (0, 0)), mode="reflect")
    hmax = heat.copy()
    for dy in range(3):
        for dx in range(3):
            if dy == 1 and dx == 1:
                continue
            np.maximum(hmax, padded[dy:dy + heat.shape[0],
                                    dx:dx + heat.shape[1]], out=hmax)
    return (hmax == heat) & (heat >= thre)




@partial(jax.jit, static_argnames=("kernel_size",))
def gaussian_blur(maps: jnp.ndarray, kernel_size: int = 5,
                  sigma: float = 3.0) -> jnp.ndarray:
    """Depthwise Gaussian smoothing with reflect padding, (H, W, C)
    (reference: utils/util.py:103-174 ``GaussianSmoothing`` — kept for the
    inventory; the final decode path deliberately does not smooth,
    evaluate.py:178-182)."""
    r = (kernel_size - 1) / 2
    grid = jnp.arange(kernel_size, dtype=jnp.float32) - r
    k1 = jnp.exp(-(grid ** 2) / (2 * sigma * sigma))
    kernel = jnp.outer(k1, k1)
    kernel = kernel / kernel.sum()
    pad = (kernel_size - 1) // 2
    x = jnp.pad(maps, ((pad, pad), (pad, pad), (0, 0)), mode="reflect")
    x = jnp.moveaxis(x, -1, 0)[:, None]            # (C, 1, H, W)
    out = jax.lax.conv_general_dilated(
        x, kernel[None, None], window_strides=(1, 1), padding="VALID")
    return jnp.moveaxis(out[:, 0], 0, -1)


def refine_peaks(score_map: np.ndarray, xs: np.ndarray, ys: np.ndarray,
                 radius: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Weighted-centroid refinement of integer peaks on one channel.

    Returns (x_refined, y_refined, score).  Peaks whose window crosses the
    border keep their integer coords and raw score (util.py:201-202).
    """
    h, w = score_map.shape
    n = xs.shape[0]
    if n == 0:
        return (np.zeros(0), np.zeros(0), np.zeros(0))
    r = radius
    inside = (xs - r >= 0) & (xs + r + 1 <= w) & (ys - r >= 0) & (ys + r + 1 <= h)

    offs = np.arange(-r, r + 1)
    wy = np.clip(ys[:, None] + offs[None, :], 0, h - 1)
    wx = np.clip(xs[:, None] + offs[None, :], 0, w - 1)
    boxes = score_map[wy[:, :, None], wx[:, None, :]]  # (n, 2r+1, 2r+1)

    total = boxes.sum(axis=(1, 2))
    total = np.where(total == 0, 1.0, total)
    gx = (boxes * offs[None, None, :]).sum(axis=(1, 2)) / total
    gy = (boxes * offs[None, :, None]).sum(axis=(1, 2)) / total

    x_ref = np.where(inside, xs + gx, xs.astype(np.float64))
    y_ref = np.where(inside, ys + gy, ys.astype(np.float64))
    score = np.where(inside, boxes.mean(axis=(1, 2)), score_map[ys, xs])
    return x_ref, y_ref, score
