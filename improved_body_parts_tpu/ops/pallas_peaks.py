"""Pallas sketches of the compact decode path's two dense inner loops.

``ops.peaks`` runs the whole compact extraction as XLA ops inside the
fused serve program; its two hot inner loops are

- the per-channel NMS + top-K + sub-pixel refinement of
  :func:`ops.peaks.topk_peaks` (one independent (H, W) problem per
  keypoint channel), and
- the dense (L, K, K, S) limb-score gather of
  :func:`ops.peaks.limb_pair_stats` (one independent (K, K, S) sampling
  problem per limb channel).

Both are embarrassingly parallel over their leading channel axis, which
XLA cannot exploit as a schedule: it fuses them into the surrounding
program and serializes the gathers.  This module hand-schedules each as
a Pallas kernel — ONE grid step per channel/limb, the channel's map
resident in VMEM for the whole step, peak/sample coordinates produced
and consumed on-core — following the ``ops/pallas_assembly.py`` sketch
discipline.

The kernels replicate the reference functions' jnp computation
graph operation-for-operation, so interpreter mode is EXACTLY
bit-identical to ``ops.peaks`` (tests/test_pallas_peaks.py pins the
full payload).  Associative reductions (the 3×3 NMS max) are decomposed
into shifted ``jnp.maximum`` chains, which are order-exact; everything
else is elementwise or matches the reference's own reduction shapes.

Status: SKETCHES, gated behind ``tools/pallas_check.py --peaks`` /
``--limbs`` like the focal and assembly kernels before them —
parity-tested in interpreter mode on CPU, to be timed under the real
Mosaic lowering the moment a chip is available.  Production selection:
``InferenceParams.use_pallas_decode`` routes the compact extraction
through these variants (interpreter mode off-TPU), so the real-hardware
A/B is one config flip, but the XLA path stays the shipped default
either way.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .peaks import _NEG, PairStats, TopKPeaks

# --------------------------------------------------------------------- #
# peak NMS + top-K + refinement (ops/peaks.py topk_peaks)                #
# --------------------------------------------------------------------- #


def _peaks_kernel(heat_ref, vh_ref, vw_ref, xs_ref, ys_ref, xr_ref,
                  yr_ref, sc_ref, va_ref, ct_ref, *, thre: float, k: int,
                  radius: int):
    """One keypoint channel's NMS → top-K → refinement, map in VMEM."""
    heat = heat_ref[0]                                   # (H, W)
    h, w = heat.shape
    valid_h = vh_ref[0, 0]
    valid_w = vw_ref[0, 0]

    rows = jax.lax.broadcasted_iota(jnp.int32, (h, w), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (h, w), 1)
    region = (rows < valid_h) & (cols < valid_w)
    masked = jnp.where(region, heat, _NEG)

    # 3×3 reflect-pad max pool as a chain of shifted maxima — max is
    # associative/commutative exactly, so this equals the reference's
    # reduce_window bit-for-bit
    padded = jnp.pad(masked, ((1, 1), (1, 1)), mode="reflect")
    hmax = masked
    for dy in range(3):
        for dx in range(3):
            hmax = jnp.maximum(hmax,
                               jax.lax.slice(padded, (dy, dx),
                                             (dy + h, dx + w)))
    keep = (hmax == masked) & (masked >= thre)
    ct_ref[0] = keep.sum(dtype=jnp.int32)

    flat = jnp.where(keep, masked, _NEG).reshape(h * w)

    # iterative top-K: K rounds of (max, first-max-index, mask) — the
    # same value/tie order as lax.top_k (stable: equal values ascend by
    # index), expressed in maxima/where vector ops a Mosaic lowering
    # supports
    iota = jax.lax.broadcasted_iota(jnp.int32, (h * w,), 0)

    def select(_, carry):
        flat, vals, idxs, i = carry
        v = jnp.max(flat)
        j = jnp.min(jnp.where(flat == v, iota, h * w))
        vals = vals.at[i].set(v)
        idxs = idxs.at[i].set(j)
        flat = jnp.where(iota == j, -jnp.inf, flat)
        return flat, vals, idxs, i + 1

    vals = jnp.full((k,), -jnp.inf, heat.dtype)
    idxs = jnp.zeros((k,), jnp.int32)
    _, vals, idxs, _ = jax.lax.fori_loop(
        0, k, select, (flat, vals, idxs, jnp.int32(0)))

    ys = idxs // w
    xs = idxs % w
    valid = vals >= thre

    # weighted-centroid refinement over (2r+1)² windows gathered from
    # the RAW map (clipped indices), exactly the reference's shapes
    r = radius
    # 2-D iota (TPU requires ≥2-D) sliced down — jnp.arange would be a
    # captured host constant, which pallas_call rejects
    offs = jax.lax.broadcasted_iota(jnp.int32, (1, 2 * r + 1), 1)[0] - r
    wy = jnp.clip(ys[:, None] + offs[None, :], 0, h - 1)
    wx = jnp.clip(xs[:, None] + offs[None, :], 0, w - 1)
    flat_idx = (wy[:, :, None] * w + wx[:, None, :]).reshape(-1)
    boxes = jnp.take(heat.reshape(h * w), flat_idx).reshape(
        k, 2 * r + 1, 2 * r + 1)

    total = boxes.sum(axis=(-1, -2))
    total = jnp.where(total == 0, 1.0, total)
    offs_f = offs.astype(boxes.dtype)
    gx = (boxes * offs_f[None, None, :]).sum(axis=(-1, -2)) / total
    gy = (boxes * offs_f[None, :, None]).sum(axis=(-1, -2)) / total
    inside = ((xs - r >= 0) & (xs + r + 1 <= valid_w)
              & (ys - r >= 0) & (ys + r + 1 <= valid_h))
    xs_ref[0] = xs
    ys_ref[0] = ys
    xr_ref[0] = jnp.where(inside, xs + gx, xs.astype(gx.dtype))
    yr_ref[0] = jnp.where(inside, ys + gy, ys.astype(gy.dtype))
    sc_ref[0] = jnp.where(inside, boxes.mean(axis=(-1, -2)), vals)
    va_ref[0] = valid.astype(jnp.int32)


def topk_peaks_pallas(heat: jnp.ndarray, valid_h, valid_w, *, thre: float,
                      k: int, radius: int,
                      interpret: bool = False) -> TopKPeaks:
    """Pallas variant of :func:`ops.peaks.topk_peaks` — one grid step
    per keypoint channel, that channel's (H, W) map VMEM-resident for
    NMS, top-K selection AND refinement (the XLA path re-materializes
    it between the fused stages).  Same contract, bit-identical payload
    in interpreter mode."""
    h, w, c = heat.shape
    chan = jnp.transpose(heat, (2, 0, 1))                # (C, H, W)
    vh = jnp.asarray(valid_h, jnp.int32).reshape(1, 1)
    vw = jnp.asarray(valid_w, jnp.int32).reshape(1, 1)
    scalar = pl.BlockSpec((1, 1), lambda ci: (0, 0),
                          memory_space=pltpu.SMEM)
    row = lambda dt: jax.ShapeDtypeStruct((c, k), dt)   # noqa: E731
    import functools

    xs, ys, xr, yr, sc, va, ct = pl.pallas_call(
        functools.partial(_peaks_kernel, thre=thre, k=k, radius=radius),
        grid=(c,),
        in_specs=[pl.BlockSpec((1, h, w), lambda ci: (ci, 0, 0)),
                  scalar, scalar],
        out_specs=[pl.BlockSpec((1, k), lambda ci: (ci, 0))] * 6
        + [pl.BlockSpec((1,), lambda ci: (ci,),
                        memory_space=pltpu.SMEM)],
        out_shape=[row(jnp.int32), row(jnp.int32), row(jnp.float32),
                   row(jnp.float32), row(jnp.float32), row(jnp.int32),
                   jax.ShapeDtypeStruct((c,), jnp.int32)],
        interpret=interpret,
    )(chan, vh, vw)
    return TopKPeaks(xs, ys, xr, yr, sc, va.astype(bool), ct)


# --------------------------------------------------------------------- #
# dense (L, K, K, S) limb-score gather (ops/peaks.py limb_pair_stats)   #
# --------------------------------------------------------------------- #


def _limbs_kernel(paf_ref, ax_ref, ay_ref, bx_ref, by_ref, mean_ref,
                  above_ref, m_ref, norm_ref, *, num_samples: int,
                  thre2: float, h: int, w: int):
    """One limb channel's dense A×B segment sampling, map in VMEM."""
    paf_row = paf_ref[0]                                 # (H*W,)
    ax, ay = ax_ref[0], ay_ref[0]                        # (K,)
    bx, by = bx_ref[0], by_ref[0]

    vx = bx[None, :] - ax[:, None]                       # (K, K)
    vy = by[None, :] - ay[:, None]
    norm = jnp.sqrt(vx * vx + vy * vy)
    m = jnp.minimum(jnp.round(norm + 1), num_samples).astype(jnp.int32)

    s = jax.lax.broadcasted_iota(norm.dtype, (1, num_samples), 1)[0]
    denom = jnp.maximum(m - 1, 1).astype(norm.dtype)
    t = jnp.minimum(s[None, None, :] / denom[..., None], 1.0)
    px = ax[:, None, None] + t * vx[..., None]           # (K, K, S)
    py = ay[:, None, None] + t * vy[..., None]
    xi = jnp.clip(jnp.round(px).astype(jnp.int32), 0, w - 1)
    yi = jnp.clip(jnp.round(py).astype(jnp.int32), 0, h - 1)

    vals = jnp.take(paf_row, (yi * w + xi).reshape(-1)).reshape(px.shape)

    in_seg = s[None, None, :] < m[..., None]
    mean_ref[0] = (jnp.where(in_seg, vals, 0.0).sum(-1)
                   / jnp.maximum(m, 1).astype(vals.dtype))
    above_ref[0] = ((vals > thre2) & in_seg).sum(-1, dtype=jnp.int32)
    m_ref[0] = m
    norm_ref[0] = norm


def limb_pair_stats_pallas(paf: jnp.ndarray, x_ref: jnp.ndarray,
                           y_ref: jnp.ndarray, *,
                           limbs_from: Tuple[int, ...],
                           limbs_to: Tuple[int, ...], num_samples: int,
                           thre2: float,
                           interpret: bool = False) -> PairStats:
    """Pallas variant of :func:`ops.peaks.limb_pair_stats` — one grid
    step per limb, that limb's paf channel VMEM-resident for all K×K×S
    samples (the dense gather never leaves the core).  Same contract,
    bit-identical payload in interpreter mode."""
    import functools

    h, w, n_limbs = paf.shape
    k = x_ref.shape[1]
    la = jnp.asarray(limbs_from)
    lb = jnp.asarray(limbs_to)
    paf_t = paf.transpose(2, 0, 1).reshape(n_limbs, h * w)
    ends = (x_ref[la], y_ref[la], x_ref[lb], y_ref[lb])  # (L, K) each
    row_k = pl.BlockSpec((1, k), lambda li: (li, 0))
    grid_kk = pl.BlockSpec((1, k, k), lambda li: (li, 0, 0))
    out = lambda dt: jax.ShapeDtypeStruct((n_limbs, k, k), dt)  # noqa: E731

    mean, above, m, norm = pl.pallas_call(
        functools.partial(_limbs_kernel, num_samples=num_samples,
                          thre2=thre2, h=h, w=w),
        grid=(n_limbs,),
        in_specs=[pl.BlockSpec((1, h * w), lambda li: (li, 0))]
        + [row_k] * 4,
        out_specs=[grid_kk] * 4,
        out_shape=[out(jnp.float32), out(jnp.int32), out(jnp.int32),
                   out(jnp.float32)],
        interpret=interpret,
    )(paf_t, *ends)
    return PairStats(mean, above, m, norm)


# --------------------------------------------------------------------- #
# parity + timing benchmarks (tools/pallas_check.py --peaks / --limbs)  #
# --------------------------------------------------------------------- #


def _rand_peaks_fixture(rng, h, w, c, peaky: float = 0.02):
    """A heat tensor with sparse genuine peaks (most maps are near-flat
    noise with a few strong modes — the regime the NMS tie/threshold
    logic actually sees)."""
    import numpy as np

    heat = rng.normal(0.0, 0.05, (h, w, c)).astype(np.float32)
    n_spikes = max(1, int(h * w * peaky))
    for ci in range(c):
        ys = rng.integers(0, h, n_spikes)
        xs = rng.integers(0, w, n_spikes)
        heat[ys, xs, ci] += rng.uniform(0.3, 1.0, n_spikes)
    return heat


def peaks_parity_benchmark(h: int = 128, w: int = 128, c: int = 18,
                           k: int = 32, radius: int = 2,
                           thre: float = 0.1, trials: int = 4,
                           iters: int = 10,
                           interpret: bool = False) -> dict:
    """Parity + timing of the Pallas top-K peaks kernel vs the XLA path
    (``ops.peaks.topk_peaks``) — the check ``tools/pallas_check.py
    --peaks`` runs.  Parity is EXACT payload equality."""
    import time

    import numpy as np

    from .peaks import topk_peaks

    rng = np.random.default_rng(0)
    ok = True
    fixture = None
    for ti in range(trials):
        heat = _rand_peaks_fixture(rng, h, w, c)
        vh = int(rng.integers(h // 2, h + 1))
        vw = int(rng.integers(w // 2, w + 1))
        fixture = fixture or (heat, vh, vw)
        want = topk_peaks(jnp.asarray(heat), vh, vw, thre=thre, k=k,
                          radius=radius)
        got = topk_peaks_pallas(jnp.asarray(heat), vh, vw, thre=thre,
                                k=k, radius=radius, interpret=interpret)
        for a, b in zip(want, got):
            ok = ok and bool((np.asarray(a) == np.asarray(b)).all())

    heat, vh, vw = fixture
    run_p = jax.jit(lambda x: topk_peaks_pallas(
        x, vh, vw, thre=thre, k=k, radius=radius, interpret=interpret))
    run_x = jax.jit(lambda x: topk_peaks(
        x, vh, vw, thre=thre, k=k, radius=radius))
    heat_d = jnp.asarray(heat)
    jax.block_until_ready(run_p(heat_d))
    jax.block_until_ready(run_x(heat_d))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run_p(heat_d)
    jax.block_until_ready(out)
    pallas_ms = (time.perf_counter() - t0) / iters * 1e3
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run_x(heat_d)
    jax.block_until_ready(out)
    xla_ms = (time.perf_counter() - t0) / iters * 1e3
    return {"kernel": "topk_peaks", "parity_ok": ok,
            "pallas_ms": pallas_ms, "xla_ms": xla_ms,
            "pallas_wins": pallas_ms < xla_ms, "trials": trials,
            "shape": [h, w, c], "k": k, "interpret": interpret}


def limbs_parity_benchmark(h: int = 128, w: int = 128, c: int = 18,
                           n_limbs: int = 30, k: int = 32,
                           num_samples: int = 20, thre2: float = 0.05,
                           trials: int = 4, iters: int = 10,
                           interpret: bool = False) -> dict:
    """Parity + timing of the Pallas limb-gather kernel vs the XLA path
    (``ops.peaks.limb_pair_stats``) — the check ``tools/pallas_check.py
    --limbs`` runs.  Parity is EXACT payload equality."""
    import time

    import numpy as np

    from .peaks import limb_pair_stats

    rng = np.random.default_rng(1)
    limbs_from = tuple(int(v) for v in rng.integers(0, c, n_limbs))
    limbs_to = tuple(int(v) for v in rng.integers(0, c, n_limbs))
    ok = True
    fixture = None
    for _ in range(trials):
        paf = rng.normal(0.0, 0.2, (h, w, n_limbs)).astype(np.float32)
        x_ref = rng.uniform(0, w - 1, (c, k)).astype(np.float32)
        y_ref = rng.uniform(0, h - 1, (c, k)).astype(np.float32)
        fixture = fixture or (paf, x_ref, y_ref)
        want = limb_pair_stats(jnp.asarray(paf), jnp.asarray(x_ref),
                               jnp.asarray(y_ref), limbs_from=limbs_from,
                               limbs_to=limbs_to,
                               num_samples=num_samples, thre2=thre2)
        got = limb_pair_stats_pallas(
            jnp.asarray(paf), jnp.asarray(x_ref), jnp.asarray(y_ref),
            limbs_from=limbs_from, limbs_to=limbs_to,
            num_samples=num_samples, thre2=thre2, interpret=interpret)
        for a, b in zip(want, got):
            ok = ok and bool((np.asarray(a) == np.asarray(b)).all())

    paf, x_ref, y_ref = fixture
    args = (jnp.asarray(paf), jnp.asarray(x_ref), jnp.asarray(y_ref))
    run_p = jax.jit(lambda p, x, y: limb_pair_stats_pallas(
        p, x, y, limbs_from=limbs_from, limbs_to=limbs_to,
        num_samples=num_samples, thre2=thre2, interpret=interpret))
    run_x = jax.jit(lambda p, x, y: limb_pair_stats(
        p, x, y, limbs_from=limbs_from, limbs_to=limbs_to,
        num_samples=num_samples, thre2=thre2))
    jax.block_until_ready(run_p(*args))
    jax.block_until_ready(run_x(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run_p(*args)
    jax.block_until_ready(out)
    pallas_ms = (time.perf_counter() - t0) / iters * 1e3
    t0 = time.perf_counter()
    for _ in range(iters):
        out = run_x(*args)
    jax.block_until_ready(out)
    xla_ms = (time.perf_counter() - t0) / iters * 1e3
    return {"kernel": "limb_pair_stats", "parity_ok": ok,
            "pallas_ms": pallas_ms, "xla_ms": xla_ms,
            "pallas_wins": pallas_ms < xla_ms, "trials": trials,
            "shape": [h, w, n_limbs], "k": k,
            "num_samples": num_samples, "interpret": interpret}
