"""Pallas TPU kernel for the masked focal L2 loss.

The XLA path (`ops/losses.py focal_l2`) is already well fused; this kernel is
the hand-scheduled alternative for the hot loss op: one VMEM pass per
(stack, batch) tile computes the focal-weighted masked squared error and its
per-stack sum without materializing any of the four intermediate tensors
(st / factor / modulated mask / squared error) in HBM.  Gradient is supplied
analytically via custom_vjp (a second kernel) — the same derivative the
reference's autograd produces for loss_model.py:151-155.

Numerically identical to ``focal_l2`` with ``gamma=1`` (parity-tested in
interpreter mode; see tests/test_pallas_focal.py).

Layout: pred (S, N, H, W, C) fp32; gt/mask broadcast over S; the per-channel
task modulation (keypoint ×3, person-mask ×0.1) is passed as a (C,) vector so
mask stays (N, H, W, 1).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _fwd_kernel(pred_ref, gt_ref, mask_ref, chan_ref, out_ref):
    s = pred_ref[0, 0]          # (H, W, C)
    g = gt_ref[0]               # (H, W, C)
    m = mask_ref[0] * chan_ref[:]   # (H, W, 1) * (C,) → (H, W, C)
    st = jnp.where(g >= 0.01, s, 1.0 - s)
    factor = jnp.abs(1.0 - st)
    val = jnp.sum((s - g) ** 2 * factor * m)

    n = pl.program_id(1)

    @pl.when(n == 0)
    def _init():
        out_ref[0] = 0.0

    out_ref[0] += val


def _bwd_kernel(pred_ref, gt_ref, mask_ref, chan_ref, ct_ref, dpred_ref):
    s = pred_ref[0, 0]
    g = gt_ref[0]
    m = mask_ref[0] * chan_ref[:]
    fg = g >= 0.01
    st = jnp.where(fg, s, 1.0 - s)
    factor = jnp.abs(1.0 - st)
    diff = s - g
    # d factor/d s: fg → -sign(1-s); else sign(s)  (|1-st| differentiated)
    dfactor = jnp.where(fg, -jnp.sign(1.0 - s), jnp.sign(s))
    grad = (2.0 * diff * factor + diff * diff * dfactor) * m
    dpred_ref[0, 0] = grad * ct_ref[0]


def _grids(pred):
    S, N, H, W, C = pred.shape
    grid = (S, N)
    pred_spec = pl.BlockSpec((1, 1, H, W, C), lambda s, n: (s, n, 0, 0, 0))
    gt_spec = pl.BlockSpec((1, H, W, C), lambda s, n: (n, 0, 0, 0))
    mask_spec = pl.BlockSpec((1, H, W, 1), lambda s, n: (n, 0, 0, 0))
    chan_spec = pl.BlockSpec((C,), lambda s, n: (0,))
    return grid, pred_spec, gt_spec, mask_spec, chan_spec


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def focal_l2_pallas(pred, gt, mask, chan_scale, interpret=False):
    """Per-stack focal L2 sums: pred (S,N,H,W,C) → (S,).

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU tests).
    """
    return _focal_fwd_impl(pred, gt, mask, chan_scale, interpret)


def _focal_fwd_impl(pred, gt, mask, chan_scale, interpret):
    S, N, H, W, C = pred.shape
    grid, pred_spec, gt_spec, mask_spec, chan_spec = _grids(pred)
    out_spec = pl.BlockSpec((1,), lambda s, n: (s,))
    return pl.pallas_call(
        _fwd_kernel,
        out_shape=jax.ShapeDtypeStruct((S,), jnp.float32),
        grid=grid,
        in_specs=[pred_spec, gt_spec, mask_spec, chan_spec],
        out_specs=out_spec,
        interpret=interpret,
    )(pred.astype(jnp.float32), gt.astype(jnp.float32),
      mask.astype(jnp.float32), chan_scale.astype(jnp.float32))


def _focal_fwd(pred, gt, mask, chan_scale, interpret):
    out = _focal_fwd_impl(pred, gt, mask, chan_scale, interpret)
    return out, (pred, gt, mask, chan_scale)


def _focal_bwd(interpret, res, ct):
    pred, gt, mask, chan_scale = res
    S, N, H, W, C = pred.shape
    grid, pred_spec, gt_spec, mask_spec, chan_spec = _grids(pred)
    ct_spec = pl.BlockSpec((1,), lambda s, n: (s,))
    dpred = pl.pallas_call(
        _bwd_kernel,
        out_shape=jax.ShapeDtypeStruct(pred.shape, jnp.float32),
        grid=grid,
        in_specs=[pred_spec, gt_spec, mask_spec, chan_spec, ct_spec],
        out_specs=pred_spec,
        interpret=interpret,
    )(pred.astype(jnp.float32), gt.astype(jnp.float32),
      mask.astype(jnp.float32), chan_scale.astype(jnp.float32),
      ct.astype(jnp.float32))
    # gt / mask / chan_scale are labels & weights — no gradients needed
    return dpred, None, None, None


focal_l2_pallas.defvjp(_focal_fwd, _focal_bwd)
