"""Pallas TPU kernel for the masked focal L2 loss.

The XLA path (`ops/losses.py focal_l2`) is already well fused; this kernel is
the hand-scheduled alternative for the hot loss op: one VMEM pass per
(stack, batch) tile computes the focal-weighted masked squared error and its
per-stack sum without materializing any of the four intermediate tensors
(st / factor / modulated mask / squared error) in HBM.  Gradient is supplied
analytically via custom_vjp (a second kernel) — the same derivative the
reference's autograd produces for loss_model.py:151-155.

Numerically identical to ``focal_l2`` with ``gamma=1`` (parity-tested in
interpreter mode; see tests/test_pallas_focal.py).

Layout: pred (S, N, H, W, C) fp32; gt/mask broadcast over S; the per-channel
task modulation (keypoint ×3, person-mask ×0.1) is passed as a (C,) vector so
mask stays (N, H, W, 1).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _fwd_kernel(pred_ref, gt_ref, mask_ref, chan_ref, out_ref):
    s = pred_ref[0, 0]          # (Ht, W, C)
    g = gt_ref[0]               # (Ht, W, C)
    m = mask_ref[0] * chan_ref[:]   # (Ht, W, 1) * (C,) → (Ht, W, C)
    st = jnp.where(g >= 0.01, s, 1.0 - s)
    factor = jnp.abs(1.0 - st)
    val = jnp.sum((s - g) ** 2 * factor * m)

    # out_ref is the FULL (S,) accumulator in SMEM (Mosaic rejects rank-1
    # blocks narrower than the array); index it by the stack program id
    s_idx = pl.program_id(0)
    n = pl.program_id(1)
    h = pl.program_id(2)

    @pl.when(jnp.logical_and(n == 0, h == 0))
    def _init():
        out_ref[s_idx] = 0.0

    out_ref[s_idx] += val


def _bwd_kernel(pred_ref, gt_ref, mask_ref, chan_ref, ct_ref, dpred_ref):
    s = pred_ref[0, 0]
    g = gt_ref[0]
    m = mask_ref[0] * chan_ref[:]
    fg = g >= 0.01
    st = jnp.where(fg, s, 1.0 - s)
    factor = jnp.abs(1.0 - st)
    diff = s - g
    # d factor/d s differentiates |1-st|. At the kink (st == 1 exactly) we
    # follow JAX's abs-VJP convention (subgradient +1, select(x>=0,1,-1))
    # so the kernel is bitwise-swappable with the XLA loss; torch's autograd
    # (the reference, loss_model.py:151-155) picks 0 there — a measure-zero
    # deviation observed once in 13M points on real hardware.
    dfactor = jnp.where(fg,
                        -jnp.where(1.0 - s >= 0.0, 1.0, -1.0),
                        jnp.where(s >= 0.0, 1.0, -1.0))
    grad = (2.0 * diff * factor + diff * diff * dfactor) * m
    dpred_ref[0, 0] = grad * ct_ref[pl.program_id(0)]


def _grids(pred):
    S, N, H, W, C = pred.shape
    # Tile the H axis so a block (plus double-buffering) fits the ~16 MB
    # scoped-VMEM budget: a full (128,128,50) f32 block is 3.3 MB per
    # operand, which OOMs the backward kernel's stack on real hardware.
    ht = next((t for t in (32, 16, 8) if H % t == 0), H)
    grid = (S, N, H // ht)
    pred_spec = pl.BlockSpec((1, 1, ht, W, C),
                             lambda s, n, h: (s, n, h, 0, 0))
    gt_spec = pl.BlockSpec((1, ht, W, C), lambda s, n, h: (n, h, 0, 0))
    mask_spec = pl.BlockSpec((1, ht, W, 1), lambda s, n, h: (n, h, 0, 0))
    chan_spec = pl.BlockSpec((C,), lambda s, n, h: (0,))
    return grid, pred_spec, gt_spec, mask_spec, chan_spec


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def focal_l2_pallas(pred, gt, mask, chan_scale, interpret=False):
    """Per-stack focal L2 sums: pred (S,N,H,W,C) → (S,).

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU tests).
    """
    return _focal_fwd_impl(pred, gt, mask, chan_scale, interpret)


def _focal_fwd_impl(pred, gt, mask, chan_scale, interpret):
    S, N, H, W, C = pred.shape
    grid, pred_spec, gt_spec, mask_spec, chan_spec = _grids(pred)
    out_spec = pl.BlockSpec((S,), lambda s, n, h: (0,),
                            memory_space=pltpu.SMEM)
    return pl.pallas_call(
        _fwd_kernel,
        out_shape=jax.ShapeDtypeStruct((S,), jnp.float32),
        grid=grid,
        in_specs=[pred_spec, gt_spec, mask_spec, chan_spec],
        out_specs=out_spec,
        interpret=interpret,
    )(pred.astype(jnp.float32), gt.astype(jnp.float32),
      mask.astype(jnp.float32), chan_scale.astype(jnp.float32))


def _focal_fwd(pred, gt, mask, chan_scale, interpret):
    out = _focal_fwd_impl(pred, gt, mask, chan_scale, interpret)
    return out, (pred, gt, mask, chan_scale)


def _focal_bwd(interpret, res, ct):
    pred, gt, mask, chan_scale = res
    S, N, H, W, C = pred.shape
    grid, pred_spec, gt_spec, mask_spec, chan_spec = _grids(pred)
    ct_spec = pl.BlockSpec((S,), lambda s, n, h: (0,),
                           memory_space=pltpu.SMEM)
    dpred = pl.pallas_call(
        _bwd_kernel,
        out_shape=jax.ShapeDtypeStruct(pred.shape, jnp.float32),
        grid=grid,
        in_specs=[pred_spec, gt_spec, mask_spec, chan_spec, ct_spec],
        out_specs=pred_spec,
        interpret=interpret,
    )(pred.astype(jnp.float32), gt.astype(jnp.float32),
      mask.astype(jnp.float32), chan_scale.astype(jnp.float32),
      ct.astype(jnp.float32))
    # gt / mask / chan_scale are labels & weights — no gradients needed
    return dpred, None, None, None


focal_l2_pallas.defvjp(_focal_fwd, _focal_bwd)


def parity_benchmark(stacks: int = 4, batch: int = 4, hw: int = 128,
                     channels: int = 50, iters: int = 30,
                     interpret: bool = False) -> dict:
    """Fwd + grad parity and timing of the Pallas kernel vs the ACTUAL
    training loss (ops.losses.focal_l2) on the active platform.

    The single check used by both tools/pallas_check.py and
    tools/tpu_session.py (one implementation — results cannot drift).  The
    case reproduces the training regime: sparse GT, a partly-zero miss
    mask, and the reference channel modulation (keypoints ×3, person-mask
    ×0.1, loss_model.py:146-149).
    """
    import time

    import numpy as np

    from .losses import focal_l2

    S, N, H, C = stacks, batch, hw, channels
    rng = np.random.default_rng(0)
    pred = jnp.asarray(rng.uniform(-0.2, 1.2, (S, N, H, H, C)), jnp.float32)
    gt = jnp.asarray(rng.uniform(0, 1, (N, H, H, C))
                     * (rng.uniform(0, 1, (N, H, H, C)) > 0.7), jnp.float32)
    mask = jnp.asarray(rng.uniform(0, 1, (N, H, H, 1)) > 0.1, jnp.float32)
    chan = np.ones((C,), np.float32)
    if C == 50:  # canonical layout: 30 paf + 18 heat + 2 bkg
        chan[-2] = 0.1
        chan[30:48] = 3.0
    chan = jnp.asarray(chan)

    p_fn = jax.jit(lambda p: focal_l2_pallas(p, gt, mask, chan, interpret))
    # the same math through the real loss: modulation folds into the mask
    x_fn = jax.jit(lambda p: focal_l2(p, gt[None], (mask * chan)[None]))
    gp_fn = jax.jit(jax.grad(lambda p: p_fn(p).sum()))
    gx_fn = jax.jit(jax.grad(lambda p: x_fn(p).sum()))

    def timed(fn, *a):
        out = fn(*a)
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*a)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3

    err = float(jnp.abs(p_fn(pred) - x_fn(pred)).max()
                / jnp.abs(x_fn(pred)).max())
    gerr = float(jnp.abs(gp_fn(pred) - gx_fn(pred)).max()
                 / (jnp.abs(gx_fn(pred)).max() + 1e-12))
    tp, tx = timed(p_fn, pred), timed(x_fn, pred)
    tgp, tgx = timed(gp_fn, pred), timed(gx_fn, pred)
    return {
        "rel_err": err, "grad_rel_err": gerr,
        "pallas_ms": round(tp, 3), "xla_ms": round(tx, 3),
        "pallas_grad_ms": round(tgp, 3), "xla_grad_ms": round(tgx, 3),
        # fp32 sums over ~100k terms differ by reduction order between the
        # per-tile accumulation and XLA's tree reduction; 1e-4 relative is
        # numerical noise, not a semantic mismatch
        "parity_ok": err < 1e-4 and gerr < 1e-4,
        "pallas_wins": tp < tx and tgp < tgx,
    }
