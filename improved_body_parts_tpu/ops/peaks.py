"""On-device peak extraction + limb pair scoring (the compact decode path).

The full-path Predictor ships (H, W, 50) fp32 maps to the host — ~100 MB
per 512-class image after the ×stride upsample.  Over a remote-attached
chip that transfer dominates end-to-end time (E2E_BENCH.json isolated it:
forward ~7 ms, decode ~60 ms, transfer ~2 s).  The compact path keeps the
maps on the device and runs, inside the same jitted ensemble program:

- 3×3 max-pool NMS + per-channel top-K selection + weighted-centroid
  sub-pixel refinement (reference: utils/util.py:177-211, evaluate.py:186);
- the limb mid-point sampling and per-pair statistics of
  ``find_connections`` (reference: evaluate.py:206-251) for ALL candidate
  pairs of every limb at once — a dense (L, K, K, S) gather, which is a
  batched lookup the TPU handles in-line with the forward pass.

Only O(C·K) peak records and (L, K, K) pair statistics cross the device
boundary (~1 MB), after which the host performs the tiny sequential parts:
greedy per-limb selection and person assembly (``infer.decode``).
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

_NEG = -1e9  # large finite "masked" value (matches Predictor's valid mask)


class TopKPeaks(NamedTuple):
    """Per-channel top-K NMS peaks, fixed shapes for jit.

    All arrays are (C, K) except ``count`` (C,).  Slots beyond a channel's
    real peak count carry ``valid=False`` and must be ignored; ``count`` is
    the TRUE number of NMS peaks in the channel, so ``count > K`` signals
    overflow (the caller should fall back to the full-map path).
    """
    xs: jnp.ndarray        # int32 raw column of each peak
    ys: jnp.ndarray        # int32 raw row
    x_ref: jnp.ndarray     # float32 sub-pixel-refined column
    y_ref: jnp.ndarray     # float32 sub-pixel-refined row
    score: jnp.ndarray     # float32 refined (window-mean) or raw score
    valid: jnp.ndarray     # bool
    count: jnp.ndarray     # int32 (C,)


class PairStats(NamedTuple):
    """Dense limb-pair statistics, (L, K, K) over candidate A×B peaks.

    ``mean_score``/``above``/``num_samples`` match find_connections'
    per-pair quantities (reference: evaluate.py:232-251); ``norm`` is the
    A→B distance the length prior uses.  Entries for invalid peak slots are
    garbage — the host indexes only valid rows/columns.
    """
    mean_score: jnp.ndarray  # float32
    above: jnp.ndarray       # int32 — samples with response > thre2
    num_samples: jnp.ndarray  # int32 — m = min(round(norm+1), S)
    norm: jnp.ndarray        # float32


class LimbCandidates(NamedTuple):
    """Top-M ACCEPTED limb candidates per limb, rank-ordered on device.

    The acceptance rule (≥connect_ration of samples above thre2, positive
    length-penalized prior — reference: evaluate.py:241-251) and the greedy
    ranking key 0.5·prior + 0.25·(endpoint scores) are evaluated on the
    device, so only the surviving pairs ship: (L, M) instead of the dense
    (L, K, K) statistics — the payload drops ~20× and the host keeps just
    the used-peak filtering and person assembly.

    ``count`` is the TRUE number of accepted pairs per limb; ``count > M``
    signals overflow (fall back to the full-map path).
    """
    slot_a: jnp.ndarray   # int32 (L, M) — index into part A's top-K slots
    slot_b: jnp.ndarray   # int32 (L, M)
    prior: jnp.ndarray    # float32 (L, M) — connection score
    norm: jnp.ndarray     # float32 (L, M) — limb length
    valid: jnp.ndarray    # bool (L, M)
    count: jnp.ndarray    # int32 (L,)


@partial(jax.jit, static_argnames=("thre", "k", "radius"))
def topk_peaks(heat: jnp.ndarray, valid_h, valid_w, *, thre: float,
               k: int, radius: int) -> TopKPeaks:
    """NMS + top-K + sub-pixel refinement on (H, W, C) keypoint maps.

    Semantics match the host pair ``ops.nms.peak_mask_np`` +
    ``ops.nms.refine_peaks`` run on the maps sliced to the valid
    (un-padded) (valid_h, valid_w) region: responses outside the region are
    masked out before NMS, and the refinement's border check uses the valid
    extent, so padded-region activations can neither create nor suppress
    peaks.
    """
    h, w, c = heat.shape
    region = ((jnp.arange(h)[:, None, None] < valid_h)
              & (jnp.arange(w)[None, :, None] < valid_w))
    masked = jnp.where(region, heat, _NEG)

    padded = jnp.pad(masked, ((1, 1), (1, 1), (0, 0)), mode="reflect")
    hmax = jax.lax.reduce_window(
        padded, -jnp.inf, jax.lax.max,
        window_dimensions=(3, 3, 1), window_strides=(1, 1, 1),
        padding="VALID")
    keep = (hmax == masked) & (masked >= thre)
    count = keep.sum(axis=(0, 1), dtype=jnp.int32)

    scores = jnp.where(keep, masked, _NEG)
    flat = scores.reshape(h * w, c).T                       # (C, H*W)
    vals, idx = jax.lax.top_k(flat, k)                      # (C, K)
    ys = (idx // w).astype(jnp.int32)
    xs = (idx % w).astype(jnp.int32)
    valid = vals >= thre

    # vectorized weighted-centroid refinement (reference: util.py:186-211);
    # windows that cross the valid border keep raw coords and raw score
    r = radius
    offs = jnp.arange(-r, r + 1)
    wy = jnp.clip(ys[:, :, None] + offs[None, None, :], 0, h - 1)
    wx = jnp.clip(xs[:, :, None] + offs[None, None, :], 0, w - 1)
    flat_idx = (wy[:, :, :, None] * w + wx[:, :, None, :]).reshape(c, -1)
    heat_t = heat.transpose(2, 0, 1).reshape(c, h * w)
    boxes = jnp.take_along_axis(heat_t, flat_idx, axis=1).reshape(
        c, k, 2 * r + 1, 2 * r + 1)

    total = boxes.sum(axis=(-1, -2))
    total = jnp.where(total == 0, 1.0, total)
    offs_f = offs.astype(boxes.dtype)
    gx = (boxes * offs_f[None, None, None, :]).sum(axis=(-1, -2)) / total
    gy = (boxes * offs_f[None, None, :, None]).sum(axis=(-1, -2)) / total
    inside = ((xs - r >= 0) & (xs + r + 1 <= valid_w)
              & (ys - r >= 0) & (ys + r + 1 <= valid_h))
    x_ref = jnp.where(inside, xs + gx, xs.astype(gx.dtype))
    y_ref = jnp.where(inside, ys + gy, ys.astype(gy.dtype))
    score = jnp.where(inside, boxes.mean(axis=(-1, -2)), vals)
    return TopKPeaks(xs, ys, x_ref, y_ref, score, valid, count)


@partial(jax.jit, static_argnames=("limbs_from", "limbs_to", "num_samples",
                                   "thre2"))
def limb_pair_stats(paf: jnp.ndarray, x_ref: jnp.ndarray, y_ref: jnp.ndarray,
                    *, limbs_from: Tuple[int, ...], limbs_to: Tuple[int, ...],
                    num_samples: int, thre2: float) -> PairStats:
    """Sample every limb channel along every candidate A→B segment.

    Mirrors ``infer.decode._sample_limb_scores`` + the per-pair reductions
    of ``find_connections`` (reference: evaluate.py:232-251): pair (i, j)
    is sampled at m = min(round(norm+1), S) points evenly spaced over the
    full segment, nearest-pixel (banker's rounding, like np.round).

    :param paf: (H, W, L) full-resolution limb maps (one channel per limb)
    :param x_ref, y_ref: (C, K) refined peak coordinates from *topk_peaks*
    """
    h, w, n_limbs = paf.shape
    la = jnp.asarray(limbs_from)
    lb = jnp.asarray(limbs_to)
    ax, ay = x_ref[la], y_ref[la]                      # (L, K)
    bx, by = x_ref[lb], y_ref[lb]
    vx = bx[:, None, :] - ax[:, :, None]               # (L, K, K)
    vy = by[:, None, :] - ay[:, :, None]
    norm = jnp.sqrt(vx * vx + vy * vy)
    m = jnp.minimum(jnp.round(norm + 1), num_samples).astype(jnp.int32)

    s = jnp.arange(num_samples, dtype=norm.dtype)
    denom = jnp.maximum(m - 1, 1).astype(norm.dtype)
    t = jnp.minimum(s[None, None, None, :] / denom[..., None], 1.0)
    px = ax[:, :, None, None] + t * vx[..., None]
    py = ay[:, :, None, None] + t * vy[..., None]
    xi = jnp.clip(jnp.round(px).astype(jnp.int32), 0, w - 1)
    yi = jnp.clip(jnp.round(py).astype(jnp.int32), 0, h - 1)

    paf_t = paf.transpose(2, 0, 1).reshape(n_limbs, h * w)
    flat = (yi * w + xi).reshape(n_limbs, -1)
    vals = jnp.take_along_axis(paf_t, flat, axis=1).reshape(px.shape)

    in_seg = s[None, None, None, :] < m[..., None]
    mean_score = (jnp.where(in_seg, vals, 0.0).sum(-1)
                  / jnp.maximum(m, 1).astype(vals.dtype))
    above = ((vals > thre2) & in_seg).sum(-1, dtype=jnp.int32)
    return PairStats(mean_score, above, m, norm)


@partial(jax.jit, static_argnames=("limbs_from", "limbs_to", "num_samples",
                                   "thre2", "connect_ration", "m_cap"))
def limb_topk_candidates(paf: jnp.ndarray, peaks: TopKPeaks, image_size,
                         *, limbs_from: Tuple[int, ...],
                         limbs_to: Tuple[int, ...], num_samples: int,
                         thre2: float, connect_ration: float,
                         m_cap: int) -> LimbCandidates:
    """Dense pair sampling + on-device acceptance + top-M rank selection.

    Applies find_connections' acceptance rule and candidate ranking
    (reference: evaluate.py:241-271) to *limb_pair_stats*' dense (L, K, K)
    grid, keeping the best ``m_cap`` accepted pairs per limb in descending
    rank order.  ``image_size`` is the valid decoded-map height (the
    length-prior scale), a runtime scalar.

    Deviation (measure-zero): exact rank ties order by top-K slot index
    here vs the host path's row-major candidate enumeration.
    """
    st = limb_pair_stats(paf, peaks.x_ref, peaks.y_ref,
                         limbs_from=limbs_from, limbs_to=limbs_to,
                         num_samples=num_samples, thre2=thre2)
    return limb_topk_from_stats(st, peaks, image_size,
                                limbs_from=limbs_from, limbs_to=limbs_to,
                                connect_ration=connect_ration, m_cap=m_cap)


@partial(jax.jit, static_argnames=("limbs_from", "limbs_to",
                                   "connect_ration", "m_cap"))
def limb_topk_from_stats(st: PairStats, peaks: TopKPeaks, image_size,
                         *, limbs_from: Tuple[int, ...],
                         limbs_to: Tuple[int, ...], connect_ration: float,
                         m_cap: int) -> LimbCandidates:
    """Acceptance + top-M rank selection over precomputed pair stats —
    the back half of :func:`limb_topk_candidates`, split out so the
    Pallas variant of the dense sampling stage (``ops.pallas_peaks``)
    can feed the identical selection logic."""
    la = jnp.asarray(limbs_from)
    lb = jnp.asarray(limbs_to)
    size_f = jnp.asarray(image_size, st.norm.dtype)
    prior = st.mean_score + jnp.minimum(
        0.5 * size_f / jnp.maximum(st.norm, 1e-12) - 1.0, 0.0)
    ok = ((st.above >= connect_ration * st.num_samples)
          & (prior > 0) & (st.norm > 0)
          & peaks.valid[la][:, :, None] & peaks.valid[lb][:, None, :])
    rank = (0.5 * prior + 0.25 * peaks.score[la][:, :, None]
            + 0.25 * peaks.score[lb][:, None, :])

    n_limbs, k, _ = rank.shape
    key = jnp.where(ok, rank, -jnp.inf).reshape(n_limbs, k * k)
    m_eff = min(m_cap, k * k)
    vals, idx = jax.lax.top_k(key, m_eff)                  # (L, M')
    slot_a = (idx // k).astype(jnp.int32)
    slot_b = (idx % k).astype(jnp.int32)
    valid = jnp.isfinite(vals)
    sel_prior = jnp.take_along_axis(prior.reshape(n_limbs, -1), idx, axis=1)
    sel_norm = jnp.take_along_axis(st.norm.reshape(n_limbs, -1), idx, axis=1)
    if m_eff < m_cap:  # keep the (L, m_cap) contract for tiny K
        pad = [(0, 0), (0, m_cap - m_eff)]
        slot_a, slot_b = jnp.pad(slot_a, pad), jnp.pad(slot_b, pad)
        sel_prior, sel_norm = jnp.pad(sel_prior, pad), jnp.pad(sel_norm, pad)
        valid = jnp.pad(valid, pad)
    count = ok.sum(axis=(1, 2), dtype=jnp.int32)
    return LimbCandidates(slot_a, slot_b, sel_prior, sel_norm, valid, count)
