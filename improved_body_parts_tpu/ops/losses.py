"""Multi-scale masked focal L2 loss (jitted).

Unifies the reference's two loss modules (reference: models/loss_model.py —
the distributed path, canonical; models/loss_model_parallel.py — the
DataParallel twin) behind one function family.  Canonical semantics are the
distributed path's (SURVEY.md §7 hard-part b): focal factor
``st = where(gt >= 0.01, s - alpha, 1 - s - beta)``, ``factor = |1 - st|``
(γ=1 linearization, loss_model.py:151-152), mask modulation on the person-mask
channel by ``multi_task_weight`` and on keypoint channels by
``keypoint_task_weight`` (loss_model.py:146-149), per-scale GT downsampling by
average pooling and mask downsampling by bilinear interpolation binarized at
0.5 (loss_model.py:52-56), scale losses combined by ``scale_weight`` and
divided by the global batch (loss_model.py:37-40).

Everything is channel-LAST (N, H, W, C): predictions come from the NHWC model,
GT from the heatmapper.
"""
from __future__ import annotations

from functools import partial
from typing import List, Sequence

import jax
import jax.numpy as jnp

from ..config import Config


def avg_pool_to(x: jnp.ndarray, size) -> jnp.ndarray:
    """Adaptive average pool NHWC → (N, size, size, C) for power-of-two ratios
    (replaces F.adaptive_avg_pool2d, loss_model.py:52)."""
    n, h, w, c = x.shape
    th, tw = size
    assert h % th == 0 and w % tw == 0, (h, w, size)
    kh, kw = h // th, w // tw
    if kh == 1 and kw == 1:
        return x
    x = x.reshape(n, th, kh, tw, kw, c)
    return x.mean(axis=(2, 4))


def downsample_mask(mask: jnp.ndarray, size) -> jnp.ndarray:
    """Bilinear-resize the miss mask then zero everything < 0.5
    (loss_model.py:55-56)."""
    n, h, w, c = mask.shape
    th, tw = size
    if (h, w) != (th, tw):
        mask = jax.image.resize(mask, (n, th, tw, c), method="bilinear")
    return jnp.where(mask < 0.5, 0.0, mask)


def _chan_scale(num_layers: int, heat_start: int, bkg_start: int,
                multi_task_weight: float, keypoint_task_weight: float,
                dtype=jnp.float32) -> jnp.ndarray:
    """Per-channel task weights (loss_model.py:146-149): person-mask channel
    × multi_task_weight, keypoint channels × keypoint_task_weight."""
    chan = jnp.ones((num_layers,), dtype=dtype)
    chan = chan.at[heat_start:bkg_start].mul(keypoint_task_weight)
    chan = chan.at[bkg_start].mul(multi_task_weight)
    return chan


def focal_l2(pred: jnp.ndarray, gt: jnp.ndarray, mask: jnp.ndarray,
             gamma: float = 1.0, alpha: float = 0.0, beta: float = 0.0,
             chan: jnp.ndarray | None = None) -> jnp.ndarray:
    """Per-stack focal L2 (loss_model.py:133-161). pred: (nstack,N,H,W,C);
    gt/mask broadcast along the stack axis. Returns per-stack sums (nstack,).

    ``chan`` (optional (C,) task-weight vector) keeps the spatial mask and
    the per-channel modulation as two rank-deficient broadcasts instead of
    a pre-multiplied (N,H,W,C) mask — the same channel-vector form the
    Pallas kernel uses, so neither path ever builds a full modulated-mask
    tensor in the user graph."""
    st = jnp.where(gt >= 0.01, pred - alpha, 1.0 - pred - beta)
    if gamma == 1.0:
        factor = jnp.abs(1.0 - st)
    else:
        factor = jnp.abs(1.0 - st) ** gamma
    out = (pred - gt) ** 2 * factor * mask
    if chan is not None:
        out = out * chan
    return out.sum(axis=(1, 2, 3, 4))


def l2(pred: jnp.ndarray, gt: jnp.ndarray, mask: jnp.ndarray,
       chan: jnp.ndarray | None = None) -> jnp.ndarray:
    """Plain masked L2 (loss_model.py:102-131). Same shapes as focal_l2."""
    out = (pred - gt) ** 2 * mask
    if chan is not None:
        out = out * chan
    return out.sum(axis=(1, 2, 3, 4))


def l1(pred: jnp.ndarray, gt: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Masked L1 for offset regression (loss_model.py:83-100); per-stack sums
    over (nstack, N, H, W, C)."""
    return (jnp.abs(pred - gt) * mask).sum(axis=(1, 2, 3, 4))


def multi_task_loss(preds: Sequence[Sequence[jnp.ndarray]], gt: jnp.ndarray,
                    mask_miss: jnp.ndarray, config: Config,
                    use_focal: bool = True,
                    use_pallas: bool = False) -> jnp.ndarray:
    """Total training loss over nstack stacks × 5 scales.

    :param preds: [nstack][5] NHWC tensors from the model (fp32)
    :param gt: (N, H, W, num_layers) GT heatmaps at stride 4
    :param mask_miss: (N, H, W, 1) miss mask in [0, 1]
    :returns: scalar — summed per-stack losses weighted by nstack_weight /
        scale_weight, divided by the global batch size
        (loss_model.py:34-40, 133-161).
    """
    sk, tr = config.skeleton, config.train
    nstack = len(preds)
    nscale = len(preds[0])
    nstack_w = jnp.asarray(tr.nstack_weight, dtype=jnp.float32)
    scale_w = list(tr.scale_weight)
    assert len(scale_w) == nscale and nstack_w.shape[0] == nstack

    use_pallas = use_pallas and use_focal
    # channel modulation stays a (C,) vector on BOTH paths — the XLA path
    # applies it as a second broadcast inside the loss (fused into the
    # reduction; no (N,H,W,C) modulated-mask tensor is ever built), which
    # is the same trick the Pallas kernel uses
    chan = _chan_scale(sk.num_layers, sk.heat_start, sk.bkg_start,
                       tr.multi_task_weight, tr.keypoint_task_weight)
    if use_pallas:
        # hand-scheduled fused kernel (ops/pallas_focal.py)
        from .pallas_focal import focal_l2_pallas

        # the kernel is written for the TPU Mosaic pipeline; interpret
        # everywhere else so the flag degrades gracefully off-TPU
        interpret = jax.default_backend() != "tpu"

    loss_fn = focal_l2 if use_focal else l2
    total = 0.0
    for s in range(nscale):
        pred_s = jnp.stack([preds[i][s] for i in range(nstack)], axis=0)
        size = pred_s.shape[2:4]
        gt_s = avg_pool_to(gt, size)
        mask_s = downsample_mask(mask_miss, size)
        if use_pallas:
            per_stack = focal_l2_pallas(pred_s, gt_s, mask_s, chan, interpret)
        else:
            per_stack = loss_fn(pred_s, gt_s[None], mask_s[None], chan=chan)
        total = total + (per_stack * nstack_w).sum() / nstack_w.sum() * scale_w[s]

    total = total / sum(scale_w)
    if tr.normalize_by_global_batch:
        total = total / gt.shape[0]
    return total
