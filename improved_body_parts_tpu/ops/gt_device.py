"""On-device ground-truth heatmap synthesis (jitted).

TPU-native alternative to the host-side ``data.heatmapper.Heatmapper``: the
whole label tensor is generated on device from raw joint coordinates, so when
host CPUs are the input-pipeline bottleneck feeding a pod slice (SURVEY.md §7
hard part f), only (people, parts, 3) joint arrays and the two masks cross the
host→device boundary instead of (H/4, W/4, 50) float maps — a ~500× transfer
reduction per sample.

Semantics match the host heatmapper exactly (parity-tested):
- keypoint Gaussians evaluated at stride-center coordinates, combined by max,
  restricted to the reference's square window (py_data_heatmapper.py:111-131);
- limb maps: Gaussian of distance-to-segment-line inside the segment bbox
  padded by paf_thre, floored at 0.01, count-averaged across instances
  (py_data_heatmapper.py:163-240);
- background channels: 3x3-eroded person mask and the max over keypoint
  channels (py_data_heatmapper.py:73-80).

People are padded to a static ``max_people`` (mark padding joints with
visibility 2) so the program compiles once.
"""
from __future__ import annotations




import jax
import jax.numpy as jnp

from ..config import SkeletonConfig


def make_gt_synthesizer(config: SkeletonConfig):
    """Build the jitted (joints, mask_all) -> (H, W, num_layers) function.

    :param joints: (max_people, num_parts, 3) float32, visibility < 2 =
        annotated (pad with visibility 2)
    :param mask_all: (H, W) float in [0, 1] on the stride-4 grid
    """
    from ..data.heatmapper import Heatmapper

    # share the host heatmapper's derived constants so the two GT paths
    # cannot drift (same window half-extent and stride-center grid)
    hm = Heatmapper(config)
    tp = config.transform_params
    sigma2x2 = hm.double_sigma2
    paf_sigma2x2 = 2.0 * tp.paf_sigma * tp.paf_sigma
    g = hm.gaussian_size // 2
    limb_thre = tp.limb_gaussian_thre
    paf_thre = config.paf_thre
    stride = config.stride
    h, w = config.grid_shape
    gx, gy = jnp.asarray(hm.grid_x), jnp.asarray(hm.grid_y)
    limb_from = jnp.asarray([f for f, _ in config.limbs_conn])
    limb_to = jnp.asarray([t for _, t in config.limbs_conn])

    def keypoint_channel(xs, ys, vis):
        """(P,) joint coords of one part -> (H, W) channel (max-combined)."""
        cx = jnp.round(xs / stride)
        cy = jnp.round(ys / stride)
        ix = jnp.arange(w, dtype=jnp.float32)
        iy = jnp.arange(h, dtype=jnp.float32)
        in_x = jnp.abs(ix[None, :] - cx[:, None]) <= g      # (P, W)
        in_y = jnp.abs(iy[None, :] - cy[:, None]) <= g      # (P, H)
        ex = jnp.exp(-((gx[None, :] - xs[:, None]) ** 2) / sigma2x2)
        ey = jnp.exp(-((gy[None, :] - ys[:, None]) ** 2) / sigma2x2)
        resp = (ey * in_y)[:, :, None] * (ex * in_x)[:, None, :]  # (P, H, W)
        resp = jnp.where(vis[:, None, None] < 2, resp, 0.0)
        return resp.max(axis=0)

    def limb_channel(x1, y1, x2, y2, vis):
        """(P,) endpoint coords of one limb -> (H, W) count-averaged map."""
        dx, dy = x2 - x1, y2 - y1
        norm = jnp.sqrt(dx * dx + dy * dy)
        ok = (vis < 2) & (norm > 0)
        # reference bbox window rounded at stride resolution
        min_sx = jnp.round((jnp.minimum(x1, x2) - paf_thre) / stride)
        max_sx = jnp.round((jnp.maximum(x1, x2) + paf_thre) / stride)
        min_sy = jnp.round((jnp.minimum(y1, y2) - paf_thre) / stride)
        max_sy = jnp.round((jnp.maximum(y1, y2) + paf_thre) / stride)
        ix = jnp.arange(w, dtype=jnp.float32)
        iy = jnp.arange(h, dtype=jnp.float32)
        in_x = (ix[None, :] >= min_sx[:, None]) & (ix[None, :] <= max_sx[:, None])
        in_y = (iy[None, :] >= min_sy[:, None]) & (iy[None, :] <= max_sy[:, None])
        window = in_y[:, :, None] & in_x[:, None, :]          # (P, H, W)
        window = window & ok[:, None, None]

        dist = jnp.abs(
            dx[:, None, None] * (y1[:, None, None] - gy[None, :, None])
            - (x1[:, None, None] - gx[None, None, :]) * dy[:, None, None]
        ) / (norm[:, None, None] + 1e-6)
        resp = jnp.exp(-(dist ** 2) / paf_sigma2x2)
        resp = jnp.where(resp <= limb_thre, 0.01, resp)       # reference floor
        acc = (resp * window).sum(axis=0)
        count = window.sum(axis=0)
        return jnp.where(count > 0, acc / jnp.maximum(count, 1), 0.0)

    @jax.jit
    def synthesize(joints, mask_all):
        joints = joints.astype(jnp.float32)
        xs, ys, vis = joints[..., 0], joints[..., 1], joints[..., 2]

        heat = jax.vmap(keypoint_channel, in_axes=(1, 1, 1), out_axes=2)(
            xs, ys, vis)                                       # (H, W, parts)

        x1 = xs[:, limb_from].T  # (L, P) — vmap over limbs
        y1 = ys[:, limb_from].T
        x2 = xs[:, limb_to].T
        y2 = ys[:, limb_to].T
        lvis = jnp.maximum(vis[:, limb_from], vis[:, limb_to]).T
        paf = jax.vmap(limb_channel, in_axes=(0, 0, 0, 0, 0), out_axes=2)(
            x1, y1, x2, y2, lvis)                              # (H, W, limbs)

        # eroded person mask (3x3 min = erosion of a [0,1] mask)
        eroded = -jax.lax.reduce_window(
            -jnp.pad(mask_all.astype(jnp.float32), 1, mode="edge"),
            -jnp.inf, jax.lax.max, (3, 3), (1, 1), "VALID")
        reverse = heat.max(axis=2)

        full = jnp.concatenate(
            [paf, heat, eroded[..., None], reverse[..., None]], axis=-1)
        return jnp.clip(full, 0.0, 1.0)

    return synthesize
