"""On-device greedy skeleton assembly — the third and last decode stage.

``ops.peaks`` already runs peak top-K and limb candidate scoring on the
device; person assembly (the reference's evaluate.py:279-498 greedy
merge/spawn walk) still ran as host NumPy/C++ on serve's decode thread
pool — ROADMAP open item 1's serving throughput ceiling.  This module is
that walk expressed as a fixed-shape, bounded-iteration device kernel:

- one ``lax.fori_loop`` over the (static) limb list;
- per limb, a **declared bounded** ``lax.while_loop`` over the
  rank-ordered accepted candidates (``ops.peaks.limb_topk_candidates``
  ships them rank-sorted with validity a prefix, so the walk stops at
  the first invalid slot and can never exceed M iterations) applying the
  one-to-one used-peak filter (reference: evaluate.py:260-271);
- per selected connection, the exact found∈{0,1,2} spawn / assign /
  replace / rescore / merge / compete rules of ``infer.decode
  .find_people`` over a fixed-capacity person table.

Peaks are identified by the flat slot id ``channel * K + slot`` (exact
in fp32 up to 2^24); the host side rebuilds a candidate array in the
same indexing, so ``infer.decode.subsets_to_keypoints`` consumes the
device subset unchanged.

Overflow is a FLAG, never an exception: a program output cannot
data-depend on host control flow, so the three capacity conditions the
host path raises ``CompactOverflow`` for (peak top-K, candidate cap) or
cannot hit (the host person table is unbounded; ``p_max`` here) are
returned as booleans and the caller falls back to the host decoder.

Documented deviations from the host walk (tests/test_assembly.py):

- arithmetic is fp32 (the host accumulates in float64) — raw scores and
  lengths are identical, only running sums round differently, which can
  flip a comparison exactly at a tie;
- the found==2 "compete" case where NEITHER endpoint of the new limb is
  in the second matched row is total here (it reads a -1 confidence);
  the host reference crashes on that input (an empty ``np.where``), so
  no parity case exists.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .peaks import LimbCandidates, TopKPeaks


class AssemblyResult(NamedTuple):
    """Fixed-capacity assembled-person table, host-layout compatible.

    ``subset`` is (P_max, num_parts+2, 2) float32 in ``find_people``'s
    row layout: per part [flat peak id ``c*K+slot`` or -1, confidence];
    row -2 = [total score, -1]; row -1 = [part count, longest limb].
    Only rows with ``mask`` are people (post-prune); the rest are
    scratch.  The three overflow flags mirror the host path's
    ``CompactOverflow`` conditions plus the table-capacity one.
    """
    subset: jnp.ndarray          # (P, num_parts + 2, 2) float32
    mask: jnp.ndarray            # (P,) bool — pruned-in people
    n_people: jnp.ndarray        # int32 — mask.sum()
    peak_overflow: jnp.ndarray   # bool — a channel's NMS count > top-K
    cand_overflow: jnp.ndarray   # bool — a limb's accepted pairs > M
    person_overflow: jnp.ndarray  # bool — person table hit p_max


def _first_two(match: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray,
                                            jnp.ndarray]:
    """(j1, j2, found) — indices of the first two True rows in table
    order (creation order; rows are allocated append-only so slot order
    is the host's post-np.delete row order) and how many were found,
    capped at 2 like the host's ``found_idx`` scan."""
    n = match.shape[0]
    rows = jnp.arange(n)
    j1 = jnp.argmax(match)
    has1 = match.any()
    later = match & (rows > j1)
    j2 = jnp.argmax(later)
    has2 = later.any()
    found = has1.astype(jnp.int32) + has2.astype(jnp.int32)
    return j1, j2, found


@partial(jax.jit, static_argnames=(
    "limbs_from", "limbs_to", "num_parts", "p_max", "len_rate",
    "connection_tole", "remove_recon", "min_parts", "min_mean_score"))
def greedy_assemble(peaks: TopKPeaks, cands: LimbCandidates, *,
                    limbs_from: Tuple[int, ...], limbs_to: Tuple[int, ...],
                    num_parts: int, p_max: int, len_rate: float,
                    connection_tole: float, remove_recon: int,
                    min_parts: int, min_mean_score: float) -> AssemblyResult:
    """Greedy person assembly on device (see module docstring).

    Statics mirror ``InferenceParams``' assembly knobs so one compiled
    kernel serves a fixed protocol; ``p_max`` is the person-table
    capacity knob (``Predictor(assembly_pmax=...)``).
    """
    f32 = jnp.float32
    c, k = peaks.valid.shape
    n_limbs, m_cap = cands.valid.shape
    p = p_max
    rows = jnp.arange(p)
    parts = jnp.arange(num_parts)

    la = jnp.asarray(limbs_from, jnp.int32)
    lb = jnp.asarray(limbs_to, jnp.int32)
    n_peaks = jnp.minimum(peaks.count, k)              # true counts, capped
    limit = jnp.minimum(n_peaks[la], n_peaks[lb])      # (L,) per-limb cap
    pscore = peaks.score.astype(f32).reshape(-1)       # flat-id score lookup

    state0 = dict(
        ids=jnp.full((p, num_parts), -1, jnp.int32),
        conf=jnp.full((p, num_parts), -1.0, f32),
        tot=jnp.zeros((p,), f32),
        npart=jnp.zeros((p,), f32),
        maxlen=jnp.full((p,), -1.0, f32),
        active=jnp.zeros((p,), bool),
        count=jnp.int32(0),
        overflow=jnp.zeros((), bool),
    )

    def process(st, ia, ib, sa, sb, score, limb_len):
        """One selected connection through the found∈{0,1,2} rules."""
        aid = ia * k + sa
        bid = ib * k + sb
        psa = pscore[aid]
        psb = pscore[bid]
        match = st["active"] & ((jnp.take(st["ids"], ia, axis=1) == aid)
                                | (jnp.take(st["ids"], ib, axis=1) == bid))
        j1, j2, found = _first_two(match)

        def spawn(st):
            # no owner: new person at the next slot (evaluate.py:473-488);
            # a full table sets the overflow flag instead of growing
            cnt = st["count"]
            can = cnt < p
            rmask = (rows == cnt) & can
            col_a = parts == ia
            col_b = parts == ib
            cell = rmask[:, None] & (col_a | col_b)[None, :]
            ids = jnp.where(cell, jnp.where(col_a[None, :], aid, bid),
                            st["ids"])
            conf = jnp.where(cell, score, st["conf"])
            return dict(
                ids=ids, conf=conf,
                tot=jnp.where(rmask, psa + psb + score, st["tot"]),
                npart=jnp.where(rmask, 2.0, st["npart"]),
                maxlen=jnp.where(rmask, limb_len, st["maxlen"]),
                active=st["active"] | rmask,
                count=cnt + can.astype(jnp.int32),
                overflow=st["overflow"] | ~can)

        def one(st):
            # one owner: assign / replace / rescore part B on row j1
            # (evaluate.py:320-380); the elif chain reduces to three
            # disjoint predicates over (slot state, confidence, length)
            j = j1
            old_b = st["ids"][j, ib]
            conf_b = st["conf"][j, ib]
            grow_ok = len_rate * st["maxlen"][j] > limb_len
            same = old_b == bid
            do_assign = (old_b == -1) & grow_ok
            do_update = (~do_assign) & jnp.where(
                same, conf_b <= score, (conf_b < score) & grow_ok)
            write = do_assign | do_update
            old_p = pscore[jnp.clip(old_b, 0, c * k - 1)]
            delta = jnp.where(
                do_assign, psb + score,
                jnp.where(do_update, psb + score - old_p - conf_b, 0.0))
            cell = (rows == j)[:, None] & (parts == ib)[None, :] & write
            rmask = (rows == j) & write
            return dict(
                ids=jnp.where(cell, bid, st["ids"]),
                conf=jnp.where(cell, score, st["conf"]),
                tot=st["tot"] + jnp.where(rows == j, delta, 0.0),
                npart=st["npart"] + jnp.where(
                    (rows == j) & do_assign, 1.0, 0.0),
                maxlen=jnp.where(rmask,
                                 jnp.maximum(st["maxlen"], limb_len),
                                 st["maxlen"]),
                active=st["active"], count=st["count"],
                overflow=st["overflow"])

        def two(st):
            memb1 = st["ids"][j1] >= 0
            memb2 = st["ids"][j2] >= 0
            overlap = (memb1 & memb2).any()

            def merge(st):
                # disjoint people sharing this limb: merge j2 into j1,
                # gated by confidence + length priors (evaluate.py:403-424)
                conf1 = st["conf"][j1]
                conf2 = st["conf"][j2]
                min_tol = jnp.minimum(
                    jnp.min(jnp.where(memb1, conf1, jnp.inf)),
                    jnp.min(jnp.where(memb2, conf2, jnp.inf)))
                refuse = ((score < connection_tole * min_tol)
                          | (len_rate * st["maxlen"][j1] <= limb_len))

                def do(st):
                    r1 = rows == j1
                    r2 = rows == j2
                    ids1 = st["ids"][j1] + st["ids"][j2] + 1
                    conf1n = conf1 + conf2 + 1.0
                    ids = jnp.where(r1[:, None], ids1[None, :], st["ids"])
                    conf = jnp.where(r1[:, None], conf1n[None, :],
                                     st["conf"])
                    return dict(
                        ids=jnp.where(r2[:, None], -1, ids),
                        conf=jnp.where(r2[:, None], -1.0, conf),
                        tot=jnp.where(
                            r1, st["tot"][j1] + st["tot"][j2] + score,
                            jnp.where(r2, 0.0, st["tot"])),
                        npart=jnp.where(
                            r1, st["npart"][j1] + st["npart"][j2],
                            jnp.where(r2, 0.0, st["npart"])),
                        # the host takes max(limb_len, j1's) — j2's
                        # longest limb is deliberately NOT folded in
                        maxlen=jnp.where(
                            r1, jnp.maximum(st["maxlen"], limb_len),
                            jnp.where(r2, -1.0, st["maxlen"])),
                        active=st["active"] & ~r2,
                        count=st["count"], overflow=st["overflow"])

                return jax.lax.cond(refuse, lambda s: s, do, st)

            def compete(st):
                # two people own one endpoint each (evaluate.py:426-460);
                # with remove_recon == 0 (the protocol default) the host
                # resolves this to a no-op, so the kernel compiles it out
                if remove_recon <= 0:
                    return st
                a_in_1 = st["ids"][j1, ia] == aid
                c1 = jnp.where(a_in_1, ia, ib)
                c2 = jnp.where(a_in_1, ib, ia)
                conf_11 = st["conf"][j1, c1]
                conf_22 = st["conf"][j2, c2]
                skip = (score < conf_11) & (score < conf_22)
                small_is_2 = conf_11 > conf_22
                sj = jnp.where(small_is_2, j2, j1)
                rc = jnp.where(small_is_2, c2, c1)

                def do(st):
                    old_id = st["ids"][sj, rc]
                    old_conf = st["conf"][sj, rc]
                    old_p = pscore[jnp.clip(old_id, 0, c * k - 1)]
                    cell = (rows == sj)[:, None] & (parts == rc)[None, :]
                    return dict(
                        ids=jnp.where(cell, -1, st["ids"]),
                        conf=jnp.where(cell, -1.0, st["conf"]),
                        tot=st["tot"] - jnp.where(
                            rows == sj, old_p + old_conf, 0.0),
                        npart=st["npart"] - jnp.where(
                            rows == sj, 1.0, 0.0),
                        maxlen=st["maxlen"], active=st["active"],
                        count=st["count"], overflow=st["overflow"])

                return jax.lax.cond(skip, lambda s: s, do, st)

            return jax.lax.cond(overlap, compete, merge, st)

        return jax.lax.switch(found, [spawn, one, two], st)

    def limb_body(li, st):
        ia = la[li]
        ib = lb[li]
        lim = limit[li]
        slot_a = cands.slot_a[li]
        slot_b = cands.slot_b[li]
        prior = cands.prior[li].astype(f32)
        norm = cands.norm[li].astype(f32)
        valid = cands.valid[li]

        def cond(carry):
            mi, nrows, _used_a, _used_b, _st = carry
            # candidates are rank-ordered with validity a prefix: the
            # first invalid slot ends the limb — the walk is bounded by
            # M but usually far shorter (the declared-while rationale)
            return ((mi < m_cap) & valid[jnp.minimum(mi, m_cap - 1)]
                    & (nrows < lim))

        def body(carry):
            mi, nrows, used_a, used_b, st = carry
            sa = jnp.clip(slot_a[mi], 0, k - 1)
            sb = jnp.clip(slot_b[mi], 0, k - 1)
            free = ~(used_a[sa] | used_b[sb])

            def take(args):
                nrows, used_a, used_b, st = args
                return (nrows + 1,
                        used_a.at[sa].set(True),
                        used_b.at[sb].set(True),
                        process(st, ia, ib, sa, sb, prior[mi], norm[mi]))

            nrows, used_a, used_b, st = jax.lax.cond(
                free, take, lambda a: a, (nrows, used_a, used_b, st))
            return mi + 1, nrows, used_a, used_b, st

        carry = (jnp.int32(0), jnp.int32(0),
                 jnp.zeros((k,), bool), jnp.zeros((k,), bool), st)
        return jax.lax.while_loop(cond, body, carry)[4]

    st = jax.lax.fori_loop(0, n_limbs, limb_body, state0)

    # prune sparse / low-confidence people (evaluate.py:491-496)
    npart_safe = jnp.maximum(st["npart"], 1.0)
    mask = (st["active"] & (st["npart"] >= min_parts)
            & (st["tot"] / npart_safe >= min_mean_score))

    part_rows = jnp.stack([st["ids"].astype(f32), st["conf"]], axis=-1)
    row_m2 = jnp.stack([st["tot"], jnp.full((p,), -1.0, f32)],
                       axis=-1)[:, None, :]
    row_m1 = jnp.stack([st["npart"], st["maxlen"]], axis=-1)[:, None, :]
    subset = jnp.concatenate([part_rows, row_m2, row_m1], axis=1)

    return AssemblyResult(
        subset=subset, mask=mask,
        n_people=mask.sum(dtype=jnp.int32),
        peak_overflow=(peaks.count > k).any(),
        cand_overflow=(cands.count > m_cap).any(),
        person_overflow=st["overflow"])
