"""Orbax checkpointing: sync helpers + the async donation-safe manager.

Replaces the reference's torch.save dict {weights, optimizer_weight,
train_loss, epoch} and its resume-time 'module.' key remapping
(reference: train.py:149-162, train_distributed.py:149-197, 304-324) — under
functional params there is nothing to remap.

The epoch boundary used to be the last fully serial host-side stall in
the training path: ``save_checkpoint`` materialized the entire canonical
state (129M params + SGD momentum + batch_stats + the SWA shadow ≈
1.5 GB) and blocked the train loop on the whole Orbax write.
:class:`CheckpointManager` splits the save the way Orbax's own
``AsyncCheckpointer`` does:

- **snapshot** (caller thread, the only blocked part): enqueue
  ``copy_to_host_async`` on every device leaf FIRST — all D2H transfers
  go in flight together — then drain them into host arrays.  This is
  bandwidth-bound (~100 ms for the canonical state over PCIe), not
  serialization-bound (seconds).  The drain must complete before
  returning: the next epoch's first step DONATES the state buffers, and
  a donated ``jax.Array`` raises on any later host read (verified on
  jax 0.4.37 — ``copy_to_host_async`` does not cache the host value
  past deletion), so "return as soon as transfers are enqueued" is only
  safe once the enqueued transfers have landed in host memory.
- **serialize + commit** (background writer thread): the Orbax write,
  then an atomic ``COMMIT.json`` marker with the run metadata, then
  retention GC.  A checkpoint without its marker is either in flight or
  the debris of a killed run; ``restore_latest``/``latest_checkpoint``
  skip it, and GC never deletes it.

COLLECTIVE CONTRACT under multi-process JAX (unchanged from the sync
path): orbax synchronizes all processes during save (and writes once,
from the primary host) — every process must enter the save, not just
rank 0, or the barrier never completes and the checkpoint is lost
(observed on a 2-process Gloo run).  With the manager the barrier moves
onto each process's writer thread; ``save()``'s wait-for-previous keeps
the per-process save sequences aligned, and the save/skip decision in
``loop.fit`` is epoch-number-based, i.e. process-symmetric.  Only the
lead host writes commit markers and runs GC (the marker names a
checkpoint on the shared filesystem; N processes writing it would race).
"""
from __future__ import annotations

import json
import math
import os
import shutil
import threading
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from .state import TrainState

COMMIT_MARKER = "COMMIT.json"
COMMIT_FORMAT = 1


def snapshot_to_host(tree):
    """Donation-safe host snapshot of a (possibly device-resident) pytree.

    Phase 1 enqueues ``copy_to_host_async`` on every ``jax.Array`` leaf —
    all transfers are in flight before any is waited on, so the blocked
    time is the max single transfer, not the sum.  Phase 2 drains each
    into a host ``np.ndarray`` the snapshot OWNS.  On the CPU backend
    ``np.asarray`` returns a zero-copy view of the device buffer; a view
    is NOT donation-safe, so those leaves are copied.  The external
    reference a view holds *usually* blocks donation reuse, but for a
    donated executable loaded from the persistent compilation cache
    (jax 0.4.37, multi-device host platform — exactly the test harness)
    the step writes its output in place THROUGH the still-referenced
    buffer without even marking the array deleted, silently corrupting
    every aliased leaf of an in-flight checkpoint.  One host memcpy per
    save is the price of a snapshot that is immutable by construction on
    every backend (accelerators already pay it: their ``np.asarray`` IS
    the D2H copy and comes back owning its memory, so no second copy).
    """
    def start(x):
        if isinstance(x, jax.Array):
            try:
                x.copy_to_host_async()
            except Exception:  # noqa: BLE001 — committed/deleted edge; the
                pass           # drain below surfaces any real failure
        return x

    jax.tree.map(start, tree)

    def drain(x):
        arr = np.asarray(x)
        if isinstance(x, jax.Array) and not arr.flags.owndata:
            arr = arr.copy()  # zero-copy view of a donatable device buffer
        return arr

    return jax.tree.map(drain, tree)


def _payload(state: TrainState, epoch: int, train_loss: float,
             best_loss: float) -> Dict[str, Any]:
    """The checkpoint dict (device leaves still on device)."""
    return {
        "params": state.params,
        "batch_stats": state.batch_stats,
        "opt_state": state.opt_state,
        "step": int(state.step),
        "swa_params": state.swa_params,
        "swa_count": (int(state.swa_count)
                      if state.swa_count is not None else None),
        "swa_start_step": (int(state.swa_start_step)
                           if state.swa_start_step is not None else None),
        "epoch": epoch,
        "train_loss": float(train_loss),
        "best_loss": float(best_loss),
    }


def _tree_bytes(tree) -> int:
    return int(sum(getattr(leaf, "nbytes", 0)
                   for leaf in jax.tree.leaves(tree)))


def _marker_meta(epoch: int, train_loss: float, best_loss: float,
                 payload_bytes: int, **extra) -> Dict[str, Any]:
    """The commit marker's base schema — ONE construction site for both
    the sync and the async save paths, so the schema cannot drift."""
    meta = {
        "format": COMMIT_FORMAT, "epoch": epoch,
        "train_loss": float(train_loss), "best_loss": float(best_loss),
        "metric": "train_loss", "metric_value": float(train_loss),
        "payload_bytes": int(payload_bytes),
    }
    meta.update(extra)
    return meta


def _write_marker(path: str, meta: Dict[str, Any]) -> None:
    """Atomic commit: the marker appears complete or not at all (tmp +
    ``os.replace`` — a crash mid-commit can never leave a torn marker
    that parses as committed).  STRICT JSON like every obs record: a
    non-finite loss (first-save best_loss=inf, a NaN-diverged run under
    --on-divergence warn) becomes its string name, never a bare
    ``NaN``/``Infinity`` token a strict consumer cannot parse."""
    from ..obs.events import _definan

    marker = os.path.join(path, COMMIT_MARKER)
    tmp = marker + ".tmp"
    with open(tmp, "w") as f:
        json.dump(_definan(meta), f, indent=2, allow_nan=False)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, marker)


def is_committed(path: str) -> bool:
    """True when ``path`` carries a commit marker (written strictly after
    the Orbax write finished)."""
    return os.path.isfile(os.path.join(path, COMMIT_MARKER))


def _inflight_stamp(directory: str, epoch: int) -> str:
    """Sidecar path marking ``epoch_<N>`` as being written by the commit
    protocol.  Written (lead host) BEFORE the Orbax write starts, removed
    strictly AFTER the commit marker lands.  A sidecar, not a file inside
    the entry, because ``force=True`` recreates the entry directory.

    Why it exists: in a directory with no markers at all (a pre-protocol
    legacy workdir) ``latest_checkpoint`` accepts unmarked entries so old
    runs keep resuming — but the FIRST new-protocol save into such a
    directory, killed mid-write, would then be accepted too.  The stamp
    survives the kill and keeps exactly that partial entry out of the
    legacy fallback."""
    return os.path.join(directory, f".inflight_epoch_{epoch}")


def read_commit_meta(path: str) -> Optional[Dict[str, Any]]:
    """The commit marker's metadata, or None (uncommitted / pre-marker
    legacy checkpoint / torn marker)."""
    try:
        with open(os.path.join(path, COMMIT_MARKER)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def save_checkpoint(directory: str, state: TrainState, epoch: int,
                    train_loss: float, best_loss: float) -> str:
    """Synchronous save of ``<directory>/epoch_<N>`` (snapshot + Orbax
    write + commit marker in the caller thread); returns the path.

    COLLECTIVE under multi-process JAX — see the module docstring.  The
    async path is :class:`CheckpointManager`; this stays as the simple
    API (tools/synth_ap.py's fresh-baseline checkpoints, tests, and the
    sync arm of tools/ckpt_bench.py).
    """
    from ..parallel.mesh import mesh_topology

    path = os.path.abspath(os.path.join(directory, f"epoch_{epoch}"))
    host = snapshot_to_host(_payload(state, epoch, train_loss, best_loss))
    lead = jax.process_index() == 0
    stamp = _inflight_stamp(os.path.dirname(path), epoch)
    if lead:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        open(stamp, "w").close()
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, host, force=True)
    if lead:
        _write_marker(path, _marker_meta(
            epoch, train_loss, best_loss, _tree_bytes(host),
            time_unix=round(time.time(), 3),
            topology=mesh_topology()))
        try:
            os.remove(stamp)
        except OSError:
            pass
    return path


def restore_checkpoint(path: str, state: Optional[TrainState] = None
                       ) -> Dict[str, Any]:
    """Load a checkpoint; if ``state`` is given, return (state, meta) with the
    arrays restored into it (resume semantics of train_distributed.py:149-197).

    Orbax serializes custom pytree nodes (optax namedtuple states) as plain
    containers; with a ``state`` template we re-impose the original structure
    on the restored leaves so ``optimizer.update`` keeps working.

    ``meta`` prefers the commit marker's fields when present: the marker
    is written (and possibly amended) AFTER validation ran, so its
    ``best_loss``/``metric`` reflect the val-keyed best tracking, while
    the payload's copy is the provisional value known at save kickoff.
    """
    ckptr = ocp.PyTreeCheckpointer()
    payload = ckptr.restore(os.path.abspath(path))
    if state is None:
        return payload

    def rebuild(template, restored):
        """Unflatten restored leaves into the template's pytree structure.

        Leaf correspondence holds because orbax preserves each container's
        key/field layout (namedtuples round-trip as dicts keyed by field
        name, whose serialization order jax also uses when flattening).

        Checkpoints without optimizer state (imported reference weights,
        tools/import_torch_checkpoint.py) keep the template's freshly
        initialized opt_state.
        """
        if restored is None:
            return template
        leaves = jax.tree.leaves(restored)
        treedef = jax.tree.structure(template)
        assert treedef.num_leaves == len(leaves), (
            f"checkpoint opt_state has {len(leaves)} leaves, "
            f"optimizer expects {treedef.num_leaves}")
        return jax.tree.unflatten(treedef, leaves)

    restored = state.replace(
        params=payload["params"],
        batch_stats=payload["batch_stats"],
        opt_state=rebuild(state.opt_state, payload["opt_state"]),
        step=np.asarray(payload["step"], np.int32),
        swa_params=payload.get("swa_params"),
        swa_count=(np.asarray(payload["swa_count"], np.int32)
                   if payload.get("swa_count") is not None else None),
        swa_start_step=(np.asarray(payload["swa_start_step"], np.int32)
                        if payload.get("swa_start_step") is not None
                        else None),
    )
    meta = {k: payload[k] for k in ("epoch", "train_loss", "best_loss")}
    marker = read_commit_meta(path)
    if marker:
        for k in ("best_loss", "metric", "metric_value", "topology"):
            if k in marker:
                meta[k] = marker[k]
    return restored, meta


def _epoch_dirs(directory: str):
    """(epoch, abs path) for every ``epoch_<N>`` entry, unsorted."""
    out = []
    for name in os.listdir(directory):
        if name.startswith("epoch_"):
            try:
                out.append((int(name.split("_")[1]),
                            os.path.join(directory, name)))
            except ValueError:
                continue
    return out


def latest_checkpoint(directory: str) -> Optional[str]:
    """Newest *restorable* checkpoint under ``directory``.

    Restorable = committed (carries ``COMMIT.json``).  When NO entry in
    the directory carries a marker the whole directory predates the
    commit protocol (pre-marker runs, imported reference weights) and
    every ``epoch_<N>`` entry is accepted — the old behavior, so
    existing workdirs keep resuming — EXCEPT entries carrying an
    in-flight stamp (a new-protocol save killed before its marker could
    land; see :func:`_inflight_stamp`).  In a marked directory an
    unmarked entry is exactly an in-flight or killed-mid-write save and
    is skipped (``--resume auto`` lands on the last committed epoch with
    no manual directory surgery).
    """
    if not os.path.isdir(directory):
        return None
    entries = _epoch_dirs(directory)
    if not entries:
        return None
    any_committed = any(is_committed(p) for _, p in entries)
    candidates = ([(e, p) for e, p in entries if is_committed(p)]
                  if any_committed else
                  [(e, p) for e, p in entries
                   if not os.path.exists(_inflight_stamp(directory, e))])
    if not candidates:
        return None
    return max(candidates)[1]


def restore_latest(directory: str, state: Optional[TrainState] = None):
    """``restore_checkpoint(latest_checkpoint(directory))`` — the resume
    entry point (``tools/train.py --resume auto``).  Returns None when
    the directory holds no committed (or legacy) checkpoint."""
    path = latest_checkpoint(directory)
    if path is None:
        return None
    return restore_checkpoint(path, state)


class CheckpointManager:
    """Async, donation-safe, crash-safe per-epoch checkpointing.

    ::

        manager = CheckpointManager(ckpt_dir, keep_last_n=3)
        for epoch ...:
            state, train_loss = train_epoch(...)
            manager.save(state, epoch, train_loss, best_loss)  # ~snapshot only
            val_loss = eval_epoch(...)       # overlaps the in-flight write
            manager.record_metric(epoch, "val_loss", val_loss, best_loss)
        manager.close()                      # flush the pending write

    ``save()`` blocks only on (a) the previous save's write — the
    wait-barrier that keeps multi-process save sequences aligned and
    bounds dirty state to one epoch — and (b) the device→host snapshot
    drain (see :func:`snapshot_to_host`).  Serialization, the Orbax
    write, the commit marker and retention GC run on a background writer
    thread; a writer failure is re-raised from the next ``save()`` /
    ``wait()`` so a broken disk cannot silently eat every checkpoint.

    Retention: ``keep_last_n`` (0 keeps everything), plus the best
    checkpoint by recorded metric when ``keep_best``, plus every epoch
    divisible by ``milestone_every`` when set.  GC only ever deletes
    COMMITTED checkpoints — an in-flight or killed-mid-write directory
    is never touched (it is invisible to ``latest_checkpoint`` anyway).

    The writer prefers Orbax's ``AsyncCheckpointer`` (its tensorstore
    writes parallelize internally; ``wait_until_finished`` is called on
    the same writer thread, so commit-marker ordering is unchanged) and
    falls back to a plain ``PyTreeCheckpointer`` when unavailable.

    Observability (all through the process defaults, so an installed
    ``obs.RunTelemetry`` picks the manager up with zero plumbing):
    ``snapshot``/``serialize``/``commit`` trace spans on their own
    ``checkpoint`` track, ``checkpoint_seconds{phase=...}`` histograms,
    ``checkpoint_bytes``/``checkpoints_retained`` gauges, and one
    ``checkpoint`` sink event per commit.
    """

    def __init__(self, directory: str, *, async_save: bool = True,
                 keep_last_n: int = 0, keep_best: bool = True,
                 milestone_every: int = 0, is_lead_host: bool = True,
                 registry=None, topology: Optional[Dict[str, Any]] = None,
                 _commit_delay_s: float = 0.0):
        self.directory = os.path.abspath(directory)
        self.async_save = bool(async_save)
        # device layout stamped into every commit marker (None = stamp
        # the process-global facts at save time); what restore-time
        # topology-change detection (parallel.mesh.topology_mismatch /
        # train.supervisor) compares against
        self.topology = topology
        self.keep_last_n = int(keep_last_n)
        self.keep_best = bool(keep_best)
        self.milestone_every = int(milestone_every)
        self.is_lead_host = bool(is_lead_host)
        # metrics registry: the process-wide default unless a run plumbs
        # its own (tests; the default is what /metrics exposes)
        self._reg = registry
        # fault-injection seam for the kill-during-write tests: sleep
        # between the Orbax write and the commit marker, the window a
        # real crash would leave a complete-but-uncommitted directory
        self._commit_delay_s = float(_commit_delay_s)
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        # epoch -> (metric name, value) (keep-best input; the name
        # matters — see _gc); rebuilt from existing commit markers so
        # retention stays correct across a resume
        self._metric: Dict[int, Tuple[str, float]] = {}
        # epoch -> metadata recorded after the save was kicked off
        # (val_loss lands mid-write); merged into the marker at commit,
        # or amended into an already-written marker
        self._pending_meta: Dict[int, Dict[str, Any]] = {}
        self._committed: set = set()
        # per-save train-loop blocked seconds (tools/ckpt_bench.py reads
        # this — it IS the number the async split is meant to shrink)
        self.blocked_seconds: list = []
        os.makedirs(self.directory, exist_ok=True)
        for epoch, path in _epoch_dirs(self.directory):
            meta = read_commit_meta(path)
            if meta is not None:
                self._committed.add(epoch)
                self._metric[epoch] = (
                    str(meta.get("metric", "train_loss")),
                    float(meta.get("metric_value",
                                   meta.get("train_loss", 0.0))))
        try:
            if jax.process_count() > 1:
                # multi-process: stay on the cross-process-validated
                # PyTreeCheckpointer barrier path (the 2-process Gloo
                # run in DIST_DRIVE.json); our writer thread still
                # provides the overlap
                raise RuntimeError("multi-process -> pytree writer")
            self._writer = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())
            self._writer_kind = "orbax_async"
        except Exception:  # noqa: BLE001 — older orbax without the async
            self._writer = None                    # machinery
            self._writer_kind = "pytree_thread"

    @classmethod
    def from_config(cls, directory: str, train_cfg,
                    is_lead_host: bool = True,
                    topology: Optional[Dict[str, Any]] = None
                    ) -> "CheckpointManager":
        """Build from ``TrainConfig`` knobs (``async_checkpoint``,
        ``keep_last_n``, ``keep_best``, ``milestone_every``)."""
        return cls(directory,
                   async_save=getattr(train_cfg, "async_checkpoint", True),
                   keep_last_n=getattr(train_cfg, "keep_last_n", 0),
                   keep_best=getattr(train_cfg, "keep_best", True),
                   milestone_every=getattr(train_cfg, "milestone_every", 0),
                   is_lead_host=is_lead_host, topology=topology)

    # ------------------------------------------------------------- save
    def save(self, state: TrainState, epoch: int, train_loss: float,
             best_loss: float) -> str:
        """Kick off the save of ``epoch``; returns its (future) path.

        Blocks on the previous save's write + the snapshot drain only
        (async mode); the Orbax write and commit happen in background.
        COLLECTIVE: every process must call this for the same epochs.
        """
        from ..obs.trace import get_tracer

        t_start = time.perf_counter()
        self.wait()  # barrier before the next save (re-raises writer errors)
        wait_s = time.perf_counter() - t_start
        tracer = get_tracer()
        t0 = time.perf_counter()
        with tracer.span("snapshot", track="checkpoint",
                         args={"epoch": epoch}):
            host = snapshot_to_host(
                _payload(state, epoch, train_loss, best_loss))
        snapshot_s = time.perf_counter() - t0
        from ..parallel.mesh import mesh_topology

        nbytes = _tree_bytes(host)
        path = os.path.join(self.directory, f"epoch_{epoch}")
        base_meta = _marker_meta(
            epoch, train_loss, best_loss, nbytes,
            topology=(self.topology if self.topology is not None
                      else mesh_topology()),
            **{"async": self.async_save})
        timings = {"wait_s": wait_s, "snapshot_s": snapshot_s}
        if self.is_lead_host:
            # in-flight stamp BEFORE the write starts: keeps a killed
            # partial out of the legacy resume fallback (removed
            # strictly after the commit marker lands)
            open(_inflight_stamp(self.directory, epoch), "w").close()
        if self.async_save:
            self._thread = threading.Thread(
                target=self._write_and_commit,
                args=(path, host, epoch, base_meta, timings),
                name="ckpt-writer", daemon=True)
            self._thread.start()
            blocked = time.perf_counter() - t_start
        else:
            self._write_and_commit(path, host, epoch, base_meta, timings)
            if self._error is not None:
                err, self._error = self._error, None
                raise err
            blocked = time.perf_counter() - t_start
        self.blocked_seconds.append(blocked)
        self._observe("blocked", blocked)
        self._observe("snapshot", snapshot_s)
        self._registry().gauge(
            "checkpoint_bytes",
            "host-snapshot size of the last checkpoint payload").set(nbytes)
        return path

    def record_metric(self, epoch: int, name: str, value: float,
                      best_loss: Optional[float] = None) -> None:
        """Attach the post-eval metric to ``epoch``'s checkpoint.

        Called AFTER validation finished — i.e. possibly while (or after)
        the write commits, since eval overlaps the write.  The metadata
        lands in the commit marker either way: merged at commit time if
        the writer has not committed yet, or amended into the marker
        atomically if it has.  Also feeds keep-best retention.
        """
        meta = {"metric": str(name), "metric_value": float(value)}
        if best_loss is not None:
            meta["best_loss"] = float(best_loss)
        # the commit transition (merge pending -> write marker -> mark
        # committed) happens atomically under the same lock in
        # _write_and_commit, so exactly one of these branches fires and
        # an amend can never read a marker that is still being written
        with self._lock:
            self._metric[epoch] = (str(name), float(value))
            if epoch not in self._committed:
                self._pending_meta.setdefault(epoch, {}).update(meta)
            elif self.is_lead_host:
                path = os.path.join(self.directory, f"epoch_{epoch}")
                marker = read_commit_meta(path) or {}
                marker.update(meta)
                _write_marker(path, marker)

    def wait(self) -> None:
        """Join the in-flight write; re-raise its failure.  Call between
        a save and anything that needs the checkpoint on disk, and at
        fit exit (a sentinel halt must still flush the pending write)."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def close(self) -> None:
        """Flush the in-flight write, then release the orbax async
        writer's background machinery (it owns a commit thread that
        outlives the manager otherwise).  Terminal — a save after close
        would fall back to the plain pytree writer."""
        self.wait()
        writer, self._writer = self._writer, None
        if writer is not None:
            try:
                writer.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        # an exception is already unwinding: flush, but don't let a
        # writer failure mask it
        if exc and exc[0] is not None:
            try:
                self.wait()
            except Exception:  # noqa: BLE001
                pass
        else:
            self.close()

    # ------------------------------------------------------- background
    def _write_and_commit(self, path: str, host_tree, epoch: int,
                          base_meta: Dict[str, Any],
                          timings: Dict[str, float]) -> None:
        from ..obs.events import get_sink
        from ..obs.trace import get_tracer

        tracer = get_tracer()
        try:
            t0 = time.perf_counter()
            with tracer.span("serialize", track="checkpoint",
                             args={"epoch": epoch}):
                if self._writer is not None:
                    # orbax's async machinery parallelizes the tensorstore
                    # writes; waiting HERE (the writer thread) keeps the
                    # marker strictly after the write
                    self._writer.save(path, host_tree, force=True)
                    self._writer.wait_until_finished()
                else:
                    ocp.PyTreeCheckpointer().save(path, host_tree,
                                                  force=True)
            serialize_s = time.perf_counter() - t0
            # deterministic fault-injection point (tools/chaos_train.py):
            # a kill HERE leaves a complete-looking but uncommitted
            # directory — exactly what the commit protocol must survive
            from .supervisor import chaos_kill_point

            chaos_kill_point("mid_ckpt_write")
            if self._commit_delay_s:
                time.sleep(self._commit_delay_s)
            t0 = time.perf_counter()
            with tracer.span("commit", track="checkpoint",
                             args={"epoch": epoch}):
                with self._lock:
                    # atomic commit transition (see record_metric): the
                    # marker is on disk before the epoch reads as
                    # committed, so a concurrent record_metric either
                    # lands in the pending merge or amends a complete
                    # marker — never a half-written one
                    meta = dict(base_meta)
                    meta.update(self._pending_meta.pop(epoch, {}))
                    meta["time_unix"] = round(time.time(), 3)
                    if self.is_lead_host:
                        _write_marker(path, meta)
                        try:
                            os.remove(_inflight_stamp(self.directory,
                                                      epoch))
                        except OSError:
                            pass
                    self._committed.add(epoch)
                    self._metric.setdefault(
                        epoch, (str(meta["metric"]),
                                float(meta["metric_value"])))
                retained = self._gc()
            commit_s = time.perf_counter() - t0
            self._observe("serialize", serialize_s)
            self._observe("commit", commit_s)
            get_sink().emit(
                "checkpoint", epoch=epoch, path=path,
                bytes=base_meta["payload_bytes"],
                wait_s=round(timings["wait_s"], 6),
                snapshot_s=round(timings["snapshot_s"], 6),
                serialize_s=round(serialize_s, 6),
                commit_s=round(commit_s, 6),
                retained=retained, writer=self._writer_kind,
                async_save=self.async_save)
        except BaseException as e:  # noqa: BLE001 — surfaced on the
            self._error = e         # caller thread by wait()/next save()

    # -------------------------------------------------------- retention
    def _gc(self) -> int:
        """Delete committed checkpoints outside the retention set; never
        touches uncommitted (in-flight / killed partial) directories.
        Returns the retained-committed count.  Lead host only."""
        entries = _epoch_dirs(self.directory)
        committed = {e: p for e, p in entries if is_committed(p)}
        if not self.is_lead_host or self.keep_last_n <= 0:
            n = len(committed)
            self._retained_gauge().set(n)
            return n
        keep = set(sorted(committed)[-self.keep_last_n:])
        if self.keep_best:
            with self._lock:
                scored = {e: nv for e, nv in self._metric.items()
                          if e in committed}
            # never rank val_loss-scored epochs against train_loss-scored
            # ones (train loss is systematically lower — under
            # eval_freq>1 a raw min() would crown a non-validated epoch
            # and GC the checkpoint that actually generalizes): when ANY
            # committed epoch carries a val score, best is best-by-val.
            # Non-finite scores (a diverged epoch under --on-divergence
            # warn) never compete — every NaN comparison is False, so a
            # NaN would WIN min() and keep-best would protect exactly
            # the diverged checkpoint
            scored = {e: (n, v) for e, (n, v) in scored.items()
                      if math.isfinite(v)}
            val = {e: v for e, (n, v) in scored.items() if n == "val_loss"}
            pool = val or {e: v for e, (n, v) in scored.items()}
            if pool:
                keep.add(min(pool, key=pool.get))
        if self.milestone_every > 0:
            keep.update(e for e in committed
                        if e % self.milestone_every == 0)
        for e, p in committed.items():
            if e not in keep:
                shutil.rmtree(p, ignore_errors=True)
        self._retained_gauge().set(len(keep))
        return len(keep)

    # ------------------------------------------------------------- obs
    def _registry(self):
        if self._reg is not None:
            return self._reg
        from ..obs.registry import get_registry

        return get_registry()

    def _retained_gauge(self):
        return self._registry().gauge(
            "checkpoints_retained",
            "committed checkpoints kept after retention GC")

    def _observe(self, phase: str, seconds: float) -> None:
        self._registry().histogram(
            "checkpoint_seconds",
            "checkpoint phase durations (blocked = train-loop stall)",
            labels={"phase": phase}).observe(seconds)
