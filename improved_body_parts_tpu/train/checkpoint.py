"""Orbax checkpointing.

Replaces the reference's torch.save dict {weights, optimizer_weight,
train_loss, epoch} and its resume-time 'module.' key remapping
(reference: train.py:149-162, train_distributed.py:149-197, 304-324) — under
functional params there is nothing to remap.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from .state import TrainState


def _to_host(tree):
    return jax.tree.map(np.asarray, tree)


def save_checkpoint(directory: str, state: TrainState, epoch: int,
                    train_loss: float, best_loss: float) -> str:
    """Write checkpoint ``<directory>/epoch_<N>`` and return its path.

    COLLECTIVE under multi-process JAX: orbax synchronizes all processes
    during save (and writes once, from the primary host) — every process
    must call this, not just rank 0, or the barrier never completes and
    the checkpoint is lost (observed on a 2-process Gloo run)."""
    path = os.path.abspath(os.path.join(directory, f"epoch_{epoch}"))
    payload = {
        "params": _to_host(state.params),
        "batch_stats": _to_host(state.batch_stats),
        "opt_state": _to_host(state.opt_state),
        "step": int(state.step),
        "swa_params": (_to_host(state.swa_params)
                       if state.swa_params is not None else None),
        "swa_count": (int(state.swa_count)
                      if state.swa_count is not None else None),
        "swa_start_step": (int(state.swa_start_step)
                           if state.swa_start_step is not None else None),
        "epoch": epoch,
        "train_loss": float(train_loss),
        "best_loss": float(best_loss),
    }
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, payload, force=True)
    return path


def restore_checkpoint(path: str, state: Optional[TrainState] = None
                       ) -> Dict[str, Any]:
    """Load a checkpoint; if ``state`` is given, return (state, meta) with the
    arrays restored into it (resume semantics of train_distributed.py:149-197).

    Orbax serializes custom pytree nodes (optax namedtuple states) as plain
    containers; with a ``state`` template we re-impose the original structure
    on the restored leaves so ``optimizer.update`` keeps working.
    """
    ckptr = ocp.PyTreeCheckpointer()
    payload = ckptr.restore(os.path.abspath(path))
    if state is None:
        return payload

    def rebuild(template, restored):
        """Unflatten restored leaves into the template's pytree structure.

        Leaf correspondence holds because orbax preserves each container's
        key/field layout (namedtuples round-trip as dicts keyed by field
        name, whose serialization order jax also uses when flattening).

        Checkpoints without optimizer state (imported reference weights,
        tools/import_torch_checkpoint.py) keep the template's freshly
        initialized opt_state.
        """
        if restored is None:
            return template
        leaves = jax.tree.leaves(restored)
        treedef = jax.tree.structure(template)
        assert treedef.num_leaves == len(leaves), (
            f"checkpoint opt_state has {len(leaves)} leaves, "
            f"optimizer expects {treedef.num_leaves}")
        return jax.tree.unflatten(treedef, leaves)

    restored = state.replace(
        params=payload["params"],
        batch_stats=payload["batch_stats"],
        opt_state=rebuild(state.opt_state, payload["opt_state"]),
        step=np.asarray(payload["step"], np.int32),
        swa_params=payload.get("swa_params"),
        swa_count=(np.asarray(payload["swa_count"], np.int32)
                   if payload.get("swa_count") is not None else None),
        swa_start_step=(np.asarray(payload["swa_start_step"], np.int32)
                        if payload.get("swa_start_step") is not None
                        else None),
    )
    meta = {k: payload[k] for k in ("epoch", "train_loss", "best_loss")}
    return restored, meta


def latest_checkpoint(directory: str) -> Optional[str]:
    if not os.path.isdir(directory):
        return None
    epochs = []
    for name in os.listdir(directory):
        if name.startswith("epoch_"):
            try:
                epochs.append((int(name.split("_")[1]), name))
            except ValueError:
                continue
    if not epochs:
        return None
    return os.path.join(directory, max(epochs)[1])
