"""Epoch-level training loop.

The framework equivalent of the reference entry scripts' train()/test()
(reference: train.py:104-206, train_distributed.py:225-379): per-epoch batch
loop over a host data source, device placement with batch sharding, throttled
metric readback, append-only epoch log, per-epoch checkpointing.

Host→device: batches are placed with ``shard_batch`` via ``device_prefetch``
(a background thread keeps ``prefetch_depth`` sharded batches in flight, so
transfer overlaps the asynchronously dispatched device step — the TPU
analogue of DataLoader prefetch + .cuda(non_blocking), README.md:34); metric
readback happens every ``print_freq`` steps only — the TPU analogue of the
reference's throttled all-reduce + cuda.synchronize
(train_distributed.py:272-298).
"""
from __future__ import annotations

import math
import os
from typing import Callable, Iterable, Optional, Tuple

import jax
import numpy as np

from ..config import Config
from ..obs.health import DivergenceError
from ..parallel.prefetch import device_prefetch
from ..utils import AverageMeter, StepTimer
from . import checkpoint as ckpt
from .state import TrainState
from .supervisor import StopRequested, chaos_kill_point


def _log_line(checkpoint_dir: str, text: str) -> None:
    """Append-only epoch log (reference: train_distributed.py:304-310)."""
    os.makedirs(checkpoint_dir, exist_ok=True)
    with open(os.path.join(checkpoint_dir, "log"), "a") as f:
        f.write(text)


def train_epoch(state: TrainState, train_step: Callable,
                batches: Iterable, config: Config, epoch: int,
                mesh=None, print_freq: Optional[int] = None,
                is_lead_host: bool = True,
                log_fn: Callable[[str], None] = print,
                prefetch_depth: int = 2,
                telemetry=None,
                should_stop: Optional[Callable[[], bool]] = None
                ) -> Tuple[TrainState, float]:
    """Run one epoch; returns (state, mean loss).

    ``should_stop`` is the elastic-training stop-point predicate
    (``train.supervisor.RunSupervisor.should_stop``): it is checked at
    each window readback — the boundary where the device has already
    drained — and a True raises :class:`supervisor.StopRequested`, which
    unwinds through ``fit``'s flush path (the in-flight checkpoint write
    lands before the process exits).  The partial epoch is discarded;
    resume restarts it from the last committed checkpoint.

    ``batches`` yields (images, mask_miss, labels) host arrays — or
    (images, mask_miss, joints, mask_all) when ``train_step`` was built
    with ``device_gt=True`` — this host's shard of the global batch when
    running multi-host.

    ``telemetry`` (an ``obs.RunTelemetry``) turns each print window into
    a structured ``train_step`` event — loss, step time, imgs/s, and the
    data-wait vs compute split measured inside ``device_prefetch`` — and
    marks the compile watch warm after the first window's readback (the
    first sync that proves every steady-state program compiled).  Each
    window additionally records a ``step_window`` trace span (whose
    data-wait/compute children come from ``StepPhases``), samples the
    per-device HBM gauges into the stream, and feeds the run-health
    sentinel: a step built with ``make_train_step(health=True)`` returns
    (state, loss, grad_norm) and the extra scalar is read back HERE, at
    the window sync that already happens — under the ``halt`` policy a
    divergent window raises :class:`obs.DivergenceError`.  Any other
    exception out of the loop triggers the OOM-forensics dump (largest
    live device buffers by shape/dtype) into the event stream before
    re-raising.
    """
    print_freq = print_freq or config.train.print_freq
    losses = AverageMeter()
    timer = StepTimer()
    # (device loss, batch size, device grad-norm-or-None) triples not yet
    # read back: the scalars are left on device to avoid a per-step sync,
    # but the weight must be recorded NOW — a trailing partial batch
    # drained after the loop would otherwise be averaged at the last full
    # batch's weight
    pending = []

    phases = telemetry.phases("train") if telemetry is not None else None
    if mesh is not None:
        batches = device_prefetch(batches, mesh, depth=prefetch_depth,
                                  phase_stats=phases)
    elif phases is not None:
        batches = phases.attribute(batches)
    trace = telemetry.trace if telemetry is not None else None
    if telemetry is not None:
        g_loss = telemetry.registry.gauge(
            "train_loss", "windowed loss readback (losses.val)")
        g_ips = telemetry.registry.gauge(
            "train_imgs_per_sec", "window throughput")
        h_step = telemetry.registry.histogram(
            "train_step_seconds", "per-step wall time (window mean)")
        window_t0 = phases.totals()
        windows = 0
        w_trace_t0 = trace.now() if trace.enabled else 0.0
    global_batch = None

    def window_health(vals):
        """Summarize one window for the sentinel: the first non-finite
        loss (else the last), the first non-finite grad norm (else the
        window max) — a single check per window, worst case wins."""
        w_losses = [v for v, _, _ in vals]
        loss_h = next((v for v in w_losses if not math.isfinite(v)),
                      w_losses[-1])
        gns = [float(g) for _, _, g in vals if g is not None]
        if not gns:
            return loss_h, None
        return loss_h, next((g for g in gns if not math.isfinite(g)),
                            max(gns))

    def close_window(vals, n_steps, step_no, dt, partial=False):
        """Everything one readback window owes the telemetry bundle —
        warm mark, split diff, gauges, trace span, stream record, health
        check, memory sample — ONE implementation for the in-loop and
        trailing-partial sites, so a new window signal cannot be added
        to one and silently lost from the other."""
        nonlocal window_t0, windows, w_trace_t0
        # the readback that produced `vals` blocked until the device
        # drained: every steady-state program is compiled from here on
        telemetry.mark_warm("epoch-end readback" if partial
                            else "first train window readback")
        wait, hold = phases.totals()
        d_wait = wait - window_t0[0]
        d_hold = hold - window_t0[1]
        window_t0 = (wait, hold)
        imgs_s = global_batch / max(dt, 1e-9)
        g_loss.set(losses.val)
        g_ips.set(imgs_s)
        h_step.observe(dt)
        if trace.enabled:
            # own track: a window closes mid-hold (at this readback), so
            # on the consumer's track it would PARTIALLY overlap the
            # boundary batch's compute span — invalid (non-nested)
            # slices that trace viewers flag; a dedicated lane tiles
            # cleanly above the phase spans instead
            t_now = trace.now()
            span_args = {"epoch": epoch, "step": step_no,
                         "loss": round(losses.val, 6)}
            if partial:
                span_args["partial"] = n_steps
            trace.add_span_rel("step_window", w_trace_t0,
                               t_now - w_trace_t0, track="train-windows",
                               args=span_args)
            w_trace_t0 = t_now
        windows += 1
        # a trailing partial window always emits (an epoch shorter than
        # print_freq would otherwise emit NOTHING); full windows honor
        # the step_sample thinning
        if partial or windows % telemetry.step_sample == 0:
            fields = dict(
                epoch=epoch, step=step_no,
                loss=round(losses.val, 6), loss_avg=round(losses.avg, 6),
                step_s=round(dt, 6), imgs_per_sec=round(imgs_s, 2),
                data_wait_s=round(d_wait, 6), compute_s=round(d_hold, 6))
            if partial:
                fields["partial_window"] = n_steps
            telemetry.emit("train_step", **fields)
        loss_h, gn_h = window_health(vals)
        # may raise DivergenceError (on_divergence=halt)
        telemetry.health.check(loss_h, gn_h, step=step_no, epoch=epoch)
        telemetry.memory.sample(emit=True, epoch=epoch, step=step_no)

    try:
        for step_idx, batch in enumerate(batches):
            # batch is (images, mask_miss, labels) — or (images,
            # mask_miss, joints, mask_all) when the step synthesizes GT
            # on device
            global_batch = batch[0].shape[0]
            out = train_step(state, *batch)
            if len(out) == 3:  # health-instrumented step
                state, loss, gnorm = out
            else:
                (state, loss), gnorm = out, None
            pending.append((loss, global_batch, gnorm))

            if (step_idx + 1) % print_freq == 0:
                # one device sync per print_freq steps
                vals = [(float(v), bs, g) for v, bs, g in pending]
                pending.clear()
                for v, bs, _ in vals:
                    losses.update(v, bs)
                dt = timer.mark(print_freq)
                if telemetry is not None:
                    # may raise DivergenceError (on_divergence=halt)
                    close_window(vals, print_freq, step_idx + 1, dt)
                if is_lead_host:
                    log_fn(
                        f"==> Epoch [{epoch}][{step_idx + 1}] "
                        f"loss {losses.val:.6f} ({losses.avg:.6f}) "
                        f"imgs/s {global_batch / max(dt, 1e-9):.1f}")
                chaos_kill_point("window")
                if should_stop is not None and should_stop():
                    # window boundary: the readback above already synced
                    # the device, so stopping HERE loses only the steps
                    # since the last committed checkpoint
                    raise StopRequested(
                        f"stop requested at epoch {epoch} step "
                        f"{step_idx + 1} (window boundary)")

        n_tail = len(pending)
        tail_vals = [(float(v), bs, g) for v, bs, g in pending]
        pending.clear()
        for v, bs, _ in tail_vals:
            losses.update(v, bs)
        if telemetry is not None and n_tail:
            # trailing partial window (epochs shorter than print_freq
            # would otherwise emit NOTHING — and never mark the compile
            # watch warm)
            close_window(tail_vals, n_tail, step_idx + 1,
                         timer.mark(n_tail), partial=True)
    except Exception as e:
        if telemetry is not None and not isinstance(
                e, (DivergenceError, StopRequested)):
            # the step loop died — name the resident device buffers
            # before unwinding (an HBM OOM post-mortem's first question);
            # a sentinel halt carries its own diagnosis and skips this.
            # Best-effort: a failing emit (ENOSPC is CORRELATED with
            # OOM-era runs) must not replace the original exception
            try:
                msg = str(e)
                telemetry.memory.emit_forensics(
                    reason=f"{type(e).__name__}: {msg[:300]}", epoch=epoch,
                    oom=("RESOURCE_EXHAUSTED" in msg
                         or "out of memory" in msg.lower()))
            except Exception:  # noqa: BLE001 — diagnostics only
                pass
        raise
    return state, losses.avg


def eval_epoch(state: TrainState, eval_step: Callable, batches: Iterable,
               mesh=None, prefetch_depth: int = 2,
               readback_freq: int = 32) -> float:
    """Eval pass; returns the sample-weighted mean loss.

    Like ``train_epoch``, the per-batch device losses are BUFFERED
    (device scalars are a few bytes each) and read back in windows: a
    per-batch ``float(loss)`` would sync the device every step,
    serializing host placement against the eval dispatch and defeating
    ``device_prefetch`` for the whole pass.  The window
    (``readback_freq``) also bounds async dispatch: without any sync a
    host faster than the device would enqueue the entire epoch, every
    unexecuted step pinning its input batch in device memory.
    """
    losses = AverageMeter()
    if mesh is not None:
        batches = device_prefetch(batches, mesh, depth=prefetch_depth)
    pending = []
    for batch in batches:
        chaos_kill_point("mid_eval")
        pending.append((eval_step(state, *batch), batch[0].shape[0]))
        if len(pending) >= readback_freq:
            for loss, bs in pending:
                losses.update(float(loss), bs)
            pending.clear()
    for loss, bs in pending:
        losses.update(float(loss), bs)
    return losses.avg


def fit(state: TrainState, train_step: Callable, config: Config,
        make_batches: Callable[[int], Iterable], epochs: int,
        start_epoch: int = 0, mesh=None,
        eval_step: Optional[Callable] = None,
        make_eval_batches: Optional[Callable[[int], Iterable]] = None,
        is_lead_host: bool = True,
        checkpoint_dir: Optional[str] = None,
        log_fn: Callable[[str], None] = print,
        best_loss: float = float("inf"),
        telemetry=None,
        checkpoint_manager=None,
        should_stop: Optional[Callable[[], bool]] = None) -> TrainState:
    """Multi-epoch driver with async per-epoch checkpoint + log
    (reference: train_distributed.py:300-324, 441-444).

    ``make_batches(epoch)`` returns that epoch's (shuffled) batch iterable —
    the epoch-seeded permutation replaces DistributedSampler.set_epoch
    (train_distributed.py:231-232).  Pass the restored checkpoint's
    ``best_loss`` on resume so the metadata keeps tracking the true best.

    The epoch boundary is no longer serial: the checkpoint save is
    *kicked off* (``CheckpointManager.save`` blocks only on the
    device→host snapshot drain), then validation runs WHILE the Orbax
    write commits in background; the manager's wait-barrier before the
    next save (and at fit exit, crash or not) bounds in-flight state to
    one epoch.  ``config.train.save_freq`` / ``eval_freq`` thin the
    cadence; the FINAL epoch always saves (the same always-ship rule as
    the trailing SWA checkpoint), and the save/eval decisions are
    epoch-number-based, i.e. process-symmetric — the collective
    save/eval entries stay aligned across a multi-process run.

    ``best_loss`` is keyed on **val_loss whenever an eval pass ran**
    (falling back to train loss) — keep-best retention then keeps the
    checkpoint that actually generalizes — and the metric used is
    recorded in the checkpoint's commit metadata
    (``CheckpointManager.record_metric``; the marker is amended after
    eval since the write it describes may already have committed).

    Pass ``checkpoint_manager`` to share one manager across stages
    (``tools/train.py`` owns it alongside SWA); otherwise fit builds one
    from the config's ``async_checkpoint``/retention knobs and flushes
    it on exit.
    """
    from ..obs.trace import get_tracer

    checkpoint_dir = checkpoint_dir or config.train.checkpoint_dir
    tr = config.train
    owns_manager = checkpoint_manager is None
    manager = checkpoint_manager
    if manager is None:
        from ..parallel.mesh import mesh_topology

        manager = ckpt.CheckpointManager.from_config(
            checkpoint_dir, tr, is_lead_host=is_lead_host,
            topology=mesh_topology(mesh))
    save_freq = max(1, int(getattr(tr, "save_freq", 1) or 1))
    eval_freq = max(1, int(getattr(tr, "eval_freq", 1) or 1))
    last_epoch = start_epoch + epochs - 1
    try:
        for epoch in range(start_epoch, start_epoch + epochs):
            state, train_loss = train_epoch(
                state, train_step, make_batches(epoch), config, epoch,
                mesh=mesh, is_lead_host=is_lead_host, log_fn=log_fn,
                telemetry=telemetry, should_stop=should_stop)
            if is_lead_host:
                _log_line(checkpoint_dir,
                          f"\nEpoch {epoch}\ttrain_loss: {train_loss}")
            # cadence keys on the ABSOLUTE epoch number: resume-stable
            # (which epochs save does not depend on where the previous
            # run was interrupted) and aligned with retention's
            # milestone_every, which also keys on absolute epochs —
            # nth-since-start would make --save-freq 5 --milestone-every
            # 10 never save a milestone
            do_save = epoch % save_freq == 0 or epoch == last_epoch
            do_eval = (eval_step is not None
                       and make_eval_batches is not None
                       and (epoch % eval_freq == 0 or epoch == last_epoch))
            if do_save:
                # collective kickoff: orbax barriers across processes and
                # writes once from the primary host — every process
                # participates (see checkpoint.CheckpointManager); only
                # the snapshot drain blocks here, the write overlaps the
                # eval below (and epoch+1's steps)
                manager.save(state, epoch, train_loss, best_loss)
                chaos_kill_point("post_save")
            val_loss = None
            if do_eval:
                with get_tracer().span("eval_epoch", track="eval",
                                       args={"epoch": epoch}):
                    val_loss = eval_epoch(state, eval_step,
                                          make_eval_batches(epoch),
                                          mesh=mesh)
                if is_lead_host:
                    _log_line(checkpoint_dir, f"\tval_loss: {val_loss}")
                    log_fn(f"Epoch {epoch} val_loss {val_loss:.6f}")
            # best is keyed on the validation loss whenever a val pass
            # ran — train loss only as the fallback.  The watermark only
            # folds in COMPARABLE values: with eval configured but
            # thinned away this epoch (eval_freq>1), the epoch's train
            # loss is systematically lower than any val loss and would
            # contaminate the val-loss watermark permanently (it resumes
            # through the checkpoint metadata)
            has_eval = eval_step is not None and make_eval_batches is not None
            metric_name, metric = (("val_loss", val_loss)
                                   if val_loss is not None
                                   else ("train_loss", train_loss))
            if val_loss is not None or not has_eval:
                best_loss = min(best_loss, metric)
            if do_save:
                manager.record_metric(epoch, metric_name, metric, best_loss)
            if telemetry is not None:
                fields = {"epoch": epoch, "train_loss": round(train_loss, 6)}
                if val_loss is not None:
                    fields["val_loss"] = round(val_loss, 6)
                if do_save:
                    fields["saved"] = True
                telemetry.emit("epoch", **fields)
            if should_stop is not None and should_stop() \
                    and epoch != last_epoch:
                # epoch boundary: this epoch's save is already kicked
                # off; the unwind below flushes it before the process
                # exits, so the stop loses zero completed work
                raise StopRequested(
                    f"stop requested at epoch {epoch} boundary")
    except BaseException:
        # a sentinel halt (obs.DivergenceError) or any crash must still
        # flush the in-flight write — the run that just died is exactly
        # the one whose last checkpoint matters — without letting a
        # writer failure mask the original exception
        try:
            manager.close() if owns_manager else manager.wait()
        except Exception:  # noqa: BLE001 — diagnostics-only path
            pass
        raise
    # fit-exit barrier: the trailing write lands (and its failure
    # surfaces) before fit returns; a fit-owned manager also releases
    # its orbax async writer (a caller-owned one stays open for the
    # caller's next stage)
    manager.close() if owns_manager else manager.wait()
    return state
