"""Epoch-level training loop.

The framework equivalent of the reference entry scripts' train()/test()
(reference: train.py:104-206, train_distributed.py:225-379): per-epoch batch
loop over a host data source, device placement with batch sharding, throttled
metric readback, append-only epoch log, per-epoch checkpointing.

Host→device: batches are placed with ``shard_batch`` via ``device_prefetch``
(a background thread keeps ``prefetch_depth`` sharded batches in flight, so
transfer overlaps the asynchronously dispatched device step — the TPU
analogue of DataLoader prefetch + .cuda(non_blocking), README.md:34); metric
readback happens every ``print_freq`` steps only — the TPU analogue of the
reference's throttled all-reduce + cuda.synchronize
(train_distributed.py:272-298).
"""
from __future__ import annotations

import os
from typing import Callable, Iterable, Optional, Tuple

import jax
import numpy as np

from ..config import Config
from ..parallel.prefetch import device_prefetch
from ..utils import AverageMeter, StepTimer
from . import checkpoint as ckpt
from .state import TrainState


def _log_line(checkpoint_dir: str, text: str) -> None:
    """Append-only epoch log (reference: train_distributed.py:304-310)."""
    os.makedirs(checkpoint_dir, exist_ok=True)
    with open(os.path.join(checkpoint_dir, "log"), "a") as f:
        f.write(text)


def train_epoch(state: TrainState, train_step: Callable,
                batches: Iterable, config: Config, epoch: int,
                mesh=None, print_freq: Optional[int] = None,
                is_lead_host: bool = True,
                log_fn: Callable[[str], None] = print,
                prefetch_depth: int = 2,
                telemetry=None
                ) -> Tuple[TrainState, float]:
    """Run one epoch; returns (state, mean loss).

    ``batches`` yields (images, mask_miss, labels) host arrays — or
    (images, mask_miss, joints, mask_all) when ``train_step`` was built
    with ``device_gt=True`` — this host's shard of the global batch when
    running multi-host.

    ``telemetry`` (an ``obs.RunTelemetry``) turns each print window into
    a structured ``train_step`` event — loss, step time, imgs/s, and the
    data-wait vs compute split measured inside ``device_prefetch`` — and
    marks the compile watch warm after the first window's readback (the
    first sync that proves every steady-state program compiled).
    """
    print_freq = print_freq or config.train.print_freq
    losses = AverageMeter()
    timer = StepTimer()
    # (device loss, batch size) pairs not yet read back: the loss is left
    # on device to avoid a per-step sync, but its weight must be recorded
    # NOW — a trailing partial batch drained after the loop would otherwise
    # be averaged at the last full batch's weight
    pending = []

    phases = telemetry.phases("train") if telemetry is not None else None
    if mesh is not None:
        batches = device_prefetch(batches, mesh, depth=prefetch_depth,
                                  phase_stats=phases)
    elif phases is not None:
        batches = phases.attribute(batches)
    if telemetry is not None:
        g_loss = telemetry.registry.gauge(
            "train_loss", "windowed loss readback (losses.val)")
        g_ips = telemetry.registry.gauge(
            "train_imgs_per_sec", "window throughput")
        h_step = telemetry.registry.histogram(
            "train_step_seconds", "per-step wall time (window mean)")
        window_t0 = phases.totals()
        windows = 0
    global_batch = None
    for step_idx, batch in enumerate(batches):
        # batch is (images, mask_miss, labels) — or (images, mask_miss,
        # joints, mask_all) when the step synthesizes GT on device
        global_batch = batch[0].shape[0]
        state, loss = train_step(state, *batch)
        pending.append((loss, global_batch))

        if (step_idx + 1) % print_freq == 0:
            # one device sync per print_freq steps
            vals = [(float(v), bs) for v, bs in pending]
            pending.clear()
            for v, bs in vals:
                losses.update(v, bs)
            dt = timer.mark(print_freq)
            if telemetry is not None:
                # the readback above blocked until the device drained:
                # every steady-state program is compiled from here on
                telemetry.mark_warm("first train window readback")
                wait, hold = phases.totals()
                d_wait = wait - window_t0[0]
                d_hold = hold - window_t0[1]
                window_t0 = (wait, hold)
                imgs_s = global_batch / max(dt, 1e-9)
                g_loss.set(losses.val)
                g_ips.set(imgs_s)
                h_step.observe(dt)
                windows += 1
                if windows % telemetry.step_sample == 0:
                    telemetry.emit(
                        "train_step", epoch=epoch, step=step_idx + 1,
                        loss=round(losses.val, 6),
                        loss_avg=round(losses.avg, 6),
                        step_s=round(dt, 6),
                        imgs_per_sec=round(imgs_s, 2),
                        data_wait_s=round(d_wait, 6),
                        compute_s=round(d_hold, 6))
            if is_lead_host:
                log_fn(
                    f"==> Epoch [{epoch}][{step_idx + 1}] "
                    f"loss {losses.val:.6f} ({losses.avg:.6f}) "
                    f"imgs/s {global_batch / max(dt, 1e-9):.1f}")

    n_tail = len(pending)
    for v, bs in pending:
        losses.update(float(v), bs)
    if telemetry is not None and n_tail:
        # trailing partial window (epochs shorter than print_freq would
        # otherwise emit NOTHING — and never mark the compile watch warm)
        telemetry.mark_warm("epoch-end readback")
        dt = timer.mark(n_tail)
        wait, hold = phases.totals()
        telemetry.emit(
            "train_step", epoch=epoch, step=step_idx + 1,
            loss=round(losses.val, 6), loss_avg=round(losses.avg, 6),
            step_s=round(dt, 6),
            imgs_per_sec=round(global_batch / max(dt, 1e-9), 2),
            data_wait_s=round(wait - window_t0[0], 6),
            compute_s=round(hold - window_t0[1], 6),
            partial_window=n_tail)
    return state, losses.avg


def eval_epoch(state: TrainState, eval_step: Callable, batches: Iterable,
               mesh=None, prefetch_depth: int = 2,
               readback_freq: int = 32) -> float:
    """Eval pass; returns the sample-weighted mean loss.

    Like ``train_epoch``, the per-batch device losses are BUFFERED
    (device scalars are a few bytes each) and read back in windows: a
    per-batch ``float(loss)`` would sync the device every step,
    serializing host placement against the eval dispatch and defeating
    ``device_prefetch`` for the whole pass.  The window
    (``readback_freq``) also bounds async dispatch: without any sync a
    host faster than the device would enqueue the entire epoch, every
    unexecuted step pinning its input batch in device memory.
    """
    losses = AverageMeter()
    if mesh is not None:
        batches = device_prefetch(batches, mesh, depth=prefetch_depth)
    pending = []
    for batch in batches:
        pending.append((eval_step(state, *batch), batch[0].shape[0]))
        if len(pending) >= readback_freq:
            for loss, bs in pending:
                losses.update(float(loss), bs)
            pending.clear()
    for loss, bs in pending:
        losses.update(float(loss), bs)
    return losses.avg


def fit(state: TrainState, train_step: Callable, config: Config,
        make_batches: Callable[[int], Iterable], epochs: int,
        start_epoch: int = 0, mesh=None,
        eval_step: Optional[Callable] = None,
        make_eval_batches: Optional[Callable[[int], Iterable]] = None,
        is_lead_host: bool = True,
        checkpoint_dir: Optional[str] = None,
        log_fn: Callable[[str], None] = print,
        best_loss: float = float("inf"),
        telemetry=None) -> TrainState:
    """Multi-epoch driver with per-epoch rank-0 checkpoint + log
    (reference: train_distributed.py:300-324, 441-444).

    ``make_batches(epoch)`` returns that epoch's (shuffled) batch iterable —
    the epoch-seeded permutation replaces DistributedSampler.set_epoch
    (train_distributed.py:231-232).  Pass the restored checkpoint's
    ``best_loss`` on resume so the metadata keeps tracking the true best.
    """
    checkpoint_dir = checkpoint_dir or config.train.checkpoint_dir
    for epoch in range(start_epoch, start_epoch + epochs):
        state, train_loss = train_epoch(
            state, train_step, make_batches(epoch), config, epoch, mesh=mesh,
            is_lead_host=is_lead_host, log_fn=log_fn, telemetry=telemetry)
        if is_lead_host:
            _log_line(checkpoint_dir,
                      f"\nEpoch {epoch}\ttrain_loss: {train_loss}")
        best_loss = min(best_loss, train_loss)
        # collective: orbax barriers across processes and writes once from
        # the primary host — every process participates (see
        # checkpoint.save_checkpoint)
        ckpt.save_checkpoint(checkpoint_dir, state, epoch, train_loss,
                             best_loss)
        val_loss = None
        if eval_step is not None and make_eval_batches is not None:
            val_loss = eval_epoch(state, eval_step, make_eval_batches(epoch),
                                  mesh=mesh)
            if is_lead_host:
                _log_line(checkpoint_dir, f"\tval_loss: {val_loss}")
                log_fn(f"Epoch {epoch} val_loss {val_loss:.6f}")
        if telemetry is not None:
            fields = {"epoch": epoch, "train_loss": round(train_loss, 6)}
            if val_loss is not None:
                fields["val_loss"] = round(val_loss, 6)
            telemetry.emit("epoch", **fields)
    return state
