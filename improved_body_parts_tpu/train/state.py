"""Train state and optimizer construction.

Functional replacement for the reference's torch SGD + Apex AMP + checkpoint
dict (reference: train_distributed.py:123-139, 304-324).  Parameters stay
fp32; compute dtype is bf16 inside the model (no loss scaling needed on TPU).

SWA: a running average of parameters kept inside the state
(reference: train_distributed_SWA.py:403-435 via torchcontrib) — trivial under
functional params.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from flax import struct

from ..config import Config


@struct.dataclass
class TrainState:
    params: Any
    batch_stats: Any
    opt_state: Any
    step: jnp.ndarray
    # SWA running average (None until SWA starts)
    swa_params: Any = None
    swa_count: Any = None
    # the global step at which SWA began — the cyclic-LR sawtooth anchor
    # (reference: current_epoch - start_epoch, train_distributed_SWA.py:366);
    # persisted so an interrupted SWA run resumes mid-cycle in phase
    swa_start_step: Any = None


def make_optimizer(config: Config, schedule: Callable) -> optax.GradientTransformation:
    """SGD(momentum=0.9) + L2 weight decay 5e-4 + optional global-norm clip
    (reference: train_distributed.py:123-124; clip parsed but disabled at
    :36-38, 266 — same default here)."""
    tr = config.train
    parts = []
    if tr.max_grad_norm and tr.max_grad_norm > 0:
        parts.append(optax.clip_by_global_norm(tr.max_grad_norm))
    parts.append(optax.add_decayed_weights(tr.weight_decay))
    parts.append(optax.sgd(learning_rate=schedule, momentum=tr.momentum))
    return optax.chain(*parts)


def create_train_state(model, config: Config, optimizer, rng,
                       sample_images, shardings=None) -> TrainState:
    """Initialize the TrainState.  ``shardings`` (a NamedSharding
    pytree matching the state, e.g. from
    ``parallel.partition.train_state_shardings``) places every leaf as
    it is created — the partitioned-training entry path, where
    materializing a replicated flagship state first would briefly hold
    world_size full copies before the reshard."""
    variables = model.init(rng, sample_images, train=True)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    opt_state = optimizer.init(params)
    state = TrainState(params=params, batch_stats=batch_stats,
                       opt_state=opt_state, step=jnp.zeros((), jnp.int32),
                       swa_params=None, swa_count=None)
    if shardings is not None:
        from ..parallel.partition import shard_tree

        state = shard_tree(state, shardings)
    return state


def start_swa(state: TrainState) -> TrainState:
    """Begin stochastic weight averaging from the current params."""
    # jnp.copy, not asarray: the anchor must be its OWN buffer — aliasing
    # state.step would donate the same buffer twice in the jitted step
    return state.replace(swa_params=jax.tree.map(jnp.copy, state.params),
                         swa_count=jnp.ones((), jnp.int32),
                         swa_start_step=jnp.copy(state.step).astype(jnp.int32))


def update_swa(state: TrainState) -> TrainState:
    """Running average update (torchcontrib SWA ``update_swa`` semantics)."""
    assert state.swa_params is not None, "call start_swa first"
    n = state.swa_count.astype(jnp.float32)
    new_avg = jax.tree.map(
        lambda avg, p: (avg * n + p) / (n + 1.0), state.swa_params,
        state.params)
    return state.replace(swa_params=new_avg, swa_count=state.swa_count + 1)


def swap_swa_params(state: TrainState) -> TrainState:
    """Swap averaged params in for evaluation/checkpointing
    (``swap_swa_sgd`` semantics)."""
    assert state.swa_params is not None
    return state.replace(params=state.swa_params, swa_params=state.params)
