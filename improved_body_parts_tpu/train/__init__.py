from .checkpoint import (
    CheckpointManager,
    is_committed,
    latest_checkpoint,
    read_commit_meta,
    restore_checkpoint,
    restore_latest,
    save_checkpoint,
)
from .distill import bind_teacher, make_distill_train_step
from .loop import eval_epoch, fit, train_epoch
from .schedule import (
    cyclic_swa_schedule,
    large_batch_schedule,
    step_decay_schedule,
)
from .state import (
    TrainState,
    create_train_state,
    make_optimizer,
    start_swa,
    swap_swa_params,
    update_swa,
)
from .step import make_eval_step, make_train_step, normalize_images
from .supervisor import (
    PartitionRulesChanged,
    RunSupervisor,
    StopRequested,
    SupervisorGaveUp,
    TopologyChanged,
    milestone_eval,
    reshard_on_topology_change,
)

__all__ = [
    "CheckpointManager", "is_committed", "latest_checkpoint",
    "read_commit_meta", "restore_checkpoint", "restore_latest",
    "save_checkpoint",
    "bind_teacher", "make_distill_train_step",
    "eval_epoch", "fit", "train_epoch",
    "cyclic_swa_schedule", "large_batch_schedule", "step_decay_schedule",
    "TrainState", "create_train_state", "make_optimizer", "start_swa",
    "swap_swa_params", "update_swa",
    "make_eval_step", "make_train_step", "normalize_images",
    "PartitionRulesChanged", "RunSupervisor", "StopRequested",
    "SupervisorGaveUp", "TopologyChanged", "milestone_eval",
    "reshard_on_topology_change",
]
