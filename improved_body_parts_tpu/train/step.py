"""The jitted SPMD train step.

One program, all devices (reference's multi-process DDP hot loop,
train_distributed.py:242-298, collapses to this): forward + loss + backward in
a single XLA computation; with the batch sharded over the mesh's 'data' axis,
gradient all-reduces ride ICI automatically — no NCCL, no delay_allreduce, no
manual ``reduce_tensor``.  BatchNorm statistics reduce over the *global* batch
for free (the SyncBN equivalent).

Abnormal-loss batch dropping (train_distributed.py:259-261 "try to rescue the
gradient explosion") is a branchless on-device select: when loss exceeds the
threshold, parameters/optimizer/batch-stats keep their previous values — no
host round-trip in the hot loop.
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import optax

from ..config import Config
from ..ops import multi_task_loss
from .state import TrainState


# The train step donates the STATE argument (and only it): position 0 of
# (state, images, mask_miss, *gt).  One constant shared by
# ``make_train_step`` and the program auditor's registry
# (``analysis.program``), so the declaration the audit verifies against
# the compiled executable's input_output_aliases can never drift from
# what the step actually donates.  graftlint's JGL001 factory config
# (``donating-factories = ["make_train_step:0"]``) mirrors it.
TRAIN_STEP_DONATE_ARGNUMS = (0,)


def normalize_images(images: jnp.ndarray) -> jnp.ndarray:
    """uint8 wire → float32 in [0, 1] on device; f32 passes through.

    Exactly the host pipeline's normalization: both sides multiply by the
    SAME f32 reciprocal (``data.transformer.IMAGE_NORM_SCALE`` — see its
    note on why multiplication, not division), so the two wire formats
    produce bit-identical network inputs.
    """
    if images.dtype == jnp.uint8:
        from ..data.transformer import IMAGE_NORM_SCALE

        return images.astype(jnp.float32) * IMAGE_NORM_SCALE
    return images


def apply_guarded_update(state: TrainState, loss, grads, new_bs,
                         config: Config, optimizer, health: bool):
    """The shared tail of every train step (traced inside the jitted
    program): SGD update + the branchless abnormal-loss/divergence
    select + the optional health grad-norm output.

    One implementation for the supervised step (``make_train_step``) and
    the distillation step (``train.distill.make_distill_train_step``) so
    the skip_step policy and the rescue select can never drift between
    them.  Returns ``(state, loss)`` — or ``(state, loss, grad_norm)``
    when ``health`` — exactly the step's own return contract.
    """
    updates, new_opt = optimizer.update(grads, state.opt_state,
                                        state.params)
    new_params = optax.apply_updates(state.params, updates)

    ok = jnp.isfinite(loss) & (loss <= config.train.abnormal_loss_thre)
    # the skip_step gate keys off the CONFIG alone: the policy is a
    # training-semantics promise and must hold for every caller of
    # the step factories, not just the ones that asked for the health
    # return value — `health` controls only the extra output
    if health or config.train.on_divergence == "skip_step":
        gnorm = optax.global_norm(grads)
        if config.train.on_divergence == "skip_step":
            gok = jnp.isfinite(gnorm)
            if config.train.health_grad_norm_limit > 0:
                gok &= gnorm <= config.train.health_grad_norm_limit
            ok &= gok

    def keep(new, old):
        return jax.tree.map(lambda a, b: jnp.where(ok, a, b), new, old)

    state = state.replace(
        params=keep(new_params, state.params),
        batch_stats=keep(new_bs, state.batch_stats),
        opt_state=keep(new_opt, state.opt_state),
        step=state.step + 1)
    if health:
        return state, loss, gnorm
    return state, loss


def make_train_step(model, config: Config,
                    optimizer: optax.GradientTransformation,
                    use_focal: bool = True,
                    donate: bool = True,
                    freeze_bn: bool = False,
                    device_gt: bool = False,
                    health: bool = False,
                    mesh=None,
                    rules: Optional[Sequence] = None,
                    min_shard_dim: Optional[int] = None,
                    state_shardings=None) -> Callable:
    """Build the jitted (state, images, mask_miss, gt) -> (state, loss) step.

    ``health=True`` additionally returns the global gradient norm —
    (state, loss, grad_norm) — ONE extra scalar per step for the
    run-health sentinel (``obs.health``), left on device and read back
    only at the train loop's existing window readback, so divergence
    detection adds no syncs.  Under
    ``config.train.on_divergence == "skip_step"`` the abnormal-batch
    select below additionally requires a finite grad norm (and one
    within ``config.train.health_grad_norm_limit`` when set), so a
    divergent update never reaches the parameters — the branchless
    on-device extension of the reference's gradient-explosion rescue.

    ``freeze_bn=True`` runs BatchNorm on its running averages without
    updating them — the SWA fine-tuning mode (reference:
    train_distributed_SWA.py:219-221, utils/util.py:214-223).

    ``device_gt=True`` changes the step signature to
    (state, images, mask_miss, joints, mask_all): the GT label tensor is
    synthesized ON DEVICE inside the step (ops.make_gt_synthesizer) from
    padded joint coordinates, so only (max_people, parts, 3) + masks cross
    the host→device boundary instead of the (h, w, 50) maps — the
    input-bottleneck path for feeding a pod slice (SURVEY.md §7f).

    Images may arrive as uint8 HWC (the shared-memory pipeline's wire
    format, ``data.shm_ring`` — 4x fewer host→device bytes): the step
    normalizes to [0, 1] on device, bit-identical to the host pipeline's
    ``astype(float32) / 255``.  The dtype is static under jit, so the f32
    path compiles with no extra ops.

    ``mesh`` + ``rules`` select the fully GSPMD-PARTITIONED program:
    the TrainState's in/out shardings come from the partition ruleset
    (``parallel.partition.train_state_shardings`` — strict, so an
    uncovered leaf fails the build), the batch arguments pin to
    batch-over-'data', and the network inputs/predictions carry
    ``with_sharding_constraint`` annotations so XLA cannot resolve a
    layout conflict by silently all-gathering an activation.  Input and
    output state shardings are THE SAME tree, which is what lets the
    donated update keep its input_output_alias under sharding (verified
    compiled-level by graftaudit PRG003/PRG006 on the registered
    ``train_step_partitioned`` program).  ``mesh=None`` (the default)
    compiles the exact program this function always built.
    """
    if (mesh is None) != (rules is None):
        raise ValueError("make_train_step: mesh and rules select the "
                         "partitioned program together — pass both or "
                         "neither")
    if device_gt:
        from ..ops.gt_device import make_gt_synthesizer

        synthesize = make_gt_synthesizer(config.skeleton)

    from ..parallel.partition import constrain_batch_sharded

    def train_step(state: TrainState, images, mask_miss, *gt_args
                   ) -> Tuple[TrainState, jnp.ndarray]:
        images = normalize_images(images)
        if device_gt:
            joints, mask_all = gt_args
            gt = jax.vmap(synthesize)(joints, mask_all[..., 0])
        else:
            (gt,) = gt_args
        # pin the network inputs to batch-over-'data' (no-op when
        # mesh is None): the hourglass activations inherit the
        # constraint through the forward, so a rule/layout conflict
        # surfaces as a propagation error, never a silent all-gather
        images, mask_miss, gt = constrain_batch_sharded(
            (images, mask_miss, gt), mesh)

        def loss_fn(params):
            if freeze_bn:
                preds = model.apply(
                    {"params": params, "batch_stats": state.batch_stats},
                    images, train=False)
                # per-stack hourglass outputs stay batch-sharded into
                # the loss (each stack re-anchors the constraint chain)
                preds = constrain_batch_sharded(preds, mesh)
                return (multi_task_loss(
                    preds, gt, mask_miss, config, use_focal=use_focal,
                    use_pallas=config.train.use_pallas_loss),
                        state.batch_stats)
            outputs = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                images, train=True, mutable=["batch_stats"])
            preds, mutated = outputs
            preds = constrain_batch_sharded(preds, mesh)
            loss = multi_task_loss(preds, gt, mask_miss, config,
                                   use_focal=use_focal,
                                   use_pallas=config.train.use_pallas_loss)
            return loss, mutated["batch_stats"]

        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)

        return apply_guarded_update(state, loss, grads, new_bs, config,
                                    optimizer, health)

    donate_argnums = TRAIN_STEP_DONATE_ARGNUMS if donate else ()
    if mesh is None:
        return jax.jit(train_step, donate_argnums=donate_argnums)

    from ..parallel.mesh import batch_sharding, replicated
    from ..parallel.partition import (
        DEFAULT_MIN_SHARD_DIM,
        train_state_shardings,
    )

    # ONE sharding tree for the state on BOTH sides of the step: the
    # donated update can only alias when input and output layouts agree
    # (PRG006's divergent-alias check is the compiled-level proof).
    # Callers that already built the tree to PLACE the state pass it as
    # ``state_shardings`` — one layout source, so the placed leaves and
    # the jit's in_shardings can never disagree (a mismatch is a silent
    # re-place at the jit boundary that breaks the donation alias).
    state_sh = state_shardings
    if state_sh is None:
        state_sh = train_state_shardings(
            model, config, optimizer, mesh, rules,
            min_shard_dim=min_shard_dim or DEFAULT_MIN_SHARD_DIM)
    bsh = batch_sharding(mesh)
    scalar = replicated(mesh)
    n_batch_args = 4 if device_gt else 3  # images, mask_miss, gt-or-(joints, mask_all)
    in_shardings = (state_sh,) + (bsh,) * n_batch_args
    out_shardings = (state_sh, scalar) + ((scalar,) if health else ())
    return jax.jit(train_step, donate_argnums=donate_argnums,
                   in_shardings=in_shardings, out_shardings=out_shardings)


def make_eval_step(model, config: Config, use_focal: bool = True) -> Callable:
    """Jitted validation step: loss only, running BN averages
    (reference: train_distributed.py:327-379 ``test``)."""

    def eval_step(state: TrainState, images, mask_miss, gt) -> jnp.ndarray:
        images = normalize_images(images)
        preds = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            images, train=False)
        return multi_task_loss(preds, gt, mask_miss, config,
                               use_focal=use_focal,
                               use_pallas=config.train.use_pallas_loss)

    return jax.jit(eval_step)
