"""Learning-rate schedules.

Replicates the reference's hand-rolled per-step LR adjustment
(reference: train_distributed.py:382-400 ``adjust_learning_rate``) and the SWA
cyclic schedule (train_distributed_SWA.py:365-371) as optax-compatible
``step -> lr`` functions (pure, jittable).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..config import TrainConfig


def _decay_factor(cfg: TrainConfig, epoch):
    """The reference's staircase exponent: epoch // 15, switching to
    (epoch - 78) // 5 after epoch 78 (train_distributed.py:385-396)."""
    return jnp.where(
        epoch >= cfg.lr_late_epoch,
        (epoch - cfg.lr_late_epoch) // cfg.lr_late_step_epochs,
        epoch // cfg.lr_step_epochs)


def step_decay_schedule(cfg: TrainConfig, steps_per_epoch: int,
                        world_size: int = 1, use_warmup: bool = True):
    """LR = base·world_size·0.2^factor with a 3-epoch linear warmup.

    factor = epoch // 15, switching to (epoch - 78) // 5 after epoch 78
    (train_distributed.py:385-396).  ``step`` is the global step count.
    """
    base = cfg.learning_rate_per_device * world_size

    def schedule(step):
        step = jnp.asarray(step)
        epoch = step // steps_per_epoch
        factor = _decay_factor(cfg, epoch)
        lr = base * cfg.lr_decay_factor ** factor.astype(jnp.float32)
        if use_warmup:
            warm_steps = cfg.warmup_epochs * steps_per_epoch
            warm = lr * (1.0 + step).astype(jnp.float32) / warm_steps
            lr = jnp.where(epoch < cfg.warmup_epochs, warm, lr)
        return lr

    return schedule


def large_batch_schedule(cfg: TrainConfig, steps_per_epoch: int,
                         global_batch: int, use_warmup: bool = True):
    """The large-batch recipe ("Extremely Large Minibatch SGD",
    PAPERS.md; Goyal et al.'s linear-scaling + gradual-warmup rule) —
    what makes a pod-slice global batch *trainable*, not just runnable:

    - **linear scaling**: LR = base · (global_batch / lr_batch_ref).
      ``cfg.lr_batch_ref`` anchors the scale to the batch the base LR
      was tuned at (0 falls back to ``batch_size_per_device`` — the
      repo's historical per-device convention, under which the
      POST-WARMUP LR matches ``step_decay_schedule(world_size=
      n_devices)``; the warmup ramps deliberately differ — gradual
      base→scaled here vs 0→lr there.  Exact equality with the plain
      schedule holds only at scale ≤ 1, where this degenerates to the
      small-batch ramp);
    - **gradual warmup**: instead of ramping 0 → lr like the small-batch
      warmup, the LR climbs from the UNSCALED base to the scaled value
      over ``cfg.large_batch_warmup_epochs`` (0 = ``warmup_epochs``)
      epochs — the early-epoch instability of a large batch comes from
      the scale factor, not from the base rate;
    - the step-decay staircase then applies to the scaled LR with the
      reference's original breakpoints.

    Returns an optax-compatible pure ``step -> lr``.
    """
    ref = cfg.lr_batch_ref if cfg.lr_batch_ref > 0 \
        else cfg.batch_size_per_device
    scale = float(global_batch) / float(ref)
    scaled = cfg.learning_rate_per_device * scale
    warm_epochs = (cfg.large_batch_warmup_epochs
                   if cfg.large_batch_warmup_epochs > 0
                   else cfg.warmup_epochs)

    def schedule(step):
        step = jnp.asarray(step)
        epoch = step // steps_per_epoch
        factor = _decay_factor(cfg, epoch)
        lr = scaled * cfg.lr_decay_factor ** factor.astype(jnp.float32)
        if use_warmup and scale > 1.0:
            warm_steps = warm_epochs * steps_per_epoch
            frac = jnp.minimum(
                (1.0 + step).astype(jnp.float32) / warm_steps, 1.0)
            # base -> scaled ramp (Goyal et al. §2.2 gradual warmup)
            warm = (scaled / scale) * (1.0 + (scale - 1.0) * frac) \
                * cfg.lr_decay_factor ** factor.astype(jnp.float32)
            lr = jnp.where(epoch < warm_epochs, warm, lr)
        elif use_warmup:
            # at/below the reference batch the recipe degenerates to the
            # plain small-batch ramp
            warm_steps = cfg.warmup_epochs * steps_per_epoch
            warm = lr * (1.0 + step).astype(jnp.float32) / warm_steps
            lr = jnp.where(epoch < cfg.warmup_epochs, warm, lr)
        return lr

    return schedule


def cyclic_swa_schedule(steps_per_epoch: int, swa_freq: int = 5,
                        lr_max: float = 1e-5, lr_min: float = 1e-6,
                        start_step: int = 0):
    """Sawtooth LR for SWA fine-tuning: decays lr_max→lr_min over each
    ``swa_freq``-epoch cycle (train_distributed_SWA.py:365-369
    ``adjust_learning_rate_cyclic`` — defaults lr_max=1e-5, lr_min=1e-6).

    The cycle phase is anchored to ``start_step`` — the global step at
    which the SWA stage began, persisted as ``TrainState.swa_start_step``
    so even a mid-cycle interrupt/resume keeps the same sawtooth
    (the reference's ``epoch = current_epoch - start_epoch`` convention).
    """

    if swa_freq <= 1:  # degenerate cycle: constant lr_max
        return lambda step: jnp.asarray(lr_max, jnp.float32)

    def schedule(step):
        epoch = (jnp.asarray(step) - start_step) // steps_per_epoch
        phase = epoch - (epoch // swa_freq) * swa_freq
        return lr_max - (lr_max - lr_min) / (swa_freq - 1) * phase.astype(
            jnp.float32)

    return schedule
