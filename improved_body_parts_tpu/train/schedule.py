"""Learning-rate schedules.

Replicates the reference's hand-rolled per-step LR adjustment
(reference: train_distributed.py:382-400 ``adjust_learning_rate``) and the SWA
cyclic schedule (train_distributed_SWA.py:365-371) as optax-compatible
``step -> lr`` functions (pure, jittable).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..config import TrainConfig


def step_decay_schedule(cfg: TrainConfig, steps_per_epoch: int,
                        world_size: int = 1, use_warmup: bool = True):
    """LR = base·world_size·0.2^factor with a 3-epoch linear warmup.

    factor = epoch // 15, switching to (epoch - 78) // 5 after epoch 78
    (train_distributed.py:385-396).  ``step`` is the global step count.
    """
    base = cfg.learning_rate_per_device * world_size

    def schedule(step):
        step = jnp.asarray(step)
        epoch = step // steps_per_epoch
        factor = jnp.where(
            epoch >= cfg.lr_late_epoch,
            (epoch - cfg.lr_late_epoch) // cfg.lr_late_step_epochs,
            epoch // cfg.lr_step_epochs)
        lr = base * cfg.lr_decay_factor ** factor.astype(jnp.float32)
        if use_warmup:
            warm_steps = cfg.warmup_epochs * steps_per_epoch
            warm = lr * (1.0 + step).astype(jnp.float32) / warm_steps
            lr = jnp.where(epoch < cfg.warmup_epochs, warm, lr)
        return lr

    return schedule


def cyclic_swa_schedule(steps_per_epoch: int, swa_freq: int = 5,
                        lr_max: float = 1e-5, lr_min: float = 1e-6,
                        start_step: int = 0):
    """Sawtooth LR for SWA fine-tuning: decays lr_max→lr_min over each
    ``swa_freq``-epoch cycle (train_distributed_SWA.py:365-369
    ``adjust_learning_rate_cyclic`` — defaults lr_max=1e-5, lr_min=1e-6).

    The cycle phase is anchored to ``start_step`` — the global step at
    which the SWA stage began, persisted as ``TrainState.swa_start_step``
    so even a mid-cycle interrupt/resume keeps the same sawtooth
    (the reference's ``epoch = current_epoch - start_epoch`` convention).
    """

    if swa_freq <= 1:  # degenerate cycle: constant lr_max
        return lambda step: jnp.asarray(lr_max, jnp.float32)

    def schedule(step):
        epoch = (jnp.asarray(step) - start_step) // steps_per_epoch
        phase = epoch - (epoch // swa_freq) * swa_freq
        return lr_max - (lr_max - lr_min) / (swa_freq - 1) * phase.astype(
            jnp.float32)

    return schedule
